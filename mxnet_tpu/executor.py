"""Executor — whole-graph XLA compilation.

TPU-native replacement for GraphExecutor (src/executor/graph_executor.cc).
Where the reference builds a gradient graph (nnvm::pass::Gradient), plans
memory (PlanMemory) and pushes cached per-op engine blocks (RunOps,
graph_executor.cc:780-830), this executor lowers the *entire* symbol —
forward, and fused forward+backward — into single jitted XLA programs:

* bulk-exec segments (InitOpSegs, :686-735) == the whole graph, always;
* PlanMemory/DetectInplaceAddTo == XLA buffer assignment in HBM;
* the Gradient pass + per-op backward kernels == one ``jax.vjp`` over the
  traced graph (custom-vjp loss ops reproduce reference loss gradients);
* `forward(is_train=True)` is *deferred*: the computation runs when either
  `backward()` fires (one fused fwd+bwd XLA program) or an output is read
  (forward-only program). Output NDArrays carry a ``force`` thunk so eager
  reads stay correct — preserving the async-engine illusion with zero
  double-compute in the train loop.

grad_req semantics ('write'/'add'/'null') follow graph_executor.cc:87
AggregateGradient; aux states (BatchNorm moving stats) are written back
after each run, replacing FMutateInputs.
"""
from __future__ import annotations

from functools import partial

import numpy as onp

from .base import MXNetError
from . import random as _random
from .registry import OpContext

__all__ = ["Executor"]


def _run_op(n, get, put, rng, is_train, aux_sink=None):
    """Execute one op node: rng split, fcompute, output + aux write-back.
    Shared by the plain and segmented evaluators so their semantics
    (dropout streams, BN stat updates) can never diverge."""
    import jax
    ins = [get(id(s), oi) for (s, oi) in n.inputs]
    sub = None
    if n.op.needs_rng:
        rng, sub = jax.random.split(rng)
    octx = OpContext(is_train=is_train, rng=sub)
    res = n.op.fcompute(n.attrs, ins, octx)
    n_out = n.op.num_outputs(n.attrs)
    for oi in range(n_out):
        put(id(n), oi, res[oi])
    if n.op.aux_names and aux_sink is not None:
        n_args = len(n.op.list_arguments(n.attrs))
        for (src, _), newv in zip(n.inputs[n_args:], res[n_out:]):
            aux_sink(id(src), jax.lax.stop_gradient(newv))
    return rng, res, n_out


def _build_eval(symbol):
    """Compile the symbol's DAG into a pure function
    (arg_vals, aux_vals, rng, is_train) -> (outs, new_aux)."""
    order = symbol._topo()
    arg_nodes = [n for n in order if n.op is None and not n.is_aux]
    aux_nodes = [n for n in order if n.op is None and n.is_aux]
    op_nodes = [n for n in order if n.op is not None]
    heads = symbol._heads
    needs_rng = any(n.op.needs_rng for n in op_nodes)

    def eval_fn(arg_vals, aux_vals, rng, is_train, tap=None):
        env = {}
        for n, v in zip(arg_nodes, arg_vals):
            env[(id(n), 0)] = v
        for n, v in zip(aux_nodes, aux_vals):
            env[(id(n), 0)] = v
        aux_out = {id(n): v for n, v in zip(aux_nodes, aux_vals)}
        aux_ids = {id(n) for n in aux_nodes}

        def sink(aid, v):
            if aid in aux_ids:
                aux_out[aid] = v

        for n in op_nodes:
            rng, res, n_out = _run_op(
                n, lambda i, oi: env[(i, oi)],
                lambda i, oi, v: env.__setitem__((i, oi), v), rng,
                is_train, aux_sink=sink)
            if tap is not None:
                if n_out == 1:
                    tap("%s_output" % n.name, res[0])
                else:
                    for oi in range(n_out):
                        tap("%s_output%d" % (n.name, oi), res[oi])
        outs = tuple(env[(id(n), oi)] for (n, oi) in heads)
        new_aux = tuple(aux_out[id(n)] for n in aux_nodes)
        return outs, new_aux

    return eval_fn, needs_rng


def _build_eval_segmented(symbol, remat="full", n_segments=None):
    """Like :func:`_build_eval`, but the op sequence is split into
    ~sqrt(N) contiguous segments, each wrapped in ``jax.checkpoint``.

    A SINGLE checkpoint around the whole forward saves nothing (the
    backward's recompute re-materializes every activation at the same
    peak); the sqrt-N segment schedule keeps only segment-boundary
    values live plus one segment's internals — the classic
    O(sqrt(N))-memory rematerialization the reference's memonger tool
    approximates by graph re-planning (example/memcost).

    remat="dots" keeps matmul/conv outputs inside segments
    (``jax.checkpoint_policies.dots_saveable``); "full" recomputes
    everything inside a segment. Training-mode only, no tap support
    (the monitor path uses the per-node evaluator).
    """
    import math

    order = symbol._topo()
    arg_nodes = [n for n in order if n.op is None and not n.is_aux]
    aux_nodes = [n for n in order if n.op is None and n.is_aux]
    op_nodes = [n for n in order if n.op is not None]
    heads = symbol._heads
    needs_rng = any(n.op.needs_rng for n in op_nodes)
    aux_ids = {id(n) for n in aux_nodes}

    n_ops = len(op_nodes)
    if n_ops == 0:
        # variable-only symbol: nothing to checkpoint (range() below would
        # get a zero step) — the plain evaluator is already optimal
        return _build_eval(symbol)
    if n_segments is None:
        n_segments = max(1, int(math.ceil(math.sqrt(n_ops))))
    seg_size = int(math.ceil(n_ops / float(n_segments)))
    segments = [op_nodes[i:i + seg_size]
                for i in range(0, n_ops, seg_size)]

    # liveness, computed ONCE at build time: per segment, the slots it
    # consumes from before it and the products needed later (or heads)
    head_slots = {(id(n), oi) for (n, oi) in heads}
    produced_in = {}
    consumed_in = {}  # slot -> set of segment indices that read it
    for si, seg in enumerate(segments):
        for n in seg:
            for oi in range(n.op.num_outputs(n.attrs)):
                produced_in[(id(n), oi)] = si
            for (src, oi) in n.inputs:
                consumed_in.setdefault((id(src), oi), set()).add(si)

    seg_plan = []  # (seg, in_slots, out_slots, aux_updates)
    for si, seg in enumerate(segments):
        in_slots, seen = [], set()
        for n in seg:
            for (src, oi) in n.inputs:
                slot = (id(src), oi)
                if produced_in.get(slot, -1) != si and slot not in seen:
                    seen.add(slot)
                    in_slots.append(slot)
        out_slots, aux_updates = [], []
        for n in seg:
            for oi in range(n.op.num_outputs(n.attrs)):
                slot = (id(n), oi)
                later = consumed_in.get(slot, set())
                if any(sj > si for sj in later) or slot in head_slots:
                    out_slots.append(slot)
            if n.op.aux_names:
                n_args = len(n.op.list_arguments(n.attrs))
                for (src, _) in n.inputs[n_args:]:
                    if id(src) in aux_ids:
                        aux_updates.append(id(src))
        seg_plan.append((seg, tuple(in_slots), tuple(out_slots),
                         tuple(aux_updates)))

    def eval_fn(arg_vals, aux_vals, rng, is_train, tap=None):
        import jax

        assert tap is None, "segmented remat has no monitor taps"
        policy = (jax.checkpoint_policies.dots_saveable
                  if remat == "dots" else None)
        env = {}
        for n, v in zip(arg_nodes, arg_vals):
            env[(id(n), 0)] = v
        for n, v in zip(aux_nodes, aux_vals):
            env[(id(n), 0)] = v
        aux_out = {id(n): v for n, v in zip(aux_nodes, aux_vals)}

        for seg, in_slots, out_slots, aux_updates in seg_plan:

            def seg_fn(in_vals, rng_in, _seg=seg, _in=in_slots,
                       _out=out_slots):
                local = dict(zip(_in, in_vals))
                upd = []

                def sink(aid, v):
                    if aid in aux_ids:
                        upd.append(v)

                r = rng_in
                for n in _seg:
                    r, _, _ = _run_op(
                        n, lambda i, oi: local[(i, oi)],
                        lambda i, oi, v: local.__setitem__((i, oi), v),
                        r, is_train, aux_sink=sink)
                return (tuple(local[s] for s in _out), tuple(upd), r)

            in_vals = tuple(env[s] for s in in_slots)
            outs, upd, rng = jax.checkpoint(seg_fn, policy=policy)(
                in_vals, rng)
            for slot, v in zip(out_slots, outs):
                env[slot] = v
            for aid, v in zip(aux_updates, upd):
                aux_out[aid] = v

        out_vals = tuple(env[(id(n), oi)] for (n, oi) in heads)
        new_aux = tuple(aux_out[id(n)] for n in aux_nodes)
        return out_vals, new_aux

    return eval_fn, needs_rng


class Executor:
    """Runnable binding of a Symbol to argument/gradient/aux NDArrays."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None):
        import jax

        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self.arg_names = arg_names
        self.aux_names = aux_names

        self.arg_arrays = self._normalize(args, arg_names, "args")
        self.aux_arrays = self._normalize(aux_states or [], aux_names,
                                          "aux_states")
        self.arg_dict = dict(zip(arg_names, self.arg_arrays))
        self.aux_dict = dict(zip(aux_names, self.aux_arrays))

        # gradient buffers + per-arg request
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in arg_names}
        if args_grad is None:
            self.grad_arrays = [None] * len(arg_names)
            for n in arg_names:
                self._grad_req[n] = "null"
        elif isinstance(args_grad, dict):
            self.grad_arrays = [args_grad.get(n) for n in arg_names]
            for n in arg_names:
                if args_grad.get(n) is None:
                    self._grad_req[n] = "null"
        else:
            self.grad_arrays = list(args_grad)
            while len(self.grad_arrays) < len(arg_names):
                self.grad_arrays.append(None)
        self.grad_dict = dict(zip(arg_names, self.grad_arrays))
        self._diff_names = [n for n in arg_names
                            if self._grad_req.get(n, "null") != "null"
                            and self.grad_dict.get(n) is not None]

        self._eval_fn, self._needs_rng = _build_eval(symbol)

        # jitted programs (compiled lazily on first use, cached thereafter —
        # the "compile once via simple_bind, reuse every batch" contract)
        self._jit_fwd = {
            True: jax.jit(partial(self._eval_fn, is_train=True)),
            False: jax.jit(partial(self._eval_fn, is_train=False)),
        }
        self._jit_grad = jax.jit(self._grad_step)

        # allocate persistent output buffers from abstract evaluation
        arg_structs = [jax.ShapeDtypeStruct(a.shape, onp.dtype(a.dtype))
                       for a in self.arg_arrays]
        aux_structs = [jax.ShapeDtypeStruct(a.shape, onp.dtype(a.dtype))
                       for a in self.aux_arrays]
        rng_struct = jax.ShapeDtypeStruct((2,), onp.uint32)
        out_structs, _ = jax.eval_shape(partial(self._eval_fn, is_train=False),
                                        arg_structs, aux_structs, rng_struct)
        from . import ndarray as nd
        self._out_arrays = [nd.zeros(s.shape, ctx=ctx, dtype=s.dtype)
                            for s in out_structs]
        self.outputs = self._out_arrays
        self.output_dict = dict(zip(symbol.list_outputs(), self._out_arrays))

        self._pending = None     # (is_train, arg_vals, aux_vals, rng)
        self._last_run = None    # captured values of the last forward
        self._monitor_callback = None

    # ------------------------------------------------------------------
    def _normalize(self, arrays, names, what):
        from .ndarray import NDArray
        if isinstance(arrays, dict):
            missing = [n for n in names if n not in arrays]
            if missing:
                raise MXNetError("missing %s: %s" % (what, missing))
            return [arrays[n] for n in names]
        arrays = list(arrays)
        if len(arrays) != len(names):
            raise MXNetError("%s length %d != expected %d"
                             % (what, len(arrays), len(names)))
        return arrays

    # ------------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """Schedule a forward pass; returns the output NDArrays (lazy).

        Mirrors Executor::Forward / MXExecutorForward: copies any kwargs into
        the bound input arrays first (the reference requires explicit copy;
        we keep the convenience from executor.py:86)."""
        if kwargs:
            for k, v in kwargs.items():
                if k not in self.arg_dict:
                    raise MXNetError("unknown input %s" % k)
                from .ndarray import NDArray
                if isinstance(v, NDArray):
                    v.copyto(self.arg_dict[k])
                else:
                    self.arg_dict[k][:] = v

        arg_vals = [a._read() for a in self.arg_arrays]
        aux_vals = [a._read() for a in self.aux_arrays]
        rng = _random.next_key() if self._needs_rng else \
            onp.zeros((2,), onp.uint32)
        self._pending = (bool(is_train), arg_vals, aux_vals, rng)
        self._last_run = self._pending
        if self._monitor_active():
            # execute-with-taps: run the per-node interpreter eagerly and
            # feed every op output to the monitor callback — the reference
            # copies each output to ExecuteMonCallback
            # (graph_executor.cc:760-778)
            self._pending = None
            cb = self._monitor_callback
            from . import ndarray as nd

            def tap(name, val):
                cb(name, nd.NDArray(val, ctx=self._ctx, writable=False))

            outs, new_aux = self._eval_fn(arg_vals, aux_vals, rng,
                                          bool(is_train), tap=tap)
            self._write_results(outs, new_aux, bool(is_train))
            return self.outputs
        force = self._materialize_forward
        for o in self._out_arrays:
            o._chunk.force = force
        return self.outputs

    def _monitor_active(self):
        cb = self._monitor_callback
        if cb is None:
            return False
        owner = getattr(cb, "__self__", None)
        # Monitor gates taps by interval via its ``activated`` flag; plain
        # callables tap every batch
        return getattr(owner, "activated", True) is not False

    def _materialize_forward(self):
        if self._pending is None:
            return
        is_train, arg_vals, aux_vals, rng = self._pending
        self._pending = None
        outs, new_aux = self._jit_fwd[is_train](arg_vals, aux_vals, rng)
        self._write_results(outs, new_aux, is_train)

    def _write_results(self, outs, new_aux, is_train):
        for o, v in zip(self._out_arrays, outs):
            o._chunk.force = None
            o._chunk.arr = v
        if is_train:
            for a, v in zip(self.aux_arrays, new_aux):
                a._write(v)

    # ------------------------------------------------------------------
    def _grad_step(self, arg_vals, aux_vals, rng, head_grads):
        import jax
        names = self.arg_names
        diff_idx = [i for i, n in enumerate(names) if n in self._diff_names]
        diff_vals = tuple(arg_vals[i] for i in diff_idx)

        def f(diff):
            merged = list(arg_vals)
            for i, v in zip(diff_idx, diff):
                merged[i] = v
            outs, new_aux = self._eval_fn(merged, aux_vals, rng, True)
            return outs, new_aux

        outs, vjp_fn, new_aux = jax.vjp(f, diff_vals, has_aux=True)
        (grads,) = vjp_fn(tuple(head_grads))
        return outs, new_aux, grads

    def backward(self, out_grads=None):
        """Fused forward+backward XLA program; writes gradients honoring
        grad_req write/add (Executor::Backward, graph_executor.cc:45)."""
        import jax.numpy as jnp
        if self._last_run is None:
            raise MXNetError("backward() called before forward()")
        is_train, arg_vals, aux_vals, rng = self._last_run
        self._pending = None
        if out_grads is None:
            heads = [jnp.ones(o.shape, o.dtype) for o in self._out_arrays]
        else:
            from .ndarray import NDArray
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = [g._read() if isinstance(g, NDArray) else jnp.asarray(g)
                     for g in out_grads]
        outs, new_aux, grads = self._jit_grad(arg_vals, aux_vals, rng, heads)
        self._write_results(outs, new_aux, is_train=True)
        for name, g in zip(self._diff_names, grads):
            buf = self.grad_dict[name]
            if self._grad_req[name] == "add":
                buf._write(buf._read() + g)
            else:
                buf._write(g)

    # ------------------------------------------------------------------
    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor bound to resized arrays (executor.py:287).

        Matches the reference's flag semantics: an arg whose shape changes
        without being named in kwargs requires ``partial_shaping``; growing
        an array beyond its current element count requires
        ``allow_up_sizing`` (same-or-smaller reshapes share memory)."""
        from . import ndarray as nd
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        if arg_shapes is None:
            raise MXNetError("Insufficient argument shapes provided.")

        def _resize(name, new_shape, arr, specified):
            new_shape = tuple(new_shape)
            if tuple(arr.shape) == new_shape:
                return arr
            if not (partial_shaping or specified):
                raise MXNetError(
                    "Shape of unspecified array %s changed. This can cause "
                    "the new executor to not share parameters with the old "
                    "one. Set partial_shaping=True if intended." % name)
            if int(onp.prod(new_shape)) > arr.size:
                if not allow_up_sizing:
                    raise MXNetError(
                        "New shape of %s larger than original; set "
                        "allow_up_sizing=True to allocate a new array."
                        % name)
                return nd.empty(new_shape, ctx=arr.context, dtype=arr.dtype)
            if int(onp.prod(new_shape)) == arr.size:
                return arr.reshape(new_shape)
            # shrink: the reference keeps a prefix view of the old buffer
            # (executor.py:287 arr.reshape); values are preserved here via a
            # prefix copy (jax arrays are immutable, so no aliased view)
            prefix = arr._read().ravel()[:int(onp.prod(new_shape))]
            return nd.NDArray(prefix.reshape(new_shape), ctx=arr.context)

        new_args, grads = {}, None
        if any(g is not None for g in self.grad_arrays):
            grads = {}
        for name, new_shape, arr in zip(self.arg_names, arg_shapes,
                                        self.arg_arrays):
            new_args[name] = _resize(name, new_shape, arr, name in kwargs)
            g = self.grad_dict.get(name)
            if g is not None:
                grads[name] = _resize("grad of " + name, new_shape, g,
                                      name in kwargs)
        new_aux = {}
        for name, new_shape, arr in zip(self.aux_names, aux_shapes,
                                        self.aux_arrays):
            new_aux[name] = _resize(name, new_shape, arr, True)
        return Executor(self._symbol, self._ctx, new_args, grads,
                        self._grad_req, new_aux)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                arr.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise ValueError("Found name \"%s\" not in arguments" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    arr.copyto(self.aux_dict[name])
                elif not allow_extra_params:
                    raise ValueError("Found name \"%s\" not in aux" % name)

    def set_monitor_callback(self, callback):
        self._monitor_callback = callback

    def debug_str(self):
        lines = ["Symbol outputs: %s" % ", ".join(self._symbol.list_outputs())]
        for n in self._symbol._topo():
            if n.op is not None:
                lines.append("Op:%s, Name=%s" % (n.op.name, n.name))
        lines.append("Memory planning: delegated to XLA buffer assignment")
        return "\n".join(lines)
