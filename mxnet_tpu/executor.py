"""Executor — whole-graph XLA compilation.

TPU-native replacement for GraphExecutor (src/executor/graph_executor.cc).
Where the reference builds a gradient graph (nnvm::pass::Gradient), plans
memory (PlanMemory) and pushes cached per-op engine blocks (RunOps,
graph_executor.cc:780-830), this executor lowers the *entire* symbol —
forward, and fused forward+backward — into single jitted XLA programs:

* bulk-exec segments (InitOpSegs, :686-735) == the whole graph, always;
* PlanMemory/DetectInplaceAddTo == XLA buffer assignment in HBM;
* the Gradient pass + per-op backward kernels == one ``jax.vjp`` over the
  traced graph (custom-vjp loss ops reproduce reference loss gradients);
* `forward(is_train=True)` is *deferred*: the computation runs when either
  `backward()` fires (one fused fwd+bwd XLA program) or an output is read
  (forward-only program). Output NDArrays carry a ``force`` thunk so eager
  reads stay correct — preserving the async-engine illusion with zero
  double-compute in the train loop.

grad_req semantics ('write'/'add'/'null') follow graph_executor.cc:87
AggregateGradient; aux states (BatchNorm moving stats) are written back
after each run, replacing FMutateInputs.
"""
from __future__ import annotations

from functools import partial

import numpy as onp

from .base import MXNetError
from . import random as _random
from .registry import OpContext

__all__ = ["Executor"]


def _run_op(n, get, put, rng, is_train, aux_sink=None):
    """Execute one op node: rng split, fcompute, output + aux write-back.
    Shared by the plain and segmented evaluators so their semantics
    (dropout streams, BN stat updates) can never diverge."""
    import jax
    ins = [get(id(s), oi) for (s, oi) in n.inputs]
    sub = None
    if n.op.needs_rng:
        rng, sub = jax.random.split(rng)
    octx = OpContext(is_train=is_train, rng=sub)
    res = n.op.fcompute(n.attrs, ins, octx)
    n_out = n.op.num_outputs(n.attrs)
    for oi in range(n_out):
        put(id(n), oi, res[oi])
    if n.op.aux_names and aux_sink is not None:
        n_args = len(n.op.list_arguments(n.attrs))
        for (src, _), newv in zip(n.inputs[n_args:], res[n_out:]):
            aux_sink(id(src), jax.lax.stop_gradient(newv))
    return rng, res, n_out


def fuse_bn_relu(symbol):
    """Graph pass: collapse BatchNorm→Activation(relu) pairs into one
    BatchNorm node carrying ``_fused_relu=True``.

    TPU-first rationale: the pair is the hottest pattern in conv nets,
    and fusing it routes training through the hand-VJP BatchNorm core
    (ops/nn.py _bn_train_core_make) with the ReLU mask recomputed
    in-register during the backward — the post-activation tensor is
    never re-read (or saved) by the backward at all.  On an HBM-bound
    ResNet step this removes whole activation sweeps.

    Fusion applies only when the Activation is the *sole* consumer of
    the BatchNorm output (otherwise the pre-ReLU value is needed) and
    the BatchNorm does not expose mean/var (`output_mean_var`).  The
    rewrite builds new nodes; the input symbol is never mutated.  The
    fused node takes the Activation's name, so head/loss wiring and
    debug output names stay stable; the BatchNorm's parameter and aux
    Variables (gamma/beta/moving stats) are reused unchanged, so
    arg/aux lists and checkpoints are unaffected.
    """
    from .symbol import Symbol, _Node

    order = symbol._topo()
    n_cons = {}
    for nd in order:
        for (s, oi) in nd.inputs:
            key = (id(s), oi)
            n_cons[key] = n_cons.get(key, 0) + 1
    for (h, oi) in symbol._heads:
        key = (id(h), oi)
        n_cons[key] = n_cons.get(key, 0) + 1

    new_of = {}   # id(old node) -> new node
    fused_away = set()   # id(BatchNorm nodes absorbed into a fused node)

    def resolve(nd):
        return new_of.get(id(nd), nd)

    changed = False
    for nd in order:
        if nd.op is None:
            continue
        if (nd.op.name == "Activation"
                and nd.attrs.get("act_type", "relu") == "relu"
                and len(nd.inputs) == 1 and nd.inputs[0][1] == 0):
            src = nd.inputs[0][0]
            if (src.op is not None and src.op.name == "BatchNorm"
                    and id(src) not in fused_away
                    and n_cons.get((id(src), 0), 0) == 1
                    and not src.attrs.get("output_mean_var", False)
                    # never move a node across a placement boundary: the
                    # fused node carries the Activation's ctx_group, so
                    # the pair must agree (pipeline stages are split on
                    # per-node ctx_group — _split_pipeline_stages)
                    and src._attr_dict.get("ctx_group")
                    == nd._attr_dict.get("ctx_group")):
                b = resolve(src)
                fused = _Node(
                    b.op, nd.name,
                    attrs=dict(b.attrs, _fused_relu=True),
                    inputs=[(resolve(s), oi) for (s, oi) in b.inputs],
                    attr_dict=dict(nd._attr_dict),
                    auto_named=nd.auto_named)
                new_of[id(nd)] = fused
                fused_away.add(id(src))
                changed = True
                continue
        new_inputs = [(resolve(s), oi) for (s, oi) in nd.inputs]
        if any(a is not b for (a, _), (b, _) in zip(new_inputs, nd.inputs)):
            new_of[id(nd)] = _Node(
                nd.op, nd.name, attrs=nd.attrs, inputs=new_inputs,
                is_aux=nd.is_aux, attr_dict=nd._attr_dict,
                auto_named=nd.auto_named)
    if not changed:
        return symbol
    return Symbol([(resolve(h), oi) for (h, oi) in symbol._heads])


def _build_eval(symbol):
    """Compile the symbol's DAG into a pure function
    (arg_vals, aux_vals, rng, is_train) -> (outs, new_aux)."""
    order = symbol._topo()
    arg_nodes = [n for n in order if n.op is None and not n.is_aux]
    aux_nodes = [n for n in order if n.op is None and n.is_aux]
    op_nodes = [n for n in order if n.op is not None]
    heads = symbol._heads
    needs_rng = any(n.op.needs_rng for n in op_nodes)

    def eval_fn(arg_vals, aux_vals, rng, is_train, tap=None):
        env = {}
        for n, v in zip(arg_nodes, arg_vals):
            env[(id(n), 0)] = v
        for n, v in zip(aux_nodes, aux_vals):
            env[(id(n), 0)] = v
        aux_out = {id(n): v for n, v in zip(aux_nodes, aux_vals)}
        aux_ids = {id(n) for n in aux_nodes}

        def sink(aid, v):
            if aid in aux_ids:
                aux_out[aid] = v

        for n in op_nodes:
            rng, res, n_out = _run_op(
                n, lambda i, oi: env[(i, oi)],
                lambda i, oi, v: env.__setitem__((i, oi), v), rng,
                is_train, aux_sink=sink)
            if tap is not None:
                if n_out == 1:
                    tap("%s_output" % n.name, res[0])
                else:
                    for oi in range(n_out):
                        tap("%s_output%d" % (n.name, oi), res[oi])
        outs = tuple(env[(id(n), oi)] for (n, oi) in heads)
        new_aux = tuple(aux_out[id(n)] for n in aux_nodes)
        return outs, new_aux

    return eval_fn, needs_rng


def _build_eval_segmented(symbol, remat="full", n_segments=None):
    """Like :func:`_build_eval`, but the op sequence is split into
    ~sqrt(N) contiguous segments, each wrapped in ``jax.checkpoint``.

    A SINGLE checkpoint around the whole forward saves nothing (the
    backward's recompute re-materializes every activation at the same
    peak); the sqrt-N segment schedule keeps only segment-boundary
    values live plus one segment's internals — the classic
    O(sqrt(N))-memory rematerialization the reference's memonger tool
    approximates by graph re-planning (example/memcost).

    remat="dots" keeps matmul/conv outputs inside segments
    (``jax.checkpoint_policies.dots_saveable``); "full" recomputes
    everything inside a segment; "bn_stats" additionally keeps the
    ``checkpoint_name("bn_stats")``-tagged per-channel BatchNorm
    statistics (ops/nn.py tags them) so the backward's segment replays
    never redo the stat sweeps; a callable passes straight through as
    the jax checkpoint policy (mxnet_tpu.precision's custom escape).
    Training-mode only, no tap support (the monitor path uses the
    per-node evaluator).
    """
    import math

    order = symbol._topo()
    arg_nodes = [n for n in order if n.op is None and not n.is_aux]
    aux_nodes = [n for n in order if n.op is None and n.is_aux]
    op_nodes = [n for n in order if n.op is not None]
    heads = symbol._heads
    needs_rng = any(n.op.needs_rng for n in op_nodes)
    aux_ids = {id(n) for n in aux_nodes}

    n_ops = len(op_nodes)
    if n_ops == 0:
        # variable-only symbol: nothing to checkpoint (range() below would
        # get a zero step) — the plain evaluator is already optimal
        return _build_eval(symbol)
    if n_segments is None:
        n_segments = max(1, int(math.ceil(math.sqrt(n_ops))))
    seg_size = int(math.ceil(n_ops / float(n_segments)))
    segments = [op_nodes[i:i + seg_size]
                for i in range(0, n_ops, seg_size)]

    # liveness, computed ONCE at build time: per segment, the slots it
    # consumes from before it and the products needed later (or heads)
    head_slots = {(id(n), oi) for (n, oi) in heads}
    produced_in = {}
    consumed_in = {}  # slot -> set of segment indices that read it
    for si, seg in enumerate(segments):
        for n in seg:
            for oi in range(n.op.num_outputs(n.attrs)):
                produced_in[(id(n), oi)] = si
            for (src, oi) in n.inputs:
                consumed_in.setdefault((id(src), oi), set()).add(si)

    seg_plan = []  # (seg, in_slots, out_slots, aux_updates)
    for si, seg in enumerate(segments):
        in_slots, seen = [], set()
        for n in seg:
            for (src, oi) in n.inputs:
                slot = (id(src), oi)
                if produced_in.get(slot, -1) != si and slot not in seen:
                    seen.add(slot)
                    in_slots.append(slot)
        out_slots, aux_updates = [], []
        for n in seg:
            for oi in range(n.op.num_outputs(n.attrs)):
                slot = (id(n), oi)
                later = consumed_in.get(slot, set())
                if any(sj > si for sj in later) or slot in head_slots:
                    out_slots.append(slot)
            if n.op.aux_names:
                n_args = len(n.op.list_arguments(n.attrs))
                for (src, _) in n.inputs[n_args:]:
                    if id(src) in aux_ids:
                        aux_updates.append(id(src))
        seg_plan.append((seg, tuple(in_slots), tuple(out_slots),
                         tuple(aux_updates)))

    # policy object resolved ONCE at build time (mxnet_tpu.precision
    # owns the name -> jax.checkpoint_policies mapping)
    from .precision.policy import remat_checkpoint_policy
    _ckpt_policy = remat_checkpoint_policy(remat)

    def eval_fn(arg_vals, aux_vals, rng, is_train, tap=None):
        import jax

        assert tap is None, "segmented remat has no monitor taps"
        policy = _ckpt_policy
        env = {}
        for n, v in zip(arg_nodes, arg_vals):
            env[(id(n), 0)] = v
        for n, v in zip(aux_nodes, aux_vals):
            env[(id(n), 0)] = v
        aux_out = {id(n): v for n, v in zip(aux_nodes, aux_vals)}

        for seg, in_slots, out_slots, aux_updates in seg_plan:

            def seg_fn(in_vals, rng_in, _seg=seg, _in=in_slots,
                       _out=out_slots):
                local = dict(zip(_in, in_vals))
                upd = []

                def sink(aid, v):
                    if aid in aux_ids:
                        upd.append(v)

                r = rng_in
                for n in _seg:
                    r, _, _ = _run_op(
                        n, lambda i, oi: local[(i, oi)],
                        lambda i, oi, v: local.__setitem__((i, oi), v),
                        r, is_train, aux_sink=sink)
                return (tuple(local[s] for s in _out), tuple(upd), r)

            in_vals = tuple(env[s] for s in in_slots)
            outs, upd, rng = jax.checkpoint(seg_fn, policy=policy)(
                in_vals, rng)
            for slot, v in zip(out_slots, outs):
                env[slot] = v
            for aid, v in zip(aux_updates, upd):
                aux_out[aid] = v

        out_vals = tuple(env[(id(n), oi)] for (n, oi) in heads)
        new_aux = tuple(aux_out[id(n)] for n in aux_nodes)
        return out_vals, new_aux

    return eval_fn, needs_rng


def _split_pipeline_stages(symbol, n_stages):
    """Classify the symbol's op nodes into preamble / ``n_stages``
    pipeline stages / postamble from ``ctx_group="stage<i>"`` attrs
    (the reference's user-facing placement surface, AttrScope ->
    PlaceDevice, graph_executor.cc:318 — here mapped to GPipe stages).

    Contract (checked, with precise errors):
      * tagged ops form stages 0..n_stages-1; dataflow between tags is
        non-decreasing;
      * untagged ops reachable INTO stages are preamble, ops depending
        on the last stage are postamble; an untagged op between interior
        stages is an error;
      * exactly ONE tensor crosses each stage boundary, same shape at
        every boundary;
      * stages are structurally identical (same op types/attrs in the
        same order) so one stage body can run under ``lax.switch``-free
        weight-stationary scheduling with stacked per-stage params;
      * no aux states (BatchNorm) inside stages.
    Returns (pre_nodes, stage_nodes: list[list], post_nodes,
    carry_slots, side_slots, stage_param_slots).
    """
    import re

    order = symbol._topo()
    op_nodes = [n for n in order if n.op is not None]
    tag_of = {}
    for n in op_nodes:
        g = n._attr_dict.get("ctx_group")
        if g is not None:
            m = re.match(r"stage(\d+)$", g)
            if m:
                tag_of[id(n)] = int(m.group(1))
    if not tag_of:
        raise MXNetError("pipeline: no ctx_group='stage<i>' attrs found")
    found = sorted(set(tag_of.values()))
    if found != list(range(n_stages)):
        raise MXNetError(
            "pipeline: mesh pp axis is %d but symbol tags stages %s"
            % (n_stages, found))

    # transitive "depends on a tagged op of stage s" classification
    max_dep = {}  # node id -> highest stage it depends on (-1 none)
    for n in order:
        d = tag_of.get(id(n), -1)
        for (src, _) in (n.inputs or []):
            d = max(d, max_dep.get(id(src), -1))
        max_dep[id(n)] = d

    pre, post = [], []
    stage_nodes = [[] for _ in range(n_stages)]
    for n in op_nodes:
        s = tag_of.get(id(n))
        if s is not None:
            dep = max(max_dep.get(id(src), -1) for (src, _) in n.inputs)
            if dep > s:
                raise MXNetError(
                    "pipeline: op %s tagged stage%d consumes stage%d "
                    "output — dataflow must be stage-monotone"
                    % (n.name, s, dep))
            stage_nodes[s].append(n)
        elif max_dep[id(n)] == -1:
            pre.append(n)
        elif max_dep[id(n)] == n_stages - 1:
            post.append(n)
        else:
            raise MXNetError(
                "pipeline: untagged op %s depends on interior stage%d — "
                "tag it or move it out of the pipelined region"
                % (n.name, max_dep[id(n)]))

    produced_by = {}
    for s, seg in enumerate(stage_nodes):
        for n in seg:
            for oi in range(n.op.num_outputs(n.attrs)):
                produced_by[(id(n), oi)] = s

    # carry slot per boundary: the single stage-(i-1) product stage i reads
    carry_slots = []
    for s in range(n_stages):
        if s == 0:
            continue
        crossing = {slot for n in stage_nodes[s] for slot in
                    ((id(src), oi) for (src, oi) in n.inputs)
                    if produced_by.get(slot) == s - 1}
        if len(crossing) != 1:
            id2name = {id(n2): n2.name for seg2 in stage_nodes
                       for n2 in seg2}
            raise MXNetError(
                "pipeline: %d tensors cross the stage%d->stage%d "
                "boundary; exactly one must (crossing outputs of ops %s)"
                % (len(crossing), s - 1, s,
                   sorted(id2name.get(i, "?") for (i, _) in crossing)))
        carry_slots.append(next(iter(crossing)))
    # final carry: the single last-stage product the postamble reads
    last_out = {slot for n in post for slot in
                ((id(src), oi) for (src, oi) in n.inputs)
                if produced_by.get(slot) == n_stages - 1}
    for (hn, hoi) in symbol._heads:
        if produced_by.get((id(hn), hoi)) is not None:
            if produced_by[(id(hn), hoi)] != n_stages - 1:
                raise MXNetError("pipeline: output taken from an "
                                 "interior stage")
            last_out.add((id(hn), hoi))
    if len(last_out) != 1:
        raise MXNetError(
            "pipeline: the last stage must hand exactly one tensor to "
            "the postamble (got %d)" % len(last_out))
    carry_slots.append(next(iter(last_out)))
    # postamble must not peek inside interior stages
    for n in post:
        for (src, oi) in n.inputs:
            p = produced_by.get((id(src), oi))
            if p is not None and p != n_stages - 1:
                raise MXNetError(
                    "pipeline: postamble op %s reads stage%d internals"
                    % (n.name, p))

    # structural identity + positional input classification
    ref_seg = stage_nodes[0]
    for s, seg in enumerate(stage_nodes[1:], 1):
        if len(seg) != len(ref_seg):
            raise MXNetError(
                "pipeline: stage%d has %d ops, stage0 has %d — stages "
                "must be structurally identical" % (s, len(seg),
                                                    len(ref_seg)))
        for a, b in zip(ref_seg, seg):
            if a.op.name != b.op.name or a.attrs != b.attrs:
                raise MXNetError(
                    "pipeline: stage%d op %s (%s) does not match stage0 "
                    "op %s (%s)" % (s, b.name, b.op.name, a.name,
                                    a.op.name))

    if n_stages < 2:
        raise MXNetError("pipeline: needs a pp axis of size >= 2")

    # which stages consume each Variable (param-vs-shared classification)
    var_stages = {}
    for n in pre + post:
        for (src, _) in n.inputs:
            if src.op is None:
                var_stages.setdefault(id(src), set()).add("outside")
    for s, seg in enumerate(stage_nodes):
        for n in seg:
            for (src, _) in n.inputs:
                if src.op is None:
                    var_stages.setdefault(id(src), set()).add(s)

    # positional input classification per stage:
    # ("internal", j, oi) | ("carry",) | ("param", k) | ("side", k)
    stage_param_slots = [[] for _ in range(n_stages)]
    sides_of = [[] for _ in range(n_stages)]
    kinds_of = [[] for _ in range(n_stages)]
    for s, seg in enumerate(stage_nodes):
        local_pos = {}
        for j, n in enumerate(seg):
            for oi in range(n.op.num_outputs(n.attrs)):
                local_pos[(id(n), oi)] = (j, oi)
        seen_p, seen_s = {}, {}
        for n in seg:
            for (src, oi) in n.inputs:
                slot = (id(src), oi)
                if slot in local_pos:
                    kinds_of[s].append(("internal",) + local_pos[slot])
                elif produced_by.get(slot) is not None:
                    kinds_of[s].append(("carry",))  # single, checked above
                elif src.op is None and src.is_aux:
                    raise MXNetError(
                        "pipeline: aux state %s used inside stage%d — "
                        "BatchNorm-style ops cannot be pipelined"
                        % (src.name, s))
                elif src.op is None and var_stages[id(src)] == {s}:
                    # consumed by exactly this stage -> its private param
                    if slot not in seen_p:
                        seen_p[slot] = len(stage_param_slots[s])
                        stage_param_slots[s].append(slot)
                    kinds_of[s].append(("param", seen_p[slot]))
                else:
                    # preamble product or a Variable shared across stages
                    # (e.g. a causal mask): a broadcast side input
                    if slot not in seen_s:
                        seen_s[slot] = len(sides_of[s])
                        sides_of[s].append(slot)
                    kinds_of[s].append(("side", seen_s[slot]))

    # stages 1..K-1 must wire identically; stage0's carry positions hold
    # the pipeline input x0 (a preamble product / arg), classified side
    ref = kinds_of[1]
    for s in range(2, n_stages):
        if kinds_of[s] != ref:
            raise MXNetError(
                "pipeline: stage%d wires its inputs differently from "
                "stage1 — stages must be structurally identical" % s)
    carry_pos = [i for i, k in enumerate(ref) if k == ("carry",)]
    if not carry_pos:
        raise MXNetError("pipeline: stages do not consume the carry")
    k0 = list(kinds_of[0])
    # stage0's carry positions name the pipeline input x0. Two legal
    # shapes: a preamble product / shared Variable (classified "side"),
    # or a bare data Variable read only by stage0 — no preamble op —
    # which the scan above classified as a stage-private "param".
    x0_slots = set()
    for i in carry_pos:
        if k0[i][0] == "side":
            x0_slots.add(sides_of[0][k0[i][1]])
        elif k0[i][0] == "param":
            x0_slots.add(stage_param_slots[0][k0[i][1]])
        else:
            x0_slots.add(None)
    if len(x0_slots) != 1 or None in x0_slots:
        raise MXNetError(
            "pipeline: stage0 must read one preamble/arg tensor at the "
            "positions where later stages read the carry")
    x0_slot = next(iter(x0_slots))

    # re-key stage0: x0 becomes the carry; drop it from whichever slot
    # list (sides or stage params) it was classified into
    def rekey(slots, tag):
        x0_idx = slots.index(x0_slot)
        kept = [sl for sl in slots if sl != x0_slot]
        remap = {i: kept.index(sl) for i, sl in enumerate(slots)
                 if sl != x0_slot}
        new_k0 = [("carry",) if k[0] == tag and k[1] == x0_idx else
                  ((tag, remap[k[1]]) if k[0] == tag else k)
                  for k in k0]
        return kept, new_k0

    if x0_slot in sides_of[0]:
        sides0, k0 = rekey(sides_of[0], "side")
    else:
        stage_param_slots[0], k0 = rekey(stage_param_slots[0], "param")
        sides0 = list(sides_of[0])
    if k0 != ref:
        raise MXNetError(
            "pipeline: stage0 wires its inputs differently from stage1")
    # shared side inputs must be the SAME source slots for every stage
    for s in range(2, n_stages):
        if sides_of[s] != sides_of[1]:
            raise MXNetError(
                "pipeline: stage%d consumes different shared inputs "
                "than stage1" % s)
    if sides0 != sides_of[1]:
        raise MXNetError(
            "pipeline: stage0 consumes different shared inputs than "
            "stage1")

    # the outgoing carry must sit at the same local position in every
    # stage (one stage body serves all pp ranks, weight-stationary)
    out_pos = None
    for s, seg in enumerate(stage_nodes):
        local_pos = {}
        for j, n in enumerate(seg):
            for oi in range(n.op.num_outputs(n.attrs)):
                local_pos[(id(n), oi)] = (j, oi)
        p = local_pos.get(carry_slots[s])
        if p is None:
            raise MXNetError(
                "pipeline: stage%d does not produce its carry" % s)
        if out_pos is None:
            out_pos = p
        elif p != out_pos:
            raise MXNetError(
                "pipeline: stage%d emits its carry from a different op "
                "position than stage0" % s)

    return {"pre": pre, "stages": stage_nodes, "post": post,
            "carry_slots": carry_slots, "x0_slot": x0_slot,
            "side_slots": sides_of[1], "kinds": ref, "out_pos": out_pos,
            "stage_param_slots": stage_param_slots}


def _build_eval_pipelined(symbol, mesh, n_microbatch, pp_axis="pp",
                          dp_axis="dp"):
    """Like :func:`_build_eval`, but the symbol's ``ctx_group="stage<i>"``
    region runs as a GPipe pipeline over the mesh's ``pp`` axis.

    One fused program: preamble ops execute under GSPMD as usual; the
    staged region becomes a ``shard_map`` over the full mesh running the
    GPipe schedule (``lax.scan`` of compute + ``lax.ppermute`` ring hops,
    parallel/pipeline_parallel.py design) with each pp rank holding its
    stage's parameters (stacked leading stage axis, sharded on 'pp');
    the postamble (loss head) runs on the re-assembled sequence output.
    ``jax.vjp`` differentiates straight through the schedule, so the
    enclosing fused fwd+bwd/train-step machinery is unchanged.

    Microbatching splits the global batch B into ``n_microbatch`` chunks
    along axis 0 (B % (n_microbatch * dp) == 0); pipeline bubble is the
    standard (S-1)/(M+S-1). Stage bodies must be batch-size-polymorphic
    (Reshape with -1, no BatchNorm inside stages — checked).
    """
    order = symbol._topo()
    arg_nodes = [n for n in order if n.op is None and not n.is_aux]
    aux_nodes = [n for n in order if n.op is None and n.is_aux]
    op_nodes = [n for n in order if n.op is not None]
    heads = symbol._heads
    needs_rng = any(n.op.needs_rng for n in op_nodes)
    aux_ids = {id(n) for n in aux_nodes}

    n_stages = dict(zip(mesh.axis_names, mesh.devices.shape))[pp_axis]
    plan = _split_pipeline_stages(symbol, n_stages)
    pre, stages, post = plan["pre"], plan["stages"], plan["post"]
    body_seg = stages[1]  # canonical stage (kinds computed against it)
    final_slot = plan["carry_slots"][-1]
    out_pos = plan["out_pos"]

    # per-op resolver table for the shared stage body
    kinds_by, it = [], iter(plan["kinds"])
    for n in body_seg:
        kinds_by.append([next(it) for _ in n.inputs])

    def stage_body(param_vals, x, side_vals, key, is_train):
        import jax
        local = {}
        for j, n in enumerate(body_seg):
            ins = []
            for kk in kinds_by[j]:
                if kk[0] == "internal":
                    ins.append(local[(kk[1], kk[2])])
                elif kk[0] == "carry":
                    ins.append(x)
                elif kk[0] == "param":
                    ins.append(param_vals[kk[1]])
                else:
                    ins.append(side_vals[kk[1]])
            sub = None
            if n.op.needs_rng:
                key, sub = jax.random.split(key)
            res = n.op.fcompute(n.attrs, ins, OpContext(is_train=is_train,
                                                        rng=sub))
            for oi in range(n.op.num_outputs(n.attrs)):
                local[(j, oi)] = res[oi]
        return local[out_pos], key

    def eval_fn(arg_vals, aux_vals, rng, is_train, tap=None):
        import jax
        import jax.numpy as jnp
        from jax import lax, shard_map
        from jax.sharding import PartitionSpec as P

        assert tap is None, "pipelined eval has no monitor taps"
        env = {}
        for n, v in zip(arg_nodes, arg_vals):
            env[(id(n), 0)] = v
        for n, v in zip(aux_nodes, aux_vals):
            env[(id(n), 0)] = v
        aux_out = {id(n): v for n, v in zip(aux_nodes, aux_vals)}

        def sink(aid, v):
            if aid in aux_ids:
                aux_out[aid] = v

        def get(i, oi):
            return env[(i, oi)]

        def put(i, oi, v):
            env[(i, oi)] = v

        for n in pre:
            rng, _, _ = _run_op(n, get, put, rng, is_train, aux_sink=sink)

        x0 = env[plan["x0_slot"]]
        sides = tuple(env[s] for s in plan["side_slots"])
        stacked = tuple(
            jnp.stack([env[plan["stage_param_slots"][s][k]]
                       for s in range(n_stages)])
            for k in range(len(plan["stage_param_slots"][0])))
        # pin the stacked stage params REPLICATED before shard_map
        # reshards them to P('pp'): on a multi-axis mesh (dp>1) the
        # GSPMD partitioner on this toolchain (jax 0.4.37) miscompiles
        # an in-jit stack flowing straight into a shard_map P('pp')
        # in_spec — each pp rank silently receives wrong slices and the
        # pipelined numerics diverge (tests/test_module_pp.py parity
        # tests; exact with dp=1, eager, or pre-staged inputs). Routing
        # stack -> replicated -> shard_map's own reshard is compiled
        # correctly and costs one all-gather of the (small) stage
        # params per step.
        from jax.sharding import NamedSharding
        _repl = NamedSharding(mesh, P())
        stacked = tuple(jax.lax.with_sharding_constraint(s, _repl)
                        for s in stacked)

        B, M = x0.shape[0], n_microbatch
        if B % M:
            raise MXNetError(
                "pipeline: batch %d not divisible by %d microbatches"
                % (B, M))
        x_mb = x0.reshape((M, B // M) + x0.shape[1:])
        if needs_rng:
            rng, pipe_key = jax.random.split(rng)
        else:
            pipe_key = jnp.zeros((2,), jnp.uint32)

        def sched(stacked_l, x_l, sides_l, key):
            S = lax.axis_size(pp_axis)
            idx = lax.axis_index(pp_axis)
            params_l = tuple(p[0] for p in stacked_l)
            Ml = x_l.shape[0]
            zero = jnp.zeros_like(x_l[0])
            perm = [(i, (i + 1) % S) for i in range(S)]

            # distinct rng stream per (tick, pp rank, dp shard): without
            # the rank folds, structurally-identical stages would draw
            # byte-identical dropout masks at every tick
            kbase = jax.random.fold_in(key, idx)
            if dp_axis in mesh.axis_names:
                kbase = jax.random.fold_in(kbase,
                                           lax.axis_index(dp_axis))

            def tick(state, t):
                inject = x_l[jnp.minimum(t, Ml - 1)]
                cur = jnp.where(idx == 0, inject, state)
                y, _ = stage_body(params_l, cur, sides_l,
                                  jax.random.fold_in(kbase, t), is_train)
                out = jnp.where(idx == S - 1, y, jnp.zeros_like(y))
                return lax.ppermute(y, pp_axis, perm), out

            _, ys = lax.scan(tick, zero, jnp.arange(Ml + S - 1))
            # only the last stage wrote non-zeros; psum replicates
            return lax.psum(ys[S - 1:], pp_axis)

        y_mb = shard_map(
            sched, mesh=mesh,
            in_specs=(tuple(P(pp_axis) for _ in stacked),
                      P(None, dp_axis), tuple(P() for _ in sides), P()),
            out_specs=P(None, dp_axis), check_vma=False)(
                stacked, x_mb, sides, pipe_key)
        env[final_slot] = y_mb.reshape((B,) + y_mb.shape[2:])

        for n in post:
            rng, _, _ = _run_op(n, get, put, rng, is_train, aux_sink=sink)

        outs = tuple(env[(id(n), oi)] for (n, oi) in heads)
        new_aux = tuple(aux_out[id(n)] for n in aux_nodes)
        return outs, new_aux

    # names of stage-private parameters: these get stacked with a leading
    # stage axis sharded on 'pp' inside shard_map, so caller-supplied
    # param_sharding rules cannot apply to them (MeshExecutorGroup checks)
    id2name = {id(n): n.name for n in arg_nodes}
    stage_param_names = {id2name[sid]
                         for slots in plan["stage_param_slots"]
                         for (sid, _oi) in slots}
    return eval_fn, needs_rng, stage_param_names


class Executor:
    """Runnable binding of a Symbol to argument/gradient/aux NDArrays."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None, shared_exec=None):
        import jax

        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self.arg_names = arg_names
        self.aux_names = aux_names

        self.arg_arrays = self._normalize(args, arg_names, "args")
        self.aux_arrays = self._normalize(aux_states or [], aux_names,
                                          "aux_states")
        self.arg_dict = dict(zip(arg_names, self.arg_arrays))
        self.aux_dict = dict(zip(aux_names, self.aux_arrays))

        # gradient buffers + per-arg request
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in arg_names}
        if args_grad is None:
            self.grad_arrays = [None] * len(arg_names)
            for n in arg_names:
                self._grad_req[n] = "null"
        elif isinstance(args_grad, dict):
            self.grad_arrays = [args_grad.get(n) for n in arg_names]
            for n in arg_names:
                if args_grad.get(n) is None:
                    self._grad_req[n] = "null"
        else:
            self.grad_arrays = list(args_grad)
            while len(self.grad_arrays) < len(arg_names):
                self.grad_arrays.append(None)
        self.grad_dict = dict(zip(arg_names, self.grad_arrays))
        self._diff_names = [n for n in arg_names
                            if self._grad_req.get(n, "null") != "null"
                            and self.grad_dict.get(n) is not None]

        self._eval_fn, self._needs_rng = _build_eval(symbol)

        # jitted programs (compiled lazily on first use, cached thereafter —
        # the "compile once via simple_bind, reuse every batch" contract)
        self._jit_fwd = {
            True: jax.jit(partial(self._eval_fn, is_train=True)),
            False: jax.jit(partial(self._eval_fn, is_train=False)),
        }
        self._jit_grad = jax.jit(self._grad_step)

        # allocate persistent output buffers from abstract evaluation
        arg_structs = [jax.ShapeDtypeStruct(a.shape, onp.dtype(a.dtype))
                       for a in self.arg_arrays]
        aux_structs = [jax.ShapeDtypeStruct(a.shape, onp.dtype(a.dtype))
                       for a in self.aux_arrays]
        rng_struct = jax.ShapeDtypeStruct((2,), onp.uint32)
        out_structs, _ = jax.eval_shape(partial(self._eval_fn, is_train=False),
                                        arg_structs, aux_structs, rng_struct)
        from . import ndarray as nd
        self._out_arrays = [nd.zeros(s.shape, ctx=ctx, dtype=s.dtype)
                            for s in out_structs]
        self.outputs = self._out_arrays
        self.output_dict = dict(zip(symbol.list_outputs(), self._out_arrays))

        self._pending = None     # (is_train, arg_vals, aux_vals, rng)
        self._last_run = None    # captured values of the last forward
        self._monitor_callback = None

    # ------------------------------------------------------------------
    def _normalize(self, arrays, names, what):
        from .ndarray import NDArray
        if isinstance(arrays, dict):
            missing = [n for n in names if n not in arrays]
            if missing:
                raise MXNetError("missing %s: %s" % (what, missing))
            return [arrays[n] for n in names]
        arrays = list(arrays)
        if len(arrays) != len(names):
            raise MXNetError("%s length %d != expected %d"
                             % (what, len(arrays), len(names)))
        return arrays

    # ------------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """Schedule a forward pass; returns the output NDArrays (lazy).

        Mirrors Executor::Forward / MXExecutorForward: copies any kwargs into
        the bound input arrays first (the reference requires explicit copy;
        we keep the convenience from executor.py:86)."""
        if kwargs:
            for k, v in kwargs.items():
                if k not in self.arg_dict:
                    raise MXNetError("unknown input %s" % k)
                from .ndarray import NDArray
                if isinstance(v, NDArray):
                    v.copyto(self.arg_dict[k])
                else:
                    self.arg_dict[k][:] = v

        arg_vals = [a._read() for a in self.arg_arrays]
        aux_vals = [a._read() for a in self.aux_arrays]
        rng = _random.next_key() if self._needs_rng else \
            onp.zeros((2,), onp.uint32)
        self._pending = (bool(is_train), arg_vals, aux_vals, rng)
        self._last_run = self._pending
        if self._monitor_active():
            # execute-with-taps: run the per-node interpreter eagerly and
            # feed every op output to the monitor callback — the reference
            # copies each output to ExecuteMonCallback
            # (graph_executor.cc:760-778)
            self._pending = None
            cb = self._monitor_callback
            from . import ndarray as nd

            def tap(name, val):
                cb(name, nd.NDArray(val, ctx=self._ctx, writable=False))

            outs, new_aux = self._eval_fn(arg_vals, aux_vals, rng,
                                          bool(is_train), tap=tap)
            self._write_results(outs, new_aux, bool(is_train))
            return self.outputs
        force = self._materialize_forward
        for o in self._out_arrays:
            o._chunk.force = force
        return self.outputs

    def _monitor_active(self):
        cb = self._monitor_callback
        if cb is None:
            return False
        owner = getattr(cb, "__self__", None)
        # Monitor gates taps by interval via its ``activated`` flag; plain
        # callables tap every batch
        return getattr(owner, "activated", True) is not False

    def _materialize_forward(self):
        if self._pending is None:
            return
        is_train, arg_vals, aux_vals, rng = self._pending
        self._pending = None
        outs, new_aux = self._jit_fwd[is_train](arg_vals, aux_vals, rng)
        self._write_results(outs, new_aux, is_train)

    def _write_results(self, outs, new_aux, is_train):
        for o, v in zip(self._out_arrays, outs):
            o._chunk.force = None
            o._chunk.arr = v
        if is_train:
            for a, v in zip(self.aux_arrays, new_aux):
                a._write(v)

    # ------------------------------------------------------------------
    def _grad_step(self, arg_vals, aux_vals, rng, head_grads):
        import jax
        names = self.arg_names
        diff_idx = [i for i, n in enumerate(names) if n in self._diff_names]
        diff_vals = tuple(arg_vals[i] for i in diff_idx)

        def f(diff):
            merged = list(arg_vals)
            for i, v in zip(diff_idx, diff):
                merged[i] = v
            outs, new_aux = self._eval_fn(merged, aux_vals, rng, True)
            return outs, new_aux

        outs, vjp_fn, new_aux = jax.vjp(f, diff_vals, has_aux=True)
        (grads,) = vjp_fn(tuple(head_grads))
        return outs, new_aux, grads

    def backward(self, out_grads=None):
        """Fused forward+backward XLA program; writes gradients honoring
        grad_req write/add (Executor::Backward, graph_executor.cc:45)."""
        import jax.numpy as jnp
        if self._last_run is None:
            raise MXNetError("backward() called before forward()")
        is_train, arg_vals, aux_vals, rng = self._last_run
        self._pending = None
        if out_grads is None:
            heads = [jnp.ones(o.shape, o.dtype) for o in self._out_arrays]
        else:
            from .ndarray import NDArray
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = [g._read() if isinstance(g, NDArray) else jnp.asarray(g)
                     for g in out_grads]
        outs, new_aux, grads = self._jit_grad(arg_vals, aux_vals, rng, heads)
        self._write_results(outs, new_aux, is_train=True)
        for name, g in zip(self._diff_names, grads):
            buf = self.grad_dict[name]
            if self._grad_req[name] == "add":
                buf._write(buf._read() + g)
            else:
                buf._write(g)

    # ------------------------------------------------------------------
    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor bound to resized arrays (executor.py:287).

        Matches the reference's flag semantics: an arg whose shape changes
        without being named in kwargs requires ``partial_shaping``; growing
        an array beyond its current element count requires
        ``allow_up_sizing`` (same-or-smaller reshapes share memory)."""
        from . import ndarray as nd
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        if arg_shapes is None:
            raise MXNetError("Insufficient argument shapes provided.")

        def _resize(name, new_shape, arr, specified):
            new_shape = tuple(new_shape)
            if tuple(arr.shape) == new_shape:
                return arr
            if not (partial_shaping or specified):
                raise MXNetError(
                    "Shape of unspecified array %s changed. This can cause "
                    "the new executor to not share parameters with the old "
                    "one. Set partial_shaping=True if intended." % name)
            if int(onp.prod(new_shape)) > arr.size:
                if not allow_up_sizing:
                    raise MXNetError(
                        "New shape of %s larger than original; set "
                        "allow_up_sizing=True to allocate a new array."
                        % name)
                return nd.empty(new_shape, ctx=arr.context, dtype=arr.dtype)
            if int(onp.prod(new_shape)) == arr.size:
                return arr.reshape(new_shape)
            # shrink: the reference keeps a prefix view of the old buffer
            # (executor.py:287 arr.reshape); values are preserved here via a
            # prefix copy (jax arrays are immutable, so no aliased view)
            prefix = arr._read().ravel()[:int(onp.prod(new_shape))]
            return nd.NDArray(prefix.reshape(new_shape), ctx=arr.context)

        new_args, grads = {}, None
        if any(g is not None for g in self.grad_arrays):
            grads = {}
        for name, new_shape, arr in zip(self.arg_names, arg_shapes,
                                        self.arg_arrays):
            new_args[name] = _resize(name, new_shape, arr, name in kwargs)
            g = self.grad_dict.get(name)
            if g is not None:
                grads[name] = _resize("grad of " + name, new_shape, g,
                                      name in kwargs)
        new_aux = {}
        for name, new_shape, arr in zip(self.aux_names, aux_shapes,
                                        self.aux_arrays):
            new_aux[name] = _resize(name, new_shape, arr, True)
        return Executor(self._symbol, self._ctx, new_args, grads,
                        self._grad_req, new_aux)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                arr.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise ValueError("Found name \"%s\" not in arguments" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    arr.copyto(self.aux_dict[name])
                elif not allow_extra_params:
                    raise ValueError("Found name \"%s\" not in aux" % name)

    def set_monitor_callback(self, callback):
        self._monitor_callback = callback

    def debug_str(self):
        lines = ["Symbol outputs: %s" % ", ".join(self._symbol.list_outputs())]
        for n in self._symbol._topo():
            if n.op is not None:
                lines.append("Op:%s, Name=%s" % (n.op.name, n.name))
        lines.append("Memory planning: delegated to XLA buffer assignment")
        return "\n".join(lines)
