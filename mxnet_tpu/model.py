"""Model helpers + legacy FeedForward API (python/mxnet/model.py:946).

Holds the kvstore decision/update helpers shared by Module
(model.py:40-116) and the deprecated-but-supported FeedForward class (used
by the reference's nightly dist tests, tests/nightly/dist_lenet.py:24) —
implemented here on top of Module, since the pre-Module executor_manager
layer has no TPU-side reason to exist.
"""
from __future__ import annotations

import logging
import os

import numpy as onp

from . import context as ctx_mod
from . import ndarray as nd
from . import symbol as sym_mod
from . import kvstore as kvs
from .base import MXNetError

__all__ = ["BatchEndParam", "FeedForward", "save_checkpoint",
           "load_checkpoint"]

BatchEndParam = None  # re-exported from module.base_module lazily


def _create_kvstore(kvstore, num_device, arg_params):
    """Decide (kvstore, update_on_kvstore) (model.py:40-76)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None  # single device: no need for a store
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                # reference heuristic: big arrays favour allreduce-style
                # (update locally), small ones update-on-kvstore
                max_size = max(onp.prod(param.shape)
                               for param in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    # MXNET_UPDATE_ON_KVSTORE: direct override of the heuristic (the
    # upstream env contract for forcing either update path)
    env_override = os.environ.get("MXNET_UPDATE_ON_KVSTORE")
    if env_override is not None and kv is not None:
        update_on_kvstore = env_override == "1"
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """init keys + optional initial pull (model.py:79)."""
    for idx, param_on_devs in enumerate(param_arrays):
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore):
    """push grads, pull updated weights (model.py:88-97)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list is None or grad_list[0] is None:
            continue
        kvstore.push(index, grad_list, priority=-index)
        kvstore.pull(index, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, donate=False):
    """aggregate via kvstore (or not), update locally (model.py:99-116).

    All per-(param, device) updates are batched into ONE jitted XLA call
    (Updater.update_multi) — the reference pushes one engine op per param.
    ``donate`` passes weight/state buffers to XLA for in-place HBM updates
    (the fused Module path sets it on accelerators)."""
    triples = []
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list is None or grad_list[0] is None:
            continue
        if kvstore:
            kvstore.push(index, grad_list, priority=-index)
            kvstore.pull(index, grad_list, priority=-index)
        for k, p, g in zip(range(len(arg_list)), arg_list, grad_list):
            # unique integer key per (param, device)
            triples.append((index * num_device + k, g, p))
    if hasattr(updater, "update_multi"):
        updater.update_multi(triples, donate=donate)
    else:
        for key, g, p in triples:
            updater(key, g, p)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """prefix-symbol.json + prefix-%04d.params (model.py save_checkpoint).

    Thin wrapper over :mod:`mxnet_tpu.checkpoint`'s legacy param-file
    helpers — the write is atomic (tmp + fsync + rename). For durable,
    async, sharded step checkpoints use
    :class:`mxnet_tpu.checkpoint.CheckpointManager` instead."""
    from .checkpoint import save_params_file
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    param_name = "%s-%04d.params" % (prefix, epoch)
    save_params_file(param_name, arg_params, aux_params)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_checkpoint(prefix, epoch):
    """Load (symbol, arg_params, aux_params) (model.py load_checkpoint).
    Thin wrapper over :mod:`mxnet_tpu.checkpoint`'s legacy helpers."""
    from .checkpoint import load_params_file
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params_file("%s-%04d.params"
                                              % (prefix, epoch))
    return (symbol, arg_params, aux_params)


class FeedForward(object):
    """Legacy training API (model.py FeedForward) — thin shim over Module."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform
        self.symbol = symbol
        if ctx is None:
            ctx = [ctx_mod.current_context()]
        elif isinstance(ctx, ctx_mod.Context):
            ctx = [ctx]
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.kwargs = kwargs.copy()
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self._module = None

    def _make_module(self, data_names, label_names):
        from .module import Module
        self._module = Module(self.symbol, data_names=data_names,
                              label_names=label_names, context=self.ctx)
        return self._module

    @staticmethod
    def _as_iter(X, y, batch_size, shuffle=False, label_name="softmax_label"):
        from .io import NDArrayIter, DataIter
        if isinstance(X, DataIter):
            return X
        return NDArrayIter(X, y, batch_size=batch_size, shuffle=shuffle,
                           label_name=label_name)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        train_data = self._as_iter(X, y, self.numpy_batch_size, shuffle=True)
        data_names = [x[0] for x in train_data.provide_data]
        label_names = [x[0] for x in train_data.provide_label]
        mod = self._make_module(data_names, label_names)
        optimizer_params = {k: v for k, v in self.kwargs.items()}
        mod.fit(train_data,
                eval_data=self._as_iter(eval_data[0], eval_data[1],
                                        self.numpy_batch_size)
                if isinstance(eval_data, tuple) else eval_data,
                eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback,
                kvstore=kvstore, optimizer=self.optimizer,
                optimizer_params=optimizer_params,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback,
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                allow_missing=True, begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch, monitor=monitor)
        self.arg_params, self.aux_params = mod.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        eval_iter = self._as_iter(X, None, self.numpy_batch_size)
        data_names = [x[0] for x in eval_iter.provide_data]
        if self._module is None or not self._module.binded:
            # loss label variables (…_label) are args of the symbol but not
            # checkpoint params; declare them as labels so an unlabeled
            # predict bind skips them (reference _init_predictor contract)
            label_names = [n for n in self.symbol.list_arguments()
                           if n.endswith("_label")]
            mod = self._make_module(data_names, label_names)
            mod.bind(data_shapes=eval_iter.provide_data, label_shapes=None,
                     for_training=False)
            mod.init_params(arg_params=self.arg_params,
                            aux_params=self.aux_params)
        out = self._module.predict(eval_iter, num_batch=num_batch,
                                   reset=reset)
        if isinstance(out, list):
            return [o.asnumpy() for o in out]
        return out.asnumpy()

    def score(self, X, y=None, eval_metric="acc", num_batch=None, reset=True):
        eval_iter = self._as_iter(X, y, self.numpy_batch_size)
        data_names = [x[0] for x in eval_iter.provide_data]
        label_names = [x[0] for x in eval_iter.provide_label]
        if self._module is None or not self._module.binded:
            mod = self._make_module(data_names, label_names)
            mod.bind(data_shapes=eval_iter.provide_data,
                     label_shapes=eval_iter.provide_label, for_training=False)
            mod.init_params(arg_params=self.arg_params,
                            aux_params=self.aux_params)
        res = self._module.score(eval_iter, eval_metric, num_batch=num_batch,
                                 reset=reset)
        return res[0][1]

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        assert epoch is not None
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """Train a new model from scratch (model.py FeedForward.create)."""
        from .initializer import Uniform
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer or Uniform(0.01),
                            **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
