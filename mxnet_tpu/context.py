"""Device context model.

TPU-native equivalent of the reference Context (include/mxnet/base.h:117-208):
``Context{kCPU,kGPU,kCPUPinned} + dev_id``. Here ``gpu``/``tpu`` are the same
accelerator device type (so reference scripts using ``--gpus`` run unchanged
with TPU chips), and every Context maps onto a concrete ``jax.Device``.
"""
from __future__ import annotations

import threading

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context",
           "num_devices"]


class Context:
    """Device context. ``Context('tpu', 0)`` / ``mx.tpu(0)`` / ``mx.gpu(0)``.

    Mirrors mxnet.context.Context (python/mxnet/context.py) including use as a
    ``with`` scope for default-context selection.
    """

    # dev-type codes follow the reference enum (base.h:121-125); tpu aliases gpu.
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 2}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- JAX mapping -------------------------------------------------------
    def jax_device(self):
        """Resolve this Context to a concrete jax.Device.

        cpu -> a jax CPU-backend device; gpu/tpu -> the default accelerator
        backend's device ``device_id``. When JAX runs CPU-only (tests use an
        8-device virtual CPU mesh), accelerator contexts map onto CPU devices
        so multi-device semantics stay testable, matching the reference's
        trick of testing "multi-device" on multiple cpu contexts
        (tests/python/unittest/test_model_parallel.py:12-30).
        """
        import jax

        # multi-process: a Context addresses THIS process's devices (the
        # reference's Context is process-local too); global jax.devices()
        # would hand out peers' unaddressable devices
        local = jax.process_count() > 1

        if self.device_type in ("cpu", "cpu_pinned"):
            try:
                devs = jax.local_devices(backend="cpu") if local \
                    else jax.devices("cpu")
            except RuntimeError:
                devs = jax.local_devices() if local else jax.devices()
            return devs[min(self.device_id, len(devs) - 1)]
        # default backend: TPU when present, else CPU
        devs = jax.local_devices() if local else jax.devices()
        if self.device_id >= len(devs):
            raise ValueError(
                "Context %s out of range: only %d device(s) visible to JAX"
                % (self, len(devs)))
        return devs[self.device_id]


def cpu(device_id=0):
    """Return a CPU context (mirrors mx.cpu)."""
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    """Pinned-host context; identical to cpu under XLA (no hipHostMalloc)."""
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Accelerator context; on this build an alias for tpu(device_id)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """Return a TPU context."""
    return Context("tpu", device_id)


def num_devices(device_type="tpu"):
    """Number of visible devices of a type."""
    import jax

    if device_type in ("cpu", "cpu_pinned"):
        try:
            return len(jax.devices("cpu"))
        except RuntimeError:
            return 0
    return len(jax.devices())


def current_context():
    """The thread-local default context (mx.current_context)."""
    cur = getattr(Context._default_ctx, "value", None)
    return cur if cur is not None else Context("cpu", 0)
