"""Monitor — inspect every op's outputs (and weights/aux) during training
(python/mxnet/monitor.py:16 + MXExecutorSetMonitorCallback).

The reference's GraphExecutor copies each op output to a registered C
callback (ExecuteMonCallback, graph_executor.cc:760-778). Here ``install``
registers ``stat_helper`` as the executor's monitor callback; while a
monitored batch is active the executor runs its per-node interpreter with
taps (executor.py forward) and feeds every op output through ``stat_func``.
``tic``/``toc`` gate taps to every ``interval``-th batch, so non-monitored
batches keep the fused jit fast path.
"""
from __future__ import annotations

import logging
import re

from . import ndarray as nd

__all__ = ["Monitor"]


class Monitor(object):
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return nd.norm(x) / (x.size ** 0.5)
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, array):
        """Per-op-output callback fed by the executor's tapped run."""
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(array)))

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            for name, array in exe.arg_dict.items():
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(array)))
            for name, array in exe.aux_dict.items():
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(array)))
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, nd.NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ""
            for v in v_list:
                assert isinstance(v, nd.NDArray)
                if v.shape == (1,):
                    s += str(v.asscalar()) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: {:7d} {:30s} {:s}".format(n, k, v))
