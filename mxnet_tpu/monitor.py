"""Monitor — inspect every op's outputs (and weights/aux) during training
(python/mxnet/monitor.py:16 + MXExecutorSetMonitorCallback).

The reference's GraphExecutor copies each op output to a registered C
callback (ExecuteMonCallback, graph_executor.cc:760-778). Here ``install``
registers ``stat_helper`` as the executor's monitor callback; while a
monitored batch is active the executor runs its per-node interpreter with
taps (executor.py forward) and feeds every op output through ``stat_func``.
``tic``/``toc`` gate taps to every ``interval``-th batch, so non-monitored
batches keep the fused jit fast path.
"""
from __future__ import annotations

import logging
import re

from . import ndarray as nd

__all__ = ["Monitor"]


def _rms_stat(x):
    """Default statistic: |x|'s root-mean-square (the reference's
    norm/sqrt(size) "asum" default)."""
    return nd.norm(x) / (x.size ** 0.5)


class Monitor(object):
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.stat_func = stat_func or _rms_stat
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, array):
        """Per-op-output callback fed by the executor's tapped run."""
        if self.activated and self.re_prog.match(name):
            self.queue.append((self.step, name, self.stat_func(array)))

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def _tap_state_dicts(self):
        """End-of-batch weight/aux taps (the reference monitors these in
        addition to op outputs)."""
        for exe in self.exes:
            for source in (exe.arg_dict, exe.aux_dict):
                for name, array in source.items():
                    if self.re_prog.match(name):
                        self.queue.append(
                            (self.step, name, self.stat_func(array)))

    @staticmethod
    def _render(stat):
        values = stat if isinstance(stat, list) else [stat]
        parts = []
        for v in values:
            assert isinstance(v, nd.NDArray), \
                "stat_func must return NDArray(s)"
            parts.append(str(v.asscalar() if v.shape == (1,)
                             else v.asnumpy()))
        return "\t".join(parts) + "\t"

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        self._tap_state_dicts()
        if self.sort:
            self.queue.sort(key=lambda entry: entry[1])
        drained = [(step, name, self._render(stat))
                   for step, name, stat in self.queue]
        self.queue = []
        return drained

    def toc_print(self):
        for step, name, rendered in self.toc():
            logging.info("Batch: {:7d} {:30s} {:s}".format(
                step, name, rendered))
