"""Monitor — inspect internal outputs/weights during training
(python/mxnet/monitor.py:16 + MXExecutorSetMonitorCallback).

The reference copies every op output via a C callback
(graph_executor.cc:760-778); here ``install`` binds a side executor over
``symbol.get_internals()`` sharing the main executor's arrays, evaluated on
``toc`` — same observability, one extra XLA program only while monitoring.
"""
from __future__ import annotations

import logging
import re

from . import ndarray as nd

__all__ = ["Monitor"]


class Monitor(object):
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return nd.norm(x) / (x.size ** 0.5)
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def install(self, exe):
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            for name, array in exe.arg_dict.items():
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(array)))
            for name, array in exe.aux_dict.items():
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(array)))
            for name, array in zip(exe._symbol.list_outputs(), exe.outputs):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(array)))
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, nd.NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ""
            for v in v_list:
                assert isinstance(v, nd.NDArray)
                if v.shape == (1,):
                    s += str(v.asscalar()) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: {:7d} {:30s} {:s}".format(n, k, v))
