"""Image decode helper backing mx.nd.imdecode (src/io/image_io.cc:304)."""
from __future__ import annotations

import numpy as onp

from .ndarray import NDArray, array


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0,
             channels=3, mean=None):
    """Decode an encoded image byte string to NDArray (HWC, BGR like the
    reference's OpenCV path). Uses cv2 when present, else PIL, else raises.
    """
    buf = onp.frombuffer(bytes(str_img), dtype=onp.uint8)
    img = None
    try:
        import cv2
        flag = 1 if channels == 3 else 0
        img = cv2.imdecode(buf, flag)
    except ImportError:
        try:
            from PIL import Image
            import io as _io
            pil = Image.open(_io.BytesIO(bytes(str_img)))
            img = onp.asarray(pil)
            if channels == 3 and img.ndim == 3:
                img = img[:, :, ::-1]  # RGB -> BGR to match OpenCV
        except ImportError:
            raise ImportError("imdecode requires cv2 or PIL")
    if img is None:
        raise ValueError("cannot decode image")
    if mean is not None:
        img = img.astype(onp.float32) - mean
    res = array(img.astype(onp.float32))
    if out is not None:
        res.copyto(out)
        return out
    return res
