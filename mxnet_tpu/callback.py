"""Training callbacks.

API counterpart of the reference's python/mxnet/callback.py. Two kinds:

- epoch callbacks ``f(epoch, symbol, arg_params, aux_params)`` invoked by
  ``Module.fit`` after each epoch (checkpointing lives here);
- batch callbacks ``f(BatchEndParam)`` invoked after every batch
  (throughput logging, progress display).

TPU note: train steps dispatch asynchronously — a batch callback that
only looks at ``param.nbatch`` measures the host-side dispatch rate, not
device progress. Callbacks that read ``param.eval_metric`` force the
outputs to materialize, which synchronizes with the device; that is why
``Speedometer`` readings with a metric attached are the honest ones.
"""
from __future__ import annotations

import logging
import math
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar", "LogValidationMetricsCallback"]


def module_checkpoint(mod, prefix=None, period=1,
                      save_optimizer_states=False, manager=None,
                      async_save=True):
    """Epoch callback: save ``mod`` every ``period`` epochs as
    ``prefix-%04d.params`` (+ ``.states``).

    With ``manager=`` (a :class:`mxnet_tpu.checkpoint
    .CheckpointManager`) the save commits a durable step entry per
    epoch — atomic, async by default (the next epoch's first train
    step overlaps the disk write), sharded per local device shard. The
    step number is the 0-based epoch index just completed, which is
    what ``fit(resume_from=manager)`` reads to continue at the next
    epoch. ``prefix`` may then be omitted; if both are given, the
    legacy prefix files are still written too (for tooling that
    consumes them)."""
    if prefix is None and manager is None:
        raise ValueError("module_checkpoint needs a prefix or a manager")
    period = max(1, int(period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        epoch = iter_no + 1
        if epoch % period == 0:
            if manager is not None:
                mod.save_checkpoint(prefix, iter_no, save_optimizer_states,
                                    manager=manager, async_save=async_save)
            if prefix is not None:
                mod.save_checkpoint(prefix, epoch, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch callback: save the passed symbol+params every ``period``
    epochs (the FeedForward-era twin of :func:`module_checkpoint`)."""
    from .model import save_checkpoint
    period = max(1, int(period))

    def _callback(iter_no, sym, arg, aux):
        epoch = iter_no + 1
        if epoch % period == 0:
            save_checkpoint(prefix, epoch, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch callback: log the training metric every ``period`` batches,
    optionally resetting it afterwards (windowed rather than running
    averages)."""

    def _callback(param):
        metric = param.eval_metric
        if metric is None or param.nbatch % period != 0:
            return
        for name, value in metric.get_name_value():
            logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                         param.epoch, param.nbatch, name, value)
        if auto_reset:
            metric.reset()

    return _callback


class Speedometer(object):
    """Batch callback: log samples/sec (and the training metric, if one
    is attached) every ``frequent`` batches. The window restarts at every
    epoch boundary (detected by ``nbatch`` wrapping backwards).

    Stride-aware: ``fit(batch_group=K)`` fires the callback once per
    group with ``nbatch`` advancing by K, so the window counts the
    batches actually seen since the last log (identical behavior at
    stride 1) and the rate is computed from that true count. The metric
    read below is the window's ONE device-tally drain — it happens at a
    group boundary, never mid-group.

    When ``fit`` trains from the async device-feed pipeline
    (``prefetch_to_device=`` / a :class:`mxnet_tpu.data.DeviceLoader`),
    each log line also carries the window's **host-wait fraction** —
    the share of the window's wall time the loop spent blocked on the
    input path (``PipelineStats.host_wait_ms``, read from the
    telemetry registry's active-pipeline slot — ``fit`` publishes the
    loader it trains through via ``telemetry.set_active_pipeline``).
    ~0% means decode + transfer are fully hidden behind the device
    step; a large value means the epoch is input-bound — visible in
    the training log, not just in bench.py."""

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self._tic = None
        self._last_count = 0
        self._seen = 0
        self._wait_seen = None

    @staticmethod
    def _pipeline_stats(param):
        """The PipelineStats of the device-feed loader the CURRENT fit
        trains through (None when fit is host-fed): the telemetry
        registry's active-pipeline registration, which replaced the old
        hack of sniffing ``train_data`` out of the fit loop's locals."""
        from . import telemetry
        return telemetry.active_pipeline()

    def __call__(self, param):
        count = param.nbatch
        # <= not <: nbatch strictly increases WITHIN an epoch, so an
        # equal count is also a new epoch (single-group/single-batch
        # epochs repeat the same nbatch every epoch — with < the wrap
        # never fired and the window silently spanned epochs)
        if count <= self._last_count:
            self._tic = None  # new epoch: restart the timing window
            self._seen = 0
        delta = count - self._last_count
        self._last_count = count

        stats = self._pipeline_stats(param)
        if self._tic is None:
            self._tic = time.time()
            self._seen = 0
            self._wait_seen = stats.snapshot()["host_wait_ms"] \
                if stats is not None else None
            return
        self._seen += delta
        if self._seen < self.frequent:
            return

        elapsed = time.time() - self._tic
        speed = self._seen * self.batch_size / elapsed
        wait_txt = ""
        if stats is not None and self._wait_seen is not None:
            # the window's slice of the cumulative host-wait clock,
            # as a fraction of the window's wall time
            wait_ms = stats.snapshot()["host_wait_ms"] - self._wait_seen
            wait_txt = "\thost-wait=%.1f%%" % (
                100.0 * wait_ms / max(elapsed * 1000.0, 1e-9))
        metric = param.eval_metric
        if metric is not None:
            # reading the metric materializes outputs -> device-synced rate
            pairs = metric.get_name_value()
            metric.reset()
            for name, value in pairs:
                logging.info(
                    "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                    "\tTrain-%s=%f%s",
                    param.epoch, count, speed, name, value, wait_txt)
        else:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                         param.epoch, count, speed, wait_txt)
        self._tic = time.time()
        self._seen = 0
        self._wait_seen = stats.snapshot()["host_wait_ms"] \
            if stats is not None else None


class ProgressBar(object):
    """Batch callback: text progress bar over ``total`` batches."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        done = int(round(self.bar_len * param.nbatch / float(self.total)))
        pct = math.ceil(100.0 * param.nbatch / float(self.total))
        logging.info("[%s] %s%%\r",
                     "=" * done + "-" * (self.bar_len - done), pct)


class LogValidationMetricsCallback(object):
    """Eval-end callback: log every validation metric for the epoch."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f",
                         param.epoch, name, value)
