"""Multi-model tenancy — several named Predictors behind one queue.

One serving process, one :class:`DynamicBatcher`, several models (or
several checkpoint generations of ONE model, for canary rollout):
each :class:`Tenant` binds a name to a Predictor, an optional
:class:`~mxnet_tpu.telemetry.SLOTracker`, and an admission priority.
Requests route by tenant name; the worker coalesces launches WITHIN a
tenant (different tenants run different compiled programs) and picks
the next launch by (priority, oldest head request), so a high-priority
tenant's backlog is served first while FIFO order holds within each
tenant.

Observability stays per-tenant by construction: every Predictor owns
its own ``serving.<i>.*`` registry scope (counters, latency/phase
histograms, warmup gauges) and every tenant's tracker its own
``slo.<name>.*`` burn-rate gauges — a p99 regression or a shed
decision is attributable to ONE tenant on a single scrape.

Admission policy (the consumer of the ``slo_breached()`` hook):

* a tenant whose OWN fast+slow burn windows are in breach is **shed**
  — new submits raise :class:`~mxnet_tpu.serving.TenantShed`
  synchronously, and already-queued requests are dropped at dequeue
  time with their queue age traced (``outcome: "shed"``) — unless the
  tenant is protected;
* ``priority >= 1`` marks a tenant protected (never shed — it keeps
  serving through its own breach; use for the production generation
  in a canary pair), as does ``protected=True`` or listing the name in
  ``MXNET_SERVE_TENANT_PROTECTED``;
* shed decisions are recorded in the tenant's serving stats (``sheds``
  counter, ``shed_age_ms`` histogram, trace ring) but are NOT fed back
  into the tenant's SLOTracker — recording its own sheds as
  unavailability would lock a breached tenant out forever; instead the
  bad events age out of the burn windows and the tenant readmits
  itself once its budget recovers;
* ``MXNET_SERVE_TENANT_SHED=0`` disables shedding process-wide
  (breaches then only gauge/report, the pre-tenancy behavior).

Canary rollout rides the checkpoint manager::

    mgr = mx.checkpoint.CheckpointManager("ckpts")
    stable = Predictor.load(mgr, 100, data_shapes=shapes)
    canary = Predictor.load(mgr, 110, data_shapes=shapes)
    srv = DynamicBatcher(tenants={
        "stable": Tenant("stable", stable, priority=1,
                         slo=SLOTracker("stable", p99_ms=50,
                                        availability=0.999)),
        "canary": Tenant("canary", canary,
                         slo=SLOTracker("canary", p99_ms=50,
                                        availability=0.99)),
    })
    srv.submit(x, tenant="canary")   # sheds itself on its own breach
"""
from __future__ import annotations

import os

from .predictor import Predictor

__all__ = ["Tenant"]


def _env_protected_names():
    raw = os.environ.get("MXNET_SERVE_TENANT_PROTECTED", "")
    return {s.strip() for s in raw.split(",") if s.strip()}


def shed_enabled():
    """Process-wide master switch for SLO-driven admission shedding
    (``MXNET_SERVE_TENANT_SHED``, default on)."""
    return os.environ.get("MXNET_SERVE_TENANT_SHED", "1") != "0"


class Tenant(object):
    """One named model behind the shared queue.

    Parameters
    ----------
    name : str
        Routing key (``submit(..., tenant=name)``) and the spelling
        shed warnings/telemetry use.
    predictor : Predictor
        The tenant's bucketed inference engine; its ``ServingStats``
        scope is the tenant's per-request observability.
    slo : mxnet_tpu.telemetry.SLOTracker, optional
        The tenant's declared objectives. Every outcome of THIS
        tenant's traffic records against it, and its multi-window
        breach state drives the admission decision. Without one the
        tenant is never shed (nothing to breach).
    priority : int
        Admission priority (default 0). The worker serves the
        highest-priority backlog first; ``priority >= 1`` additionally
        protects the tenant from shedding.
    protected : bool, optional
        Explicit shed exemption; defaults to ``priority >= 1``. Names
        in ``MXNET_SERVE_TENANT_PROTECTED`` are always protected.
    """

    def __init__(self, name, predictor, slo=None, priority=0,
                 protected=None):
        if not isinstance(predictor, Predictor):
            raise TypeError(
                "Tenant %r needs a Predictor (got %s)"
                % (name, type(predictor).__name__))
        self.name = str(name)
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        self.predictor = predictor
        self.slo = slo
        self.priority = int(priority)
        if protected is None:
            protected = self.priority >= 1
        self._protected = bool(protected)

    @property
    def protected(self):
        """Shed exemption — explicit/priority protection fixed at
        construction, plus a LIVE read of
        ``MXNET_SERVE_TENANT_PROTECTED`` (like the
        ``MXNET_SERVE_TENANT_SHED`` master switch, so an operator can
        protect a tenant mid-incident without a restart)."""
        return self._protected or self.name in _env_protected_names()

    @property
    def stats(self):
        """The tenant's :class:`ServingStats` (the Predictor's)."""
        return self.predictor._stats

    def shed_active(self):
        """Whether admission is currently shedding this tenant: its
        own SLO in multi-window breach, tenant not protected, shedding
        enabled. O(1) between the tracker's ``refresh_s`` windows."""
        return (shed_enabled() and self.slo is not None
                and not self.protected and self.slo.breached_cached())

    def __repr__(self):
        return ("Tenant(%r, priority=%d%s%s)"
                % (self.name, self.priority,
                   ", protected" if self.protected else "",
                   ", slo=%s" % self.slo.name if self.slo is not None
                   else ""))
