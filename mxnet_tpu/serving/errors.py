"""Serving-path error types.

Overload must degrade, not OOM: each failure mode a caller can react
to gets its own exception class so client code (and the demo servers)
can distinguish "back off and retry" (:class:`QueueFull`) from "this
request died" (:class:`RequestTimeout`) from "stop sending"
(:class:`ServerClosed`).
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["QueueFull", "RequestAbandoned", "RequestTimeout",
           "ServerClosed", "TenantShed", "WorkerCrashed"]


class QueueFull(MXNetError):
    """Backpressure: the batcher's bounded request queue is at capacity.

    Raised synchronously by :meth:`DynamicBatcher.submit` — the request
    was never enqueued. Callers should shed load or retry with backoff;
    an unbounded queue here would turn overload into latency collapse
    and eventually host OOM."""


class TenantShed(QueueFull):
    """SLO-driven admission shed this tenant's request: the tenant's
    own declared objectives are in multi-window burn-rate breach
    (``SLOTracker.breached()``) and the tenant is not protected.

    A subclass of :class:`QueueFull` so generic backoff handlers treat
    it as shed load; raised synchronously at ``submit`` (the request is
    never enqueued) and set on already-queued futures the worker drops
    while the breach is active. Only the breached tenant is shed —
    co-hosted tenants keep serving (pinned by
    tests/test_serving_tenancy.py)."""


class RequestTimeout(MXNetError, TimeoutError):
    """The request's deadline passed before it reached the device.

    Set as the future's exception by the batcher worker when a queued
    request expires (``timeout_ms``). Also a ``TimeoutError`` so generic
    timeout handling catches it."""


class ServerClosed(MXNetError):
    """The batcher has been shut down and accepts no new requests."""


class RequestAbandoned(MXNetError):
    """A streaming decode request ended before its token budget: the
    client cancelled mid-stream (``DecodeRequest.cancel()``), a
    ``serving.decode_abandon`` fault fired, or the engine shut down
    without drain while the sequence was active.

    The slot is retired at the next step boundary and the future
    resolves with THIS error — it never hangs — while the tokens
    emitted before abandonment stay readable via
    ``DecodeRequest.tokens()`` (a disconnect wastes at most one step
    of device work, never a hung slot)."""


class WorkerCrashed(MXNetError):
    """An unexpected exception escaped the batcher worker while this
    request was in flight.

    Before the supervision loop, an escaped exception silently killed
    the worker thread and every queued future hung forever; now the
    implicated requests fail with THIS error (carrying the original
    exception as ``__cause__``), the tenant's
    ``serving.<i>.worker_restarts`` counter increments, and the worker
    restarts to serve the rest of the queue. Retrying the request is
    safe — it never (completely) launched."""
