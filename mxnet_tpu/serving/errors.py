"""Serving-path error types.

Overload must degrade, not OOM: each failure mode a caller can react
to gets its own exception class so client code (and the demo servers)
can distinguish "back off and retry" (:class:`QueueFull`) from "this
request died" (:class:`RequestTimeout`) from "stop sending"
(:class:`ServerClosed`).
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["QueueFull", "RequestTimeout", "ServerClosed"]


class QueueFull(MXNetError):
    """Backpressure: the batcher's bounded request queue is at capacity.

    Raised synchronously by :meth:`DynamicBatcher.submit` — the request
    was never enqueued. Callers should shed load or retry with backoff;
    an unbounded queue here would turn overload into latency collapse
    and eventually host OOM."""


class RequestTimeout(MXNetError, TimeoutError):
    """The request's deadline passed before it reached the device.

    Set as the future's exception by the batcher worker when a queued
    request expires (``timeout_ms``). Also a ``TimeoutError`` so generic
    timeout handling catches it."""


class ServerClosed(MXNetError):
    """The batcher has been shut down and accepts no new requests."""
