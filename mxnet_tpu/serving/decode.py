"""Continuous-batching decode engine: slot-structured step-wise serving
for sequence models.

The Predictor/DynamicBatcher stack serves *one-shot* fixed-shape
requests; an autoregressive LM is served as a *decode loop* — per-step
launches over a batch in which sequences join and retire mid-flight.
:class:`DecodeEngine` is that serving shape, built from three
disciplines the stack already proved:

* **bucketed-by-length prefill** — the prompt runs through one program
  per power-of-two length bucket (the Predictor bucket-ladder idiom:
  pad up, mask, slice back). A per-row length mask makes padding a
  pure ``where`` select, so the bucketed prefill is BITWISE equal to a
  whole-sequence forward at the exact length (:meth:`prefill_parity`);
  oversized prompts chunk through the top bucket carrying slot state.
* **slot-structured decode state** — the recurrent state (the RNN
  h/c, a transformer's KV rows) lives as ONE device-resident,
  slot-indexed pytree. Prefill writes rows with a jitted
  ``state.at[idx].set(rows, mode="drop")`` scatter and resumed chunks
  read them back with a gather — the ``(B,)`` int32-index discipline
  of ``data.ShardedCachedDataset``. The per-step transfer is the
  ``(slots,)`` token/mask vectors; the state NEVER round-trips to the
  host.
* **continuous batching** — between steps the scheduler admits queued
  sequences into free slots and retires finished ones, then launches
  ONE fixed-shape decode program regardless of occupancy. Inactive
  rows are carried through an active-mask ``where``, so occupancy
  churn never changes a program shape and never retraces
  (``CompileWatch`` counts stay frozen after :meth:`warmup`). Because
  rows are computed independently and masking is an exact select, the
  token stream of a request decoded at occupancy N is bitwise equal
  to the same request decoded alone — the property the
  ``dryrun_decode`` gate pins while showing aggregate tokens/sec
  strictly above the sequential baseline.

Per-sequence SLOs ride the existing judgment layer: time-to-first-token
and per-token latency are :class:`~mxnet_tpu.telemetry.SLOTracker`
objectives (``slo.<name>.ttft.*`` / ``slo.<name>.per_token.*`` gauges);
``shed_on_breach=True`` turns a TTFT breach into admission shed
(:class:`TenantShed`) at submit. Request traces use the decode phase
set (queue-wait / prefill / decode / resolve,
:data:`~mxnet_tpu.serving.stats.DECODE_TRACE_PHASES`) in the shared
request-trace ring, and counters publish under a ``decode.<i>.*``
registry scope.

The prefill/step/state-init program family is cacheable through the
PR-11 persistent executable cache: ``warmup(cache_dir=...)`` AOT
compiles + commits entries keyed by (params digest, precision mode,
bucket, input signature, backend); a second replica deserializes every
program with ZERO XLA compiles and serves bitwise-identical streams.
The engine runs under a named :class:`~mxnet_tpu.precision
.PrecisionPolicy` (the mode name is part of every cache key).

Fault seams (armed via :mod:`mxnet_tpu.faults`):
``serving.decode_worker`` (check — scheduler loop; a crash restarts the
loop, slots and device state survive), ``serving.decode_step`` (check —
per-step launch; ``delay`` = device slowdown), and
``serving.decode_abandon`` (fires — a mid-stream client abandon: the
oldest active request retires with :class:`RequestAbandoned`).

Quick start::

    from mxnet_tpu.serving.decode import DecodeEngine, LSTMCharLM

    model = LSTMCharLM(vocab_size=32, num_hidden=32, num_embed=16)
    eng = DecodeEngine(model, model.init_params(seed=0), slots=4)
    eng.warmup()                       # compile the program family
    reqs = [eng.submit(prompt, max_new_tokens=16) for prompt in prompts]
    streams = [r.result(timeout=60) for r in reqs]
    eng.shutdown(drain=True)

Env knobs: ``MXNET_SERVE_DECODE_SLOTS`` (default slot count),
``MXNET_SERVE_DECODE_MAX_STEPS`` (per-request generation cap),
``MXNET_SERVE_DECODE_TTFT_SLO_MS`` / ``MXNET_SERVE_DECODE_TOKEN_SLO_MS``
(default SLO objectives) — docs/how_to/env_var.md.
"""
from __future__ import annotations

import collections
import hashlib
import logging
import os
import threading
import time

import numpy as onp

from .. import faults as _faults
from .. import telemetry
from ..base import MXNetError
from ..precision import resolve as _resolve_precision
from .errors import (QueueFull, RequestAbandoned, RequestTimeout,
                     ServerClosed, TenantShed, WorkerCrashed)
from .stats import DECODE_TRACE_PHASES, ServingStats

__all__ = ["DecodeModel", "LSTMCharLM", "DecodeRequest", "DecodeEngine"]

logger = logging.getLogger("mxnet_tpu.serving")

# prefill programs run a fixed tiny row batch: row 0 is the admitted
# request, the rest are masked padding (lengths 0, slot index = slots →
# the scatter drops them). Starting at 2 keeps the matmuls off the
# batch-1 gemv lowering the Predictor ladder documents as the one
# shape whose codegen can differ bitwise.
PREFILL_ROWS = 2


def _env_int(name, default):
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# model interface
# ---------------------------------------------------------------------------
class DecodeModel(object):
    """A pure-functional autoregressive model the engine can serve.

    Subclasses define ``vocab_size``, :meth:`state_struct` (the
    per-sequence recurrent-state rows) and :meth:`step` (one token of
    batched forward math, row-independent). :meth:`prefill` — a
    length-masked ``lax.scan`` over :meth:`step` — comes for free and
    is what makes padded prefill bitwise: padded positions update
    state through an exact ``where`` select, and each row's logits are
    captured at its own final real position.
    """

    vocab_size = None

    def state_struct(self):
        """``{name: (per_row_shape, dtype_str)}`` for the recurrent
        state — the engine allocates each leaf as ``(slots,) + shape``."""
        raise NotImplementedError

    def step(self, params, tokens, state):
        """One decode step: ``(params, (B,) int32 tokens, state rows)
        -> (new state rows, (B, vocab) logits)``. Must be row-wise
        independent (row r's outputs depend only on row r's inputs)."""
        raise NotImplementedError

    def signature(self):
        """Canonical config string — the executable-cache input
        signature component."""
        raise NotImplementedError

    def params_digest(self, params):
        """Content digest of (config, param names, param bytes) — the
        executable-cache identity; two processes holding bitwise-equal
        params agree on it."""
        h = hashlib.sha256(self.signature().encode())
        for k in sorted(params):
            h.update(k.encode())
            h.update(onp.ascontiguousarray(onp.asarray(params[k])).tobytes())
        return h.hexdigest()

    def prefill(self, params, tokens, lengths, state0):
        """Whole-prompt forward: ``tokens (B, L) int32``, per-row real
        ``lengths (B,) int32``, initial state rows ``state0``. Returns
        ``(state rows at each row's position length-1, logits at that
        position)``. Positions ``t >= lengths[b]`` are exact no-ops for
        row ``b``."""
        import jax
        import jax.numpy as jnp
        B, L = tokens.shape
        logits0 = jnp.zeros((B, int(self.vocab_size)), jnp.float32)

        def body(carry, xs):
            state, logits = carry
            t, tok = xs
            new_state, new_logits = self.step(params, tok, state)
            keep = t < lengths
            state = jax.tree_util.tree_map(
                lambda n, o: jnp.where(
                    keep.reshape((B,) + (1,) * (n.ndim - 1)), n, o),
                new_state, state)
            logits = jnp.where((t == lengths - 1)[:, None],
                               new_logits.astype(logits.dtype), logits)
            return (state, logits), None

        (state, logits), _ = jax.lax.scan(
            body, (state0, logits0),
            (jnp.arange(L, dtype=jnp.int32), jnp.transpose(tokens)))
        return state, logits


class LSTMCharLM(DecodeModel):
    """The `example/rnn` char-LM as a functional decode model.

    The step math mirrors :class:`mxnet_tpu.rnn.LSTMCell` exactly
    (gate order [i, f, g, o], ``FullyConnected`` = ``x @ W.T + b``),
    so :meth:`from_params` adopts parameters trained through
    ``Module.fit`` on the unfused ``lstm_l<i>_`` symbol graph
    (``example/rnn/decode_lm.py``) verbatim: ``embed_weight``,
    ``lstm_l<i>_{i2h,h2h}_{weight,bias}``, ``pred_{weight,bias}``.
    """

    def __init__(self, vocab_size, num_hidden=64, num_embed=32,
                 num_layers=1):
        self.vocab_size = int(vocab_size)
        self.num_hidden = int(num_hidden)
        self.num_embed = int(num_embed)
        self.num_layers = int(num_layers)

    def signature(self):
        return ("lstm_char_lm:vocab=%d;embed=%d;hidden=%d;layers=%d"
                % (self.vocab_size, self.num_embed, self.num_hidden,
                   self.num_layers))

    def state_struct(self):
        shape = (self.num_layers, self.num_hidden)
        return {"h": (shape, "float32"), "c": (shape, "float32")}

    def param_shapes(self):
        """``{name: shape}`` of the full parameter set (init +
        from_params validation)."""
        V, E, H = self.vocab_size, self.num_embed, self.num_hidden
        shapes = {"embed_weight": (V, E),
                  "pred_weight": (V, H), "pred_bias": (V,)}
        for l in range(self.num_layers):
            in_dim = E if l == 0 else H
            shapes["lstm_l%d_i2h_weight" % l] = (4 * H, in_dim)
            shapes["lstm_l%d_i2h_bias" % l] = (4 * H,)
            shapes["lstm_l%d_h2h_weight" % l] = (4 * H, H)
            shapes["lstm_l%d_h2h_bias" % l] = (4 * H,)
        return shapes

    def init_params(self, seed=0, scale=0.1):
        """Deterministic random parameters (tests / dryruns that need
        no training)."""
        rng = onp.random.RandomState(int(seed))
        return {k: (rng.rand(*s) * 2 - 1).astype(onp.float32) * scale
                for k, s in sorted(self.param_shapes().items())}

    @classmethod
    def from_params(cls, params, num_layers=None):
        """Adopt a fit-trained parameter dict (numpy or NDArray
        values) from the unfused char-LM graph; the config is inferred
        from the shapes."""
        arrs = {k: (v.asnumpy() if hasattr(v, "asnumpy") else
                    onp.asarray(v))
                for k, v in params.items()}
        if num_layers is None:
            num_layers = len([k for k in arrs
                              if k.endswith("_i2h_weight")])
        V, E = arrs["embed_weight"].shape
        H = arrs["lstm_l0_h2h_weight"].shape[1]
        model = cls(V, num_hidden=H, num_embed=E, num_layers=num_layers)
        want = model.param_shapes()
        got = {k: tuple(v.shape) for k, v in arrs.items()
               if k in want}
        bad = [k for k in want if got.get(k) != want[k]]
        if bad:
            raise MXNetError(
                "LSTMCharLM.from_params: missing/mismatched params %s "
                "(want %s)" % (bad, {k: want[k] for k in bad}))
        model._adopted = {k: arrs[k] for k in want}
        return model

    def step(self, params, tokens, state):
        import jax
        import jax.numpy as jnp
        x = jnp.take(params["embed_weight"], tokens, axis=0)
        h_all, c_all = state["h"], state["c"]
        hs, cs = [], []
        for l in range(self.num_layers):
            gates = (x @ params["lstm_l%d_i2h_weight" % l].T
                     + params["lstm_l%d_i2h_bias" % l]
                     + h_all[:, l] @ params["lstm_l%d_h2h_weight" % l].T
                     + params["lstm_l%d_h2h_bias" % l])
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = (jax.nn.sigmoid(f) * c_all[:, l]
                 + jax.nn.sigmoid(i) * jnp.tanh(g))
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            hs.append(h)
            cs.append(c)
            x = h
        logits = x @ params["pred_weight"].T + params["pred_bias"]
        return ({"h": jnp.stack(hs, axis=1), "c": jnp.stack(cs, axis=1)},
                logits)


class TransformerLM(DecodeModel):
    """The `example/transformer-lm` causal decoder as a functional
    decode model (the scenario matrix's transformer serving customer).

    Recurrent state is the sliding token window of the training
    length: each step writes the incoming token at its row's position
    (shifting left once the window fills) and re-runs the full causal
    forward over the window — the identical math the training symbol
    graph computes (``FullyConnected`` = ``x @ W.T + b``, softmax over
    ``scores + causal_mask``), so :meth:`from_params` adopts
    fit-trained parameters (``embed_weight``, ``pos_embed``,
    ``blk<i>_{att_{q,k,v,o},mlp_{fc1,fc2}}_{weight,bias}``,
    ``head_{weight,bias}``) verbatim.  The ``causal_mask`` constant is
    synthesized internally (``triu(-1e9)``, the LMInit rule), never
    read from the checkpoint — a mask must not ride the weight-quant
    path.  Positions beyond a row's real length hold zeros; the causal
    mask keeps them out of every attended position, so the garbage is
    unreachable.
    """

    def __init__(self, vocab_size, num_embed, num_heads, window,
                 num_blocks):
        self.vocab_size = int(vocab_size)
        self.num_embed = int(num_embed)
        self.num_heads = int(num_heads)
        self.window = int(window)
        self.num_blocks = int(num_blocks)
        if self.num_embed % self.num_heads:
            raise MXNetError(
                "TransformerLM: num_embed %d not divisible by "
                "num_heads %d" % (self.num_embed, self.num_heads))
        self._mask = onp.triu(
            onp.full((self.window, self.window), -1e9, onp.float32),
            k=1)

    def signature(self):
        return ("transformer_lm:vocab=%d;embed=%d;heads=%d;window=%d;"
                "blocks=%d" % (self.vocab_size, self.num_embed,
                               self.num_heads, self.window,
                               self.num_blocks))

    def state_struct(self):
        return {"ctx": ((self.window,), "int32"),
                "len": ((), "int32")}

    def param_shapes(self):
        V, D, T = self.vocab_size, self.num_embed, self.window
        shapes = {"embed_weight": (V, D), "pos_embed": (1, T, D),
                  "head_weight": (V, D), "head_bias": (V,)}
        for i in range(self.num_blocks):
            for p in ("att_q", "att_k", "att_v", "att_o"):
                shapes["blk%d_%s_weight" % (i, p)] = (D, D)
                shapes["blk%d_%s_bias" % (i, p)] = (D,)
            shapes["blk%d_mlp_fc1_weight" % i] = (4 * D, D)
            shapes["blk%d_mlp_fc1_bias" % i] = (4 * D,)
            shapes["blk%d_mlp_fc2_weight" % i] = (D, 4 * D)
            shapes["blk%d_mlp_fc2_bias" % i] = (D,)
        return shapes

    def init_params(self, seed=0, scale=0.1):
        """Deterministic random parameters (tests that need no
        training)."""
        rng = onp.random.RandomState(int(seed))
        return {k: (rng.rand(*s) * 2 - 1).astype(onp.float32) * scale
                for k, s in sorted(self.param_shapes().items())}

    @classmethod
    def from_params(cls, params, num_heads):
        """Adopt a fit-trained parameter dict (numpy or NDArray
        values) from the transformer-lm symbol graph; everything but
        the head count is inferred from the shapes."""
        arrs = {k: (v.asnumpy() if hasattr(v, "asnumpy") else
                    onp.asarray(v))
                for k, v in params.items()}
        V, D = arrs["embed_weight"].shape
        T = arrs["pos_embed"].shape[1]
        blocks = len([k for k in arrs
                      if k.startswith("blk") and
                      k.endswith("_att_q_weight")])
        model = cls(V, num_embed=D, num_heads=num_heads, window=T,
                    num_blocks=blocks)
        want = model.param_shapes()
        got = {k: tuple(v.shape) for k, v in arrs.items() if k in want}
        bad = [k for k in want if got.get(k) != want[k]]
        if bad:
            raise MXNetError(
                "TransformerLM.from_params: missing/mismatched params "
                "%s (want %s)" % (bad, {k: want[k] for k in bad}))
        model._adopted = {k: arrs[k] for k in want}
        return model

    def _block(self, jnp, params, x, i):
        """One decoder block over the window: causal multi-head
        attention + MLP, both residual — mirrors the training graph's
        ``attention()``/``mlp()`` builders shape for shape."""
        B, T, D = x.shape
        H = self.num_heads
        DH = D // H

        def proj(name, inp):
            return inp @ params["blk%d_%s_weight" % (i, name)].T \
                + params["blk%d_%s_bias" % (i, name)]

        def heads(p):
            # (B, T, D) -> (B, H, T, DH)
            return jnp.transpose(p.reshape(B, T, H, DH), (0, 2, 1, 3))

        q, k, v = (heads(proj(n, x))
                   for n in ("att_q", "att_k", "att_v"))
        scores = (q @ jnp.swapaxes(k, -1, -2)) \
            * onp.float32(DH ** -0.5)
        scores = scores + jnp.asarray(self._mask)[None, None]
        att = jax_softmax(jnp, scores)
        ctx = att @ v                               # (B, H, T, DH)
        ctx = jnp.transpose(ctx, (0, 2, 1, 3)).reshape(B, T, D)
        x = x + proj("att_o", ctx)
        h = x @ params["blk%d_mlp_fc1_weight" % i].T \
            + params["blk%d_mlp_fc1_bias" % i]
        h = jnp.maximum(h, 0.0)
        return x + (h @ params["blk%d_mlp_fc2_weight" % i].T
                    + params["blk%d_mlp_fc2_bias" % i])

    def step(self, params, tokens, state):
        import jax.numpy as jnp
        T = self.window
        ctx, ln = state["ctx"], state["len"]        # (B, T), (B,)
        B = ctx.shape[0]
        full = ln >= T
        # window full: slide left one and write at T-1; else append
        ctx = jnp.where(full[:, None], jnp.roll(ctx, -1, axis=1), ctx)
        pos = jnp.where(full, T - 1, ln).astype(jnp.int32)
        ctx = ctx.at[jnp.arange(B), pos].set(
            tokens.astype(jnp.int32))
        x = jnp.take(params["embed_weight"], ctx, axis=0) \
            + params["pos_embed"][0]
        for i in range(self.num_blocks):
            x = self._block(jnp, params, x, i)
        h = x[jnp.arange(B), pos]                   # (B, D)
        logits = h @ params["head_weight"].T + params["head_bias"]
        return ({"ctx": ctx,
                 "len": jnp.minimum(ln + 1, T).astype(jnp.int32)},
                logits)


def jax_softmax(jnp, scores):
    """Max-subtracted softmax over the last axis — the same lowering
    ``mx.sym.softmax`` compiles to, kept as one shared helper so the
    decode model and any future functional graph agree bit for bit."""
    z = scores - scores.max(axis=-1, keepdims=True)
    e = jnp.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


# ---------------------------------------------------------------------------
# request future
# ---------------------------------------------------------------------------
class DecodeRequest(object):
    """One submitted sequence: a future over its generated token
    stream. Thread-safe; resolved exactly once (tokens or an
    exception) — engine shutdown and abandonment both resolve it, a
    future never hangs."""

    def __init__(self, req_id, prompt, max_new_tokens, seed,
                 timeout_ms=None):
        self.id = req_id
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.seed = int(seed) & 0xFFFFFFFF
        self.timeout_ms = (None if timeout_ms is None
                           else float(timeout_ms))
        self._lock = threading.Lock()
        self._emitted = []
        self._done = threading.Event()
        self._exc = None
        self._cancel = False
        self.outcome = None   # "ok" | "abandoned" | "error" | "timeout"
        self.slot = None
        self.bucket = None          # top prefill length bucket used
        self.t_submit = time.time()
        self.deadline = (None if self.timeout_ms is None
                         else self.t_submit + self.timeout_ms / 1000.0)
        self.t_admit = None
        self.t_first = None         # first token emitted (TTFT point)
        self.t_done = None

    # -- engine side ----------------------------------------------------
    def _append(self, tok):
        with self._lock:
            self._emitted.append(int(tok))

    def _resolve(self, outcome, exc=None):
        with self._lock:
            if self._done.is_set():
                return
            self.outcome = outcome
            self._exc = exc
        self._done.set()

    # -- client side ----------------------------------------------------
    def tokens(self):
        """The tokens emitted so far (a snapshot — readable while the
        request streams, and after abandonment)."""
        with self._lock:
            return list(self._emitted)

    def cancel(self):
        """Client abandons the stream: the engine retires the slot at
        the next step boundary and the future resolves with
        :class:`RequestAbandoned`."""
        self._cancel = True

    def done(self):
        return self._done.is_set()

    @property
    def ttft_ms(self):
        if self.t_first is None:
            return None
        return (self.t_first - self.t_submit) * 1000.0

    def result(self, timeout=None):
        """Block for the full stream. Raises the resolution error
        (:class:`RequestAbandoned`, :class:`WorkerCrashed`,
        :class:`ServerClosed`) if the request did not complete."""
        if not self._done.wait(timeout):
            raise TimeoutError("decode request %s still streaming "
                               "after %.1fs" % (self.id, timeout or 0))
        if self._exc is not None:
            raise self._exc
        return self.tokens()


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------
class DecodeEngine(object):
    """Continuous-batching decode scheduler over one slot-structured
    device state (module docstring).

    Parameters
    ----------
    model : DecodeModel
    params : dict
        Host parameters (numpy / NDArray values). Placed on device
        once, cast per the precision policy; never re-staged per step.
    slots : int
        Concurrent sequences (``MXNET_SERVE_DECODE_SLOTS`` default).
    max_prefill_len : int
        Top of the power-of-two prefill length-bucket ladder; longer
        prompts chunk through the top bucket carrying slot state.
    temperature : float
        0.0 = greedy argmax (the bitwise-gate path); > 0 samples via a
        deterministic counter-hash gumbel keyed by (request seed,
        step) — same request, same stream, at any occupancy.
    eos_id : int or None
        Token id that retires a sequence early.
    precision : str / PrecisionPolicy / None
        Named precision mode (``mxnet_tpu.precision.resolve``); the
        mode name keys every cache entry.
    ttft_slo_ms / token_slo_ms : float
        p95 objectives for the two SLO trackers (env defaults
        ``MXNET_SERVE_DECODE_TTFT_SLO_MS`` /
        ``MXNET_SERVE_DECODE_TOKEN_SLO_MS``; 0 disables that tracker).
    shed_on_breach : bool
        Shed new submits (:class:`TenantShed`) while the TTFT
        objective is in multi-window burn-rate breach.
    start : bool
        Spawn the scheduler thread now; ``start=False`` lets tests
        queue a full arrival transcript first (deterministic
        join/retire order), then call :meth:`start`.
    """

    def __init__(self, model, params, slots=None, max_prefill_len=32,
                 temperature=0.0, eos_id=None, precision=None,
                 max_queue=256, ttft_slo_ms=None, token_slo_ms=None,
                 shed_on_breach=False, name="decode", start=True,
                 seed=0):
        import jax
        import jax.numpy as jnp
        self._model = model
        self._name = str(name)
        self._slots = int(slots if slots is not None else
                          _env_int("MXNET_SERVE_DECODE_SLOTS", 8))
        if self._slots < 1:
            raise MXNetError("DecodeEngine needs slots >= 1")
        self._max_steps = _env_int("MXNET_SERVE_DECODE_MAX_STEPS", 256)
        self._temperature = float(temperature)
        self._eos_id = None if eos_id is None else int(eos_id)
        # resolve(None) = the implicit f32 baseline (returns None);
        # the engine always runs under a NAMED policy — the mode name
        # keys every executable-cache entry
        self._policy = _resolve_precision(precision) \
            or _resolve_precision("f32")
        self._seed = int(seed)
        self._max_queue = int(max_queue)
        self._shed_on_breach = bool(shed_on_breach)
        self._max_restarts = _env_int(
            "MXNET_SERVE_MAX_WORKER_RESTARTS", 100)

        if getattr(model, "_adopted", None) is not None and params is None:
            params = model._adopted
        host = {k: (v.asnumpy() if hasattr(v, "asnumpy")
                    else onp.asarray(v))
                for k, v in params.items()}
        self._digest = model.params_digest(host)
        cdt = jnp.dtype(self._policy.compute_dtype or "float32")
        self._compute_dtype = cdt
        self._weight_quant = getattr(self._policy, "weight_quant", None)
        if self._weight_quant == "int8":
            # weight-only int8 (precision.quant): params live on device
            # as per-channel int8 + f32 scales; the step program
            # dequantizes IN-PROGRAM, so its arguments — re-read every
            # token on the memory-bound decode path — shrink ~4x
            # (step_argument_bytes is the witness)
            from ..precision import quant as _quant
            self._dparams = {
                k: jax.device_put(
                    jnp.asarray(v).astype(cdt)
                    if (not _quant.is_quantized(v)
                        and onp.issubdtype(v.dtype, onp.floating))
                    else v)
                for k, v in _quant.quantize_params(host).items()}
        else:
            self._dparams = {
                k: jax.device_put(
                    jnp.asarray(v).astype(cdt)
                    if onp.issubdtype(v.dtype, onp.floating)
                    else jnp.asarray(v))
                for k, v in host.items()}

        # power-of-two length-bucket ladder (Predictor idiom)
        top = max(4, int(max_prefill_len))
        b, buckets = 4, []
        while True:
            buckets.append(b)
            if b >= top:
                break
            b *= 2
        self._buckets = buckets

        self._stats = ServingStats(
            scope=telemetry.registry().unique_scope("decode"),
            phases=DECODE_TRACE_PHASES)
        self._g_occupancy = self._stats.scope.gauge("occupancy")
        self._c_steps = self._stats.scope.counter("steps")
        self._c_tokens = self._stats.scope.counter("tokens")
        self._c_prefills = self._stats.scope.counter("prefill_launches")
        self._c_abandoned = self._stats.scope.counter("abandoned")
        self._h_ttft = self._stats.scope.histogram("ttft_ms")

        from ..telemetry.slo import SLOTracker
        if ttft_slo_ms is None:
            ttft_slo_ms = _env_float(
                "MXNET_SERVE_DECODE_TTFT_SLO_MS", 500.0)
        if token_slo_ms is None:
            token_slo_ms = _env_float(
                "MXNET_SERVE_DECODE_TOKEN_SLO_MS", 100.0)
        self.slo_ttft = (SLOTracker(name="%s.ttft" % self._name,
                                    p95_ms=float(ttft_slo_ms))
                         if ttft_slo_ms else None)
        self.slo_token = (SLOTracker(name="%s.per_token" % self._name,
                                     p95_ms=float(token_slo_ms))
                          if token_slo_ms else None)

        # slot tables (touched only by the scheduler thread)
        n = self._slots
        self._slot_req = [None] * n
        self._active = onp.zeros((n,), onp.bool_)
        self._cur_tok = onp.zeros((n,), onp.int32)
        self._steps_in = onp.zeros((n,), onp.int32)
        self._seeds = onp.zeros((n,), onp.uint32)

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue = collections.deque()
        self._closed = False
        self._drain = True
        self._restarts = 0
        self._n_steps = 0
        self._n_tokens = 0
        self._occ_sum = 0.0
        self._busy_s = 0.0
        self._ttft_ring = collections.deque(maxlen=4096)
        self._transcript = []
        self._warmed = False
        self._warmup_report = {}
        self._thread = None

        self._build_programs()
        if start:
            self.start()

    # -- program family --------------------------------------------------
    def _count_trace(self, site, **shapes):
        """Runs INSIDE each traced body — exactly once per XLA trace
        (the Predictor._instrument discipline): the serving compile
        counter plus the process CompileWatch streams (warmup vs
        steady attribution, post-warmup retrace warnings)."""
        self._stats.note_compile()
        telemetry.compile_watch().note_trace("decode.%s" % site, shapes)

    def _state_zeros(self, batch):
        import jax.numpy as jnp
        out = {}
        for k, (shape, dt) in sorted(self._model.state_struct().items()):
            dt = jnp.dtype(dt)
            if jnp.issubdtype(dt, jnp.floating):
                dt = self._compute_dtype
            out[k] = jnp.zeros((batch,) + tuple(shape), dt)
        return out

    def _select(self, logits, steps, seeds):
        """Next-token rule, shared by prefill (first token) and decode
        step — greedy argmax, or a deterministic counter-hash gumbel
        keyed by (seed, step) when temperature > 0. uint32 arithmetic
        only (x64 stays off)."""
        import jax.numpy as jnp
        if self._temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        V = logits.shape[-1]
        ctr = (seeds[:, None].astype(jnp.uint32)
               ^ (steps[:, None].astype(jnp.uint32)
                  * jnp.uint32(0x9E3779B9)))
        ctr = ctr + jnp.arange(V, dtype=jnp.uint32)[None, :] \
            * jnp.uint32(0x85EBCA77)
        x = ctr
        for mult in (0x7FEB352D, 0x846CA68B):
            x = x ^ (x >> jnp.uint32(16))
            x = x * jnp.uint32(mult)
        x = x ^ (x >> jnp.uint32(16))
        u = (x >> jnp.uint32(8)).astype(jnp.float32) \
            * onp.float32(1.0 / (1 << 24))
        u = jnp.clip(u, 1e-7, 1.0 - 1e-7)
        g = -jnp.log(-jnp.log(u))
        scaled = logits.astype(jnp.float32) \
            / onp.float32(self._temperature)
        return jnp.argmax(scaled + g, axis=-1).astype(jnp.int32)

    def _dense_params(self, params):
        """The dense param view a program body consumes: in-program
        per-channel dequant under weight-only int8 (the executable's
        ARGUMENTS stay int8 — that is the bytes win), identity
        otherwise.  Bitwise-deterministic per (q, s), so quantized
        decode streams and the prefill-parity reference agree exactly."""
        if self._weight_quant != "int8":
            return params
        import jax.numpy as jnp
        from ..precision import quant as _quant
        return _quant.dequant_params(jnp, params, self._compute_dtype)

    def _build_programs(self):
        import jax
        import jax.numpy as jnp
        model, slots, pb = self._model, self._slots, PREFILL_ROWS
        tree = jax.tree_util.tree_map
        dense = self._dense_params

        def init_fn():
            self._count_trace("state_init", slots=(slots,))
            return self._state_zeros(slots)

        def step_fn(params, state, tokens, active, steps, seeds):
            self._count_trace("step", tokens=(slots,))
            rows, logits = model.step(dense(params), tokens, state)
            nxt = self._select(logits, steps, seeds)
            bmask = lambda ref: active.reshape(  # noqa: E731
                (slots,) + (1,) * (ref.ndim - 1))
            state = tree(lambda n, o: jnp.where(bmask(n), n, o),
                         rows, state)
            nxt = jnp.where(active, nxt, tokens)
            return state, nxt

        def make_prefill(L):
            def prefill_fn(params, state, tokens, lengths, idx,
                           resume, seeds):
                self._count_trace("prefill_%d" % L, tokens=(pb, L))
                clip = jnp.clip(idx, 0, slots - 1)
                rows0 = tree(
                    lambda s: jnp.where(
                        resume.reshape((pb,) + (1,) * (s.ndim - 1)),
                        jnp.take(s, clip, axis=0),
                        jnp.zeros((pb,) + s.shape[1:], s.dtype)),
                    state)
                rows, logits = model.prefill(dense(params), tokens,
                                             lengths, rows0)
                # OOB index == slots → dropped: the padding rows (and
                # non-final chunks of co-padded rows) never land
                state = tree(
                    lambda s, r: s.at[idx].set(r.astype(s.dtype),
                                               mode="drop"),
                    state, rows)
                first = self._select(
                    logits, jnp.zeros((pb,), jnp.int32), seeds)
                return state, logits, first
            return prefill_fn

        self._init_jit = jax.jit(init_fn)
        self._step_jit = jax.jit(step_fn)
        self._prefill_jits = {L: jax.jit(make_prefill(L))
                              for L in self._buckets}
        self._init_exec = None
        self._step_exec = None
        self._prefill_execs = {}
        self._ref_jits = {}
        self._state = None

    # -- launches --------------------------------------------------------
    def _launch_init(self):
        fn = self._init_exec or self._init_jit
        return fn()

    def _launch_step(self, state, tokens, active, steps, seeds):
        fn = self._step_exec or self._step_jit
        return fn(self._dparams, state, tokens, active, steps, seeds)

    def _launch_prefill(self, L, state, tokens, lengths, idx, resume,
                        seeds):
        fn = self._prefill_execs.get(L) or self._prefill_jits[L]
        return fn(self._dparams, state, tokens, lengths, idx, resume,
                  seeds)

    # -- bucket ladder ---------------------------------------------------
    @property
    def buckets(self):
        return list(self._buckets)

    @property
    def slots(self):
        return self._slots

    @property
    def params_digest(self):
        return self._digest

    def bucket_for(self, n):
        """Smallest length bucket that fits ``n`` prompt tokens (the
        top bucket for oversized prompts — those chunk)."""
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    # -- weight-bytes accounting (the memory-bound decode roofline) ------
    def weight_bytes(self):
        """Stored bytes of the device-resident param tree — what the
        decode step re-reads per token.  Under ``int8_weight`` this is
        the int8 payloads + f32 scale vectors (~4x under the f32
        tree)."""
        import jax
        return int(sum(x.size * onp.dtype(x.dtype).itemsize
                       for x in jax.tree_util.tree_leaves(
                           self._dparams)))

    def step_argument_bytes(self):
        """``analyze_compiled`` argument bytes of the decode STEP
        program — the byte witness the quant mode must shrink (the
        arguments are dominated by the weights every token re-reads).
        Uses the warmed executable when present, else an AOT compile
        outside the retrace counters."""
        from ..telemetry import analyze_compiled
        compiled = self._step_exec
        if compiled is None:
            with telemetry.compile_watch().suppressed():
                for name, _b, jit_fn, args, _i in self._program_specs():
                    if name == "step":
                        compiled = jit_fn.lower(*args).compile()
                        break
        return int(analyze_compiled(compiled).get("argument_bytes", 0))

    # -- warmup / executable cache --------------------------------------
    def _program_specs(self):
        """(name, bucket, jit, abstract_args, install) for the whole
        cacheable decode program family."""
        import jax
        tree = jax.tree_util.tree_map
        sds = lambda t: tree(  # noqa: E731
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t)
        p_s = sds(self._dparams)
        state_s = sds(self._state_zeros(self._slots))
        n, pb = self._slots, PREFILL_ROWS
        i32 = onp.dtype("int32")
        specs = [
            ("state_init", 0, self._init_jit, (),
             lambda c: setattr(self, "_init_exec", c)),
            ("step", 1, self._step_jit,
             (p_s, state_s,
              jax.ShapeDtypeStruct((n,), i32),
              jax.ShapeDtypeStruct((n,), onp.dtype("bool")),
              jax.ShapeDtypeStruct((n,), i32),
              jax.ShapeDtypeStruct((n,), onp.dtype("uint32"))),
             lambda c: setattr(self, "_step_exec", c)),
        ]
        for L in self._buckets:
            specs.append((
                "prefill_%d" % L, L, self._prefill_jits[L],
                (p_s, state_s,
                 jax.ShapeDtypeStruct((pb, L), i32),
                 jax.ShapeDtypeStruct((pb,), i32),
                 jax.ShapeDtypeStruct((pb,), i32),
                 jax.ShapeDtypeStruct((pb,), onp.dtype("bool")),
                 jax.ShapeDtypeStruct((pb,), onp.dtype("uint32"))),
                (lambda c, _L=L:
                 self._prefill_execs.__setitem__(_L, c))))
        return specs

    def _program_key(self, name, bucket):
        import jax
        from . import cache as _cache
        dev = jax.devices()[0]
        backend = _cache.backend_signature(
            mesh_axes=None, n_dev=1,
            device_kind=getattr(dev, "device_kind", ""),
            platform=jax.default_backend())
        input_sig = ("decode.%s:model=%s;slots=%d;pb=%d;temp=%g"
                     % (name, self._model.signature(), self._slots,
                        PREFILL_ROWS, self._temperature))
        if self._weight_quant:
            # quantized storage changes the program's argument layout
            # (int8 payloads + scale vectors): the quant scheme rides
            # the input signature so a wide replica can never adopt a
            # narrow executable (belt to the precision-mode suspender)
            input_sig += ";wq=%s" % self._weight_quant
        return _cache.cache_key(self._digest, self._policy.name,
                                bucket, input_sig, backend)

    def warmup(self, cache_dir=None):
        """AOT-compile (or deserialize) the full program family —
        state init, every prefill bucket, the decode step — BEFORE
        traffic; afterwards steady-state serving performs zero XLA
        compiles regardless of slot join/retire churn
        (``stats()['compiles']`` stays frozen, ``CompileWatch`` counts
        nothing post-warmup).

        ``cache_dir`` activates the persistent executable cache with
        the Predictor key discipline — (params digest, precision mode,
        bucket, input signature, backend) — extended to the decode
        family via per-program input signatures. A warm replica
        deserializes every program with zero compiles and serves
        bitwise-identical token streams (the ``dryrun_decode`` gate).
        Defaults to ``$MXNET_COMPILE_CACHE_DIR/aot`` when set."""
        from . import cache as _cache
        if cache_dir is None:
            root = os.environ.get("MXNET_COMPILE_CACHE_DIR")
            cache_dir = os.path.join(root, "aot") if root else None
        else:
            cache_dir = os.path.join(str(cache_dir), "aot")
        store = _cache.ExecutableCache(cache_dir) if cache_dir else None
        watch = telemetry.compile_watch()
        report = {}
        with watch.warmup_scope():
            for name, bucket, jit_fn, args, install in \
                    self._program_specs():
                t0 = time.perf_counter()
                source = self._warm_program(
                    name, bucket, jit_fn, args, install, store, watch)
                ms = (time.perf_counter() - t0) * 1000.0
                self._stats.note_warmup_bucket(
                    bucket, ms, source if store else None)
                report[name] = {"warmup_ms": round(ms, 3),
                                "source": source}
            if self._state is None:
                self._state = self._launch_init()
        self._warmed = True
        self._warmup_report = report
        return report

    def _warm_program(self, name, bucket, jit_fn, abstract_args,
                      install, store, watch):
        """Load-or-compile one program (the Predictor ``_warm_bucket``
        discipline): deserialize the crc-verified entry, else AOT
        compile and commit it; either way the compiled executable is
        INSTALLED so the request path never touches a jit wrapper."""
        from . import cache as _cache
        key = self._program_key(name, bucket)
        loaded, source = None, "compiled"
        if store is not None:
            try:
                payload, in_tree, out_tree = store.load(key)
                from jax.experimental import serialize_executable as _se
                loaded = _se.deserialize_and_load(payload, in_tree,
                                                  out_tree)
                source = "deserialized"
            except _cache.CacheMiss as e:
                log = logger.info if e.reason == "absent" \
                    else logger.warning
                log("decode program %s: executable cache %s — falling "
                    "back to a fresh compile (%s)",
                    name, e.reason, getattr(e, "detail", "") or "")
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "decode program %s: cached executable failed to "
                    "deserialize (%s) — falling back to a fresh "
                    "compile", name, e)
        if loaded is None:
            compiled = jit_fn.lower(*abstract_args).compile()
            if store is not None:
                try:
                    from jax.experimental import \
                        serialize_executable as _se
                    payload, in_tree, out_tree = _se.serialize(compiled)
                    store.store(key, payload, in_tree, out_tree)
                except Exception as e:  # noqa: BLE001 - best-effort
                    logger.warning(
                        "decode program %s: could not persist the "
                        "compiled executable (%s) — the next replica "
                        "will recompile", name, e)
            loaded = compiled
        install(loaded)
        if store is not None:
            if source == "deserialized":
                watch.note_cache_hit()
            else:
                watch.note_cache_miss()
        return source if store else "jit"

    def warmup_report(self):
        """Per-program outcome of the last :meth:`warmup` —
        ``{name: {"warmup_ms", "source"}}`` with source
        ``"deserialized"`` / ``"compiled"`` / ``"jit"``."""
        return {k: dict(v) for k, v in self._warmup_report.items()}

    # -- prefill parity ---------------------------------------------------
    def prefill_parity(self, prompt):
        """Bitwise witness for the bucket ladder: the padded-bucket
        prefill's final-position logits for ``prompt`` equal a
        reference whole-sequence forward at the EXACT length (no
        padding, no masking in effect). Uses scratch state — never
        touches live slots. Returns True on bitwise equality."""
        import jax
        import jax.numpy as jnp
        prompt = [int(t) for t in prompt]
        watch = telemetry.compile_watch()
        with watch.suppressed():
            scratch = self._launch_init()
            _, _, logits = self._run_prefill_chunks(
                scratch, 0, prompt, 0)
            L = len(prompt)
            ref_jit = self._ref_jits.get(L)
            if ref_jit is None:
                model, pb = self._model, PREFILL_ROWS

                def ref_fn(params, tokens, lengths):
                    rows0 = self._state_zeros(pb)
                    _, lg = model.prefill(self._dense_params(params),
                                          tokens, lengths, rows0)
                    return lg
                ref_jit = self._ref_jits[L] = jax.jit(ref_fn)
            toks = onp.zeros((PREFILL_ROWS, L), onp.int32)
            toks[0, :] = prompt
            lengths = onp.array([L, 0], onp.int32)
            ref = ref_jit(self._dparams, jnp.asarray(toks),
                          jnp.asarray(lengths))
        return bool(onp.array_equal(onp.asarray(ref)[0],
                                    onp.asarray(logits)[0]))

    # -- submission -------------------------------------------------------
    def submit(self, prompt, max_new_tokens=32, seed=0,
               timeout_ms=None):
        """Queue one sequence; returns its :class:`DecodeRequest`
        future. ``max_new_tokens`` is clamped to
        ``MXNET_SERVE_DECODE_MAX_STEPS``. Raises :class:`ServerClosed`
        after shutdown, :class:`QueueFull` at capacity, and
        :class:`TenantShed` when ``shed_on_breach`` and the TTFT
        objective is in breach.

        ``timeout_ms`` is a per-request admission deadline (the
        ``DynamicBatcher.submit(timeout_ms=)`` contract, applied to
        the TTFT phase): a request still queued past its deadline
        fails its future with :class:`RequestTimeout` instead of
        prefilling, and the miss lands in the TTFT SLO tracker as a
        timeout — how the gateway propagates a client's
        ``X-Deadline-Ms`` into the decode plane."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise MXNetError("decode prompt must be non-empty")
        if any(t < 0 or t >= self._model.vocab_size for t in prompt):
            raise MXNetError("prompt token out of range [0, %d)"
                             % self._model.vocab_size)
        if self._closed:
            raise ServerClosed("decode engine is shut down")
        if (self._shed_on_breach and self.slo_ttft is not None
                and self.slo_ttft.breached_cached()):
            self._stats.note_shed()
            self.slo_ttft.record(outcome="reject")
            raise TenantShed(
                "decode TTFT objective in multi-window breach — "
                "request shed at admission")
        with self._cond:
            if self._closed:
                raise ServerClosed("decode engine is shut down")
            if len(self._queue) >= self._max_queue:
                self._stats.note_reject()
                if self.slo_ttft is not None:
                    self.slo_ttft.record(outcome="reject")
                raise QueueFull("decode queue at capacity (%d)"
                                % self._max_queue)
            req = DecodeRequest(
                self._stats.new_request_id(), prompt,
                min(int(max_new_tokens), self._max_steps), seed,
                timeout_ms=timeout_ms)
            self._queue.append(req)
            self._stats.note_request()
            self._cond.notify_all()
        return req

    def generate(self, prompt, max_new_tokens=32, seed=0, timeout=None):
        """Blocking convenience: :meth:`submit` + ``result()``."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           seed=seed).result(timeout=timeout)

    # -- scheduler --------------------------------------------------------
    def start(self):
        """Start the scheduler thread (no-op when running)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._thread = threading.Thread(
            target=self._loop, name="mxtpu-decode", daemon=True)
        self._thread.start()
        return self

    def _any_active(self):
        return bool(self._active.any())

    def _loop(self):
        while True:
            with self._cond:
                while (not self._closed and not self._queue
                       and not self._any_active()
                       and not any(r is not None and r._cancel
                                   for r in self._slot_req)):
                    self._cond.wait(0.05)
                no_drain = self._closed and not self._drain
                done = (self._closed and not self._queue
                        and not self._any_active())
            if no_drain:
                self._fail_pending(ServerClosed(
                    "decode engine shut down without drain"))
                return
            if done:
                return
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 - supervised loop
                if not self._on_crash(e):
                    return

    def _tick(self):
        if self._state is None:
            # lazy so an un-warmed engine still works; after warmup()
            # this ran from the installed state_init executable already
            self._state = self._launch_init()
        if _faults.armed():
            _faults.check("serving.decode_worker", step=self._n_steps)
        self._admit_pending()
        if _faults.armed() and _faults.fires("serving.decode_abandon",
                                             step=self._n_steps):
            self._abandon_oldest()
        for s in range(self._slots):
            req = self._slot_req[s]
            if req is not None and req._cancel:
                self._retire(s, "abandoned", RequestAbandoned(
                    "decode request %s cancelled by the client after "
                    "%d tokens" % (req.id, len(req.tokens()))))
        if not self._any_active():
            return
        if _faults.armed():
            _faults.check("serving.decode_step", step=self._n_steps)
        t0 = time.perf_counter()
        n_active = int(self._active.sum())
        state, nxt = self._launch_step(
            self._state, self._cur_tok, self._active, self._steps_in,
            self._seeds)
        nxt_host = onp.asarray(nxt)
        self._state = state
        dt = time.perf_counter() - t0
        self._busy_s += dt
        self._n_steps += 1
        self._c_steps.add()
        self._occ_sum += n_active / float(self._slots)
        self._g_occupancy.set(round(n_active / float(self._slots), 4))
        self._stats.note_batch(self._slots, n_active)
        self._cur_tok = nxt_host.astype(onp.int32)
        for s in range(self._slots):
            if not self._active[s]:
                continue
            self._steps_in[s] += 1
            self._emit(s, int(nxt_host[s]))

    def _admit_pending(self):
        while True:
            with self._cond:
                if not self._queue:
                    return
                free = [s for s in range(self._slots)
                        if self._slot_req[s] is None]
                if not free:
                    return
                req = self._queue.popleft()
            if req._cancel:
                req._resolve("abandoned", RequestAbandoned(
                    "decode request %s cancelled while queued"
                    % req.id))
                self._c_abandoned.add()
                continue
            if req.deadline is not None and time.time() > req.deadline:
                age_ms = (time.time() - req.t_submit) * 1000.0
                req._resolve("timeout", RequestTimeout(
                    "decode request %s expired after %.0f ms in queue "
                    "(deadline %.0f ms)"
                    % (req.id, age_ms, req.timeout_ms)))
                self._stats.note_timeout(age_ms)
                if self.slo_ttft is not None:
                    self.slo_ttft.record(age_ms, "timeout")
                if telemetry.enabled():
                    self._stats.note_trace(
                        req.id, rows=1, bucket=0,
                        phases={"queue_wait_ms": age_ms,
                                "prefill_ms": 0.0, "decode_ms": 0.0,
                                "resolve_ms": 0.0},
                        outcome="timeout", ts_end=time.time())
                continue
            try:
                self._admit(free[0], req)
            except BaseException as e:
                req._resolve("error", WorkerCrashed(
                    "decode scheduler crashed while prefilling "
                    "request %s" % req.id))
                self._stats.note_error()
                raise

    def _admit(self, slot, req):
        req.t_admit = time.time()
        req.slot = slot
        self._state, first_tok, _ = self._run_prefill_chunks(
            self._state, slot, req.prompt, req.seed, req=req)
        self._slot_req[slot] = req
        self._active[slot] = True
        self._cur_tok[slot] = first_tok
        self._steps_in[slot] = 1
        self._seeds[slot] = onp.uint32(req.seed)
        self._transcript.append(
            ("admit", req.id, slot, self._n_steps))
        req.t_first = time.time()
        ttft = req.ttft_ms
        self._ttft_ring.append(ttft)
        self._h_ttft.observe(ttft)
        if self.slo_ttft is not None:
            self.slo_ttft.record(ttft, "ok")
        self._emit(slot, first_tok)

    def _run_prefill_chunks(self, state, slot, prompt, seed, req=None):
        """Run one prompt through the bucket ladder into ``slot`` of
        ``state``: each chunk pads to its bucket, non-first chunks
        gather the slot row back (``resume``) so state is continuous;
        returns (state, first generated token, final-chunk logits)."""
        top = self._buckets[-1]
        pos, resume = 0, False
        first_tok, logits = 0, None
        pb = PREFILL_ROWS
        seeds = onp.zeros((pb,), onp.uint32)
        seeds[0] = onp.uint32(seed)
        while pos < len(prompt):
            chunk = prompt[pos:pos + top]
            L = self.bucket_for(len(chunk))
            toks = onp.zeros((pb, L), onp.int32)
            toks[0, :len(chunk)] = chunk
            lengths = onp.zeros((pb,), onp.int32)
            lengths[0] = len(chunk)
            idx = onp.full((pb,), self._slots, onp.int32)
            idx[0] = slot
            res = onp.zeros((pb,), onp.bool_)
            res[0] = resume
            state, logits, first = self._launch_prefill(
                L, state, toks, lengths, idx, res, seeds)
            self._c_prefills.add()
            self._stats.scope.counter(
                "prefill_bucket_hits.%d" % L).add()
            if req is not None:
                req.bucket = L
            pos += len(chunk)
            resume = True
            first_tok = int(onp.asarray(first)[0])
        return state, first_tok, logits

    def _emit(self, slot, tok):
        req = self._slot_req[slot]
        req._append(tok)
        self._n_tokens += 1
        self._c_tokens.add()
        if ((self._eos_id is not None and tok == self._eos_id)
                or len(req.tokens()) >= req.max_new_tokens):
            self._retire(slot, "ok")

    def _retire(self, slot, outcome, exc=None):
        req = self._slot_req[slot]
        req.t_done = time.time()
        n_tok = len(req.tokens())
        decode_ms = (req.t_done - req.t_first) * 1000.0 \
            if req.t_first else 0.0
        if outcome == "ok":
            self._stats.note_completed(
                (req.t_done - req.t_submit) * 1000.0)
            if self.slo_token is not None and n_tok > 1:
                self.slo_token.record(decode_ms / (n_tok - 1), "ok")
        elif outcome == "abandoned":
            self._c_abandoned.add()
            if self.slo_token is not None:
                self.slo_token.record(decode_ms or None, "error")
        else:
            self._stats.note_error()
            if self.slo_token is not None:
                self.slo_token.record(decode_ms or None, "error")
        if telemetry.enabled():
            qw = ((req.t_admit - req.t_submit) * 1000.0
                  if req.t_admit else 0.0)
            pf = ((req.t_first - req.t_admit) * 1000.0
                  if req.t_first and req.t_admit else 0.0)
            self._stats.note_trace(
                req.id, rows=1, bucket=req.bucket or 0,
                phases={"queue_wait_ms": qw, "prefill_ms": pf,
                        "decode_ms": decode_ms, "resolve_ms": 0.0},
                outcome=outcome, ts_end=req.t_done)
        self._transcript.append(
            ("retire", req.id, slot, n_tok, outcome, self._n_steps))
        self._slot_req[slot] = None
        self._active[slot] = False
        req._resolve(outcome, exc)
        with self._cond:
            self._cond.notify_all()

    def _abandon_oldest(self):
        """The ``serving.decode_abandon`` seam body: the oldest active
        request's client walks away mid-stream."""
        oldest, t = None, None
        for s in range(self._slots):
            req = self._slot_req[s]
            if req is not None and (t is None or req.t_admit < t):
                oldest, t = s, req.t_admit
        if oldest is not None:
            req = self._slot_req[oldest]
            self._retire(oldest, "abandoned", RequestAbandoned(
                "decode request %s abandoned mid-stream (injected "
                "client disconnect) after %d tokens"
                % (req.id, len(req.tokens()))))

    def _fail_pending(self, exc):
        """Resolve every queued + active request with ``exc`` (the
        no-drain shutdown / restart-storm path — futures never hang)."""
        with self._cond:
            queued = list(self._queue)
            self._queue.clear()
        for req in queued:
            req._resolve("error", exc)
            self._stats.note_error()
        for s in range(self._slots):
            if self._slot_req[s] is not None:
                self._retire(s, "error", exc)

    def _on_crash(self, e):
        """Supervised restart (the DynamicBatcher worker discipline).
        Unlike the one-shot batcher, in-flight decode sequences
        SURVIVE a scheduler crash — the slot state is device-resident
        and the loop resumes stepping it. Returns False when the
        restart budget is exhausted (everything failed loudly)."""
        self._restarts += 1
        self._stats.note_worker_restart()
        logger.warning(
            "decode scheduler crashed (restart %d/%d): %s — slot "
            "state is device-resident, in-flight sequences resume",
            self._restarts, self._max_restarts, e, exc_info=True)
        if self._restarts > self._max_restarts:
            crash = WorkerCrashed(
                "decode scheduler exceeded %d restarts"
                % self._max_restarts)
            crash.__cause__ = e
            with self._cond:
                self._closed = True
            self._fail_pending(crash)
            return False
        return True

    # -- lifecycle --------------------------------------------------------
    def shutdown(self, drain=True, timeout=None):
        """Stop the engine. ``drain=True`` finishes every queued and
        in-flight sequence first; ``drain=False`` resolves them all
        with :class:`ServerClosed` immediately. Either way no future
        is left hanging (pinned by tests/test_serving_decode.py)."""
        with self._cond:
            self._closed = True
            self._drain = bool(drain)
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        if not drain:
            # belt-and-braces for a never-started engine
            self._fail_pending(ServerClosed(
                "decode engine shut down without drain"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)
        return False

    def release(self):
        """Drop the ``decode.<i>`` registry scope (long-lived
        multi-tenant processes discarding an engine)."""
        self._stats.release()

    # -- reading ----------------------------------------------------------
    def transcript(self):
        """The slot lifecycle transcript — ``("admit", req_id, slot,
        step)`` and ``("retire", req_id, slot, n_tokens, outcome,
        step)`` tuples in order. With a fixed arrival transcript
        (``start=False``, submit, :meth:`start`) it is a pure function
        of (seed, arrival order) — the determinism contract."""
        return list(self._transcript)

    def request_traces(self):
        return self._stats.request_traces()

    def stats(self):
        """The ServingStats snapshot plus a ``decode`` section:
        steps, tokens, tokens_per_sec (over device-busy wall),
        avg_occupancy, TTFT percentiles, abandon count."""
        s = self._stats.snapshot()
        ttfts = sorted(self._ttft_ring)
        s["decode"] = {
            "slots": self._slots,
            "buckets": list(self._buckets),
            "steps": int(self._n_steps),
            "tokens": int(self._n_tokens),
            "tokens_per_sec": round(
                self._n_tokens / self._busy_s, 2)
            if self._busy_s > 0 else None,
            "avg_occupancy": round(
                self._occ_sum / self._n_steps, 4)
            if self._n_steps else None,
            "abandoned": int(self._c_abandoned.value),
            "ttft_ms": {
                "count": len(ttfts),
                "p50": ServingStats._pct(ttfts, 50),
                "p99": ServingStats._pct(ttfts, 99),
            },
            "precision_mode": self._policy.name,
            "weight_quant": self._weight_quant,
            "weight_bytes": self.weight_bytes(),
        }
        return s
