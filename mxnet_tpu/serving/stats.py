"""Serving observability: counters, gauges, latency percentiles, and
per-request phase traces.

One :class:`ServingStats` instance is shared by a ``Predictor`` and any
``DynamicBatcher`` built on it, so ``stats()`` is a single coherent
snapshot of the serving stack: request outcomes, device-launch batch
fill, queue depth, and the compile counter that pins the "zero
recompiles after warmup" contract.

Since the telemetry subsystem landed, ServingStats is a **view over
the shared** :class:`mxnet_tpu.telemetry.MetricsRegistry`: every
counter lives in a per-instance registry scope (``serving.<i>.*``), so
the process-wide Prometheus endpoint / JSONL flush sees serving
traffic without any extra wiring, while ``snapshot()`` keeps its exact
historical shape. The latency reservoir stays a local bounded ring of
the most recent samples (exact percentiles over current behavior);
each completion also lands in the scope's ``latency_ms`` histogram for
export.

Two additions from the judgment layer:

* **deadline misses are latency samples.** A request expired at launch
  time used to count only in the ``timeouts`` counter — its queue age
  never reached the reservoir, so reported p50/p95/p99 excluded
  exactly the worst outcomes and p99 *under-reported under overload*.
  ``note_timeout(age_ms)`` now folds the expired request's age into
  the reservoir and the ``latency_ms`` histogram (and a dedicated
  ``timeout_age_ms`` histogram), so the reported tail includes the
  requests that never made it.
* **request traces.** When telemetry is enabled, every request gets a
  stable id and a phase-decomposed trace — queue-wait, coalesce-wait,
  pad, device, resolve — kept in a bounded ring
  (:meth:`request_traces`), exported as Chrome-trace ``ph:X`` events
  into the span timeline, and aggregated into per-phase, per-bucket
  latency histograms (``serving.<i>.b<bucket>.phase_<name>_ms``) so a
  p99 blowup is attributable to queueing vs device time per bucket.
  Ring capacity rides ``MXNET_TELEMETRY_REQTRACE`` (0 disables).
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time

from .. import telemetry

__all__ = ["ServingStats"]

# request-trace phase names, in wall-clock order
TRACE_PHASES = ("queue_wait_ms", "coalesce_wait_ms", "pad_ms",
                "device_ms", "resolve_ms")

# the decode plane's phase decomposition (serving.decode): one request
# spans a queue wait, its bucketed prefill, the continuous-batched
# decode steps it was active for, and resolution
DECODE_TRACE_PHASES = ("queue_wait_ms", "prefill_ms", "decode_ms",
                       "resolve_ms")


class ServingStats:
    """Thread-safe serving counters over a telemetry-registry scope,
    with a bounded latency reservoir and a request-trace ring."""

    def __init__(self, latency_window=2048, scope=None,
                 trace_capacity=None, phases=None):
        self._phases = tuple(phases) if phases else TRACE_PHASES
        self._lock = threading.Lock()
        self._window = int(latency_window)
        self._lat = [0.0] * self._window
        self._lat_n = 0            # total samples ever (ring write head)
        self.scope = scope or telemetry.registry().unique_scope("serving")
        c = self.scope.counter
        self._c_requests = c("requests")   # submitted (batcher or direct)
        self._c_completed = c("completed")
        self._c_rejected = c("rejected")   # queue-full backpressure
        self._c_timeouts = c("timeouts")   # expired before launch
        self._c_errors = c("errors")
        self._c_batches = c("batches")     # device launches (excl. warmup)
        self._c_warmup_batches = c("warmup_batches")
        self._c_real_rows = c("real_rows")     # request rows served
        self._c_padded_rows = c("padded_rows")  # bucket rows launched
        self._c_compiles = c("compiles")   # XLA traces through serving
        # persistent-executable-cache warm start (serving.cache):
        # per-bucket hits (deserialized, zero XLA work) vs misses
        # (fresh compile — absent, drifted key, or corrupt entry)
        self._c_cache_hits = c("cache_hits")
        self._c_cache_misses = c("cache_misses")
        # SLO-driven admission: requests shed because the tenant's own
        # burn windows are in breach (distinct from queue-full rejects)
        self._c_sheds = c("sheds")
        # worker supervision: times the batcher worker loop was
        # restarted after an unexpected exception escaped it (the
        # implicated requests failed with WorkerCrashed, loudly)
        self._c_worker_restarts = c("worker_restarts")
        self._h_latency = self.scope.histogram("latency_ms")
        self._h_timeout_age = self.scope.histogram("timeout_age_ms")
        self._h_shed_age = self.scope.histogram("shed_age_ms")
        self._warmup_ms = {}       # bucket -> compile/deserialize ms
        self._g_queue = self.scope.gauge("queue_depth")
        self.compile_tracking = True
        self.bucket_hits = {}      # bucket size -> launch count
        self._queue_probe = None   # () -> current queue depth
        if trace_capacity is None:
            trace_capacity = int(
                os.environ.get("MXNET_TELEMETRY_REQTRACE", "512"))
        self._trace_capacity = int(trace_capacity)
        self._traces = collections.deque(
            maxlen=max(self._trace_capacity, 1))
        self._req_ids = itertools.count()
        self._phase_hists = {}     # (bucket, phase) -> Histogram

    # -- registry-backed counter values (internal + snapshot use) -------
    requests = telemetry.instrument_value("_c_requests")
    completed = telemetry.instrument_value("_c_completed")
    rejected = telemetry.instrument_value("_c_rejected")
    timeouts = telemetry.instrument_value("_c_timeouts")
    errors = telemetry.instrument_value("_c_errors")
    batches = telemetry.instrument_value("_c_batches")
    warmup_batches = telemetry.instrument_value("_c_warmup_batches")
    real_rows = telemetry.instrument_value("_c_real_rows")
    padded_rows = telemetry.instrument_value("_c_padded_rows")
    compiles = telemetry.instrument_value("_c_compiles")
    cache_hits = telemetry.instrument_value("_c_cache_hits")
    cache_misses = telemetry.instrument_value("_c_cache_misses")
    sheds = telemetry.instrument_value("_c_sheds")
    worker_restarts = telemetry.instrument_value("_c_worker_restarts")

    def release(self):
        """Drop this instance's ``serving.<i>`` scope from the shared
        registry (the counters keep working locally). Call when the
        owning Predictor is discarded in a long-lived process."""
        self.scope.release()

    # -- recorders (called by Predictor / DynamicBatcher) ---------------
    def note_compile(self):
        self._c_compiles.add()

    def note_request(self, n=1):
        self._c_requests.add(n)

    def note_reject(self):
        self._c_rejected.add()

    def _reserve(self, latency_ms):
        """One sample into the percentile reservoir + export histogram
        — THE one rule for what the reported tail covers (completions
        AND deadline misses)."""
        self._h_latency.observe(latency_ms)
        with self._lock:
            self._lat[self._lat_n % self._window] = latency_ms
            self._lat_n += 1

    def note_timeout(self, age_ms=None):
        """A request expired before launch. ``age_ms`` (its time in
        queue) folds the miss into the latency reservoir/histogram —
        reported p99 must reflect the requests that never made it —
        plus the dedicated ``timeout_age_ms`` histogram."""
        self._c_timeouts.add()
        if age_ms is not None:
            age_ms = float(age_ms)
            self._h_timeout_age.observe(age_ms)
            self._reserve(age_ms)

    def note_error(self):
        self._c_errors.add()

    def note_shed(self, age_ms=None):
        """A request shed by SLO-driven admission (the tenant's own
        burn windows in breach). A worker-side shed passes the queue
        age — like a deadline miss it is a worst outcome the client
        experienced, so it folds into the latency reservoir/histogram
        (plus the dedicated ``shed_age_ms`` histogram); a submit-time
        reject passes None (the request never waited)."""
        self._c_sheds.add()
        if age_ms is not None:
            age_ms = float(age_ms)
            self._h_shed_age.observe(age_ms)
            self._reserve(age_ms)

    def note_worker_restart(self):
        """The batcher worker crashed on this tenant's work and was
        restarted (`serving.<i>.worker_restarts`)."""
        self._c_worker_restarts.add()

    def note_warmup_bucket(self, bucket, ms, source=None):
        """One bucket's warmup wall time (compile OR deserialize) into
        the ``b<bucket>.warmup_ms`` gauge; ``source`` tags the
        executable-cache outcome (``"deserialized"`` counts a cache
        hit, ``"compiled"`` a miss, None = cache not in play)."""
        ms = round(float(ms), 3)
        with self._lock:
            self._warmup_ms[int(bucket)] = ms
        self.scope.gauge("b%d.warmup_ms" % int(bucket)).set(ms)
        if source == "deserialized":
            self._c_cache_hits.add()
        elif source == "compiled":
            self._c_cache_misses.add()

    def note_batch(self, bucket, rows, warmup=False):
        if warmup:
            self._c_warmup_batches.add()
            return
        self._c_batches.add()
        self._c_real_rows.add(rows)
        self._c_padded_rows.add(bucket)
        with self._lock:
            self.bucket_hits[bucket] = self.bucket_hits.get(bucket, 0) + 1
        self.scope.counter("bucket_hits.%d" % bucket).add()

    def note_completed(self, latency_ms):
        latency_ms = float(latency_ms)
        self._c_completed.add()
        self._reserve(latency_ms)

    def set_queue_probe(self, fn):
        """Install a ``() -> int`` gauge for the current queue depth
        (the batcher points this at its deque)."""
        self._queue_probe = fn
        self._g_queue.set_fn(fn)

    # -- request traces --------------------------------------------------
    def new_request_id(self):
        """A stable per-instance request id (``r<seq>``) — stamped on
        every submitted request and carried by its trace."""
        return "r%08d" % next(self._req_ids)

    def _phase_hist(self, bucket, phase):
        key = (bucket, phase)
        h = self._phase_hists.get(key)
        if h is None:
            h = self._phase_hists[key] = self.scope.histogram(
                "b%d.phase_%s" % (bucket, phase))
        return h

    def note_trace(self, req_id, rows, bucket, phases, outcome="ok",
                   ts_end=None):
        """Record one request's phase-decomposed trace (callers gate on
        ``telemetry.enabled()`` — one branch when off). ``phases`` maps
        phase name (this instance's phase set — :data:`TRACE_PHASES`
        by default, :data:`DECODE_TRACE_PHASES` for a decode engine)
        to ms; missing phases are 0.
        The trace lands in the bounded ring, each phase in its
        per-bucket histogram, and (for served requests) as Chrome-trace
        ``ph:X`` events in the span timeline — ``profiler.dump_profile``
        renders the request next to the host spans."""
        if self._trace_capacity <= 0:
            return None
        ts_end = time.time() if ts_end is None else float(ts_end)
        phases = {p: round(float(phases.get(p, 0.0)), 3)
                  for p in self._phases}
        total = round(sum(phases.values()), 3)
        trace = {"id": str(req_id), "rows": int(rows),
                 "bucket": int(bucket) if bucket else None,
                 "outcome": str(outcome), "phases": phases,
                 "total_ms": total,
                 "ts": round(ts_end - total / 1000.0, 6)}
        with self._lock:
            self._traces.append(trace)
        if bucket:
            for p, ms in phases.items():
                if ms or p in ("queue_wait_ms", "device_ms",
                               "decode_ms"):
                    self._phase_hist(trace["bucket"], p).observe(ms)
        elif phases.get("queue_wait_ms"):
            # never-launched outcomes (timeout, admission shed) have no
            # bucket but DID wait — their queue time lands in a
            # bucket-free histogram so the decision stays attributable
            # in this scope's phase view
            self.scope.histogram("phase_queue_wait_ms").observe(
                phases["queue_wait_ms"])
        # phase events laid out back-to-back ending at ts_end: the
        # request renders as a contiguous bar decomposed by phase
        events, t_us = [], (ts_end - total / 1000.0) * 1e6
        tid = threading.get_ident()
        for p in self._phases:
            dur_us = phases[p] * 1e3
            if dur_us <= 0:
                continue
            events.append({
                "name": "serving.req.%s" % p[:-3], "cat": "serving",
                "ph": "X", "ts": t_us, "dur": dur_us, "pid": 0,
                "tid": tid,
                "args": {"id": trace["id"], "rows": trace["rows"],
                         "bucket": trace["bucket"],
                         "outcome": trace["outcome"]}})
            t_us += dur_us
        if events:
            telemetry.record_events(events)
        return trace

    def request_traces(self):
        """The retained request traces, oldest first."""
        with self._lock:
            return [dict(t) for t in self._traces]

    # -- snapshot -------------------------------------------------------
    @staticmethod
    def _pct(sorted_vals, p):
        if not sorted_vals:
            return None
        idx = min(len(sorted_vals) - 1,
                  max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
        return sorted_vals[idx]

    def snapshot(self):
        """One coherent dict of every counter/gauge/percentile — the
        ``stats()`` surface documented in docs/api/serving.md.
        ``latency_ms.count`` counts reservoir samples: completions plus
        deadline misses recorded with their queue age (so the
        percentiles cover the worst outcomes, not only the served
        ones)."""
        with self._lock:
            lat_total = self._lat_n
            n = min(lat_total, self._window)
            lats = sorted(self._lat[:n])
            bucket_hits = dict(self.bucket_hits)
            warmup_ms = dict(self._warmup_ms)
        real_rows, padded_rows = self.real_rows, self.padded_rows
        fill = (real_rows / float(padded_rows)) if padded_rows else None
        out = {
            "requests": self.requests,
            "completed": self.completed,
            "rejected": self.rejected,
            "timeouts": self.timeouts,
            "errors": self.errors,
            "batches": self.batches,
            "warmup_batches": self.warmup_batches,
            "batch_fill": round(fill, 4) if fill is not None else None,
            "compiles": self.compiles,
            "compile_tracking": self.compile_tracking,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "sheds": self.sheds,
            "worker_restarts": self.worker_restarts,
            "warmup_ms": warmup_ms,
            "bucket_hits": bucket_hits,
            "latency_ms": {
                "count": lat_total,
                "mean": round(sum(lats) / n, 3) if n else None,
                "p50": self._pct(lats, 50),
                "p95": self._pct(lats, 95),
                "p99": self._pct(lats, 99),
                "max": lats[-1] if lats else None,
            },
        }
        probe = self._queue_probe
        try:
            out["queue_depth"] = int(probe()) if probe is not None else 0
        except Exception:
            out["queue_depth"] = 0
        return out
