"""Serving observability: counters, gauges, and latency percentiles.

One :class:`ServingStats` instance is shared by a ``Predictor`` and any
``DynamicBatcher`` built on it, so ``stats()`` is a single coherent
snapshot of the serving stack: request outcomes, device-launch batch
fill, queue depth, and the compile counter that pins the "zero
recompiles after warmup" contract.

Everything is updated under one lock from multiple threads (client
threads submit, the batcher worker completes); the latency reservoir is
a bounded ring of the most recent samples, so percentiles track current
behavior instead of averaging over the process lifetime.
"""
from __future__ import annotations

import threading

__all__ = ["ServingStats"]


class ServingStats:
    """Thread-safe serving counters with a bounded latency reservoir."""

    def __init__(self, latency_window=2048):
        self._lock = threading.Lock()
        self._window = int(latency_window)
        self._lat = [0.0] * self._window
        self._lat_n = 0            # total samples ever (ring write head)
        self.requests = 0          # submitted (batcher or direct predict)
        self.completed = 0
        self.rejected = 0          # queue-full backpressure rejections
        self.timeouts = 0          # expired before launch
        self.errors = 0
        self.batches = 0           # device launches (excl. warmup)
        self.warmup_batches = 0
        self.real_rows = 0         # request rows actually served
        self.padded_rows = 0       # bucket rows launched (incl. padding)
        self.compiles = 0          # XLA traces through serving programs
        self.compile_tracking = True
        self.bucket_hits = {}      # bucket size -> launch count
        self._queue_probe = None   # () -> current queue depth

    # -- recorders (called by Predictor / DynamicBatcher) ---------------
    def note_compile(self):
        with self._lock:
            self.compiles += 1

    def note_request(self, n=1):
        with self._lock:
            self.requests += n

    def note_reject(self):
        with self._lock:
            self.rejected += 1

    def note_timeout(self):
        with self._lock:
            self.timeouts += 1

    def note_error(self):
        with self._lock:
            self.errors += 1

    def note_batch(self, bucket, rows, warmup=False):
        with self._lock:
            if warmup:
                self.warmup_batches += 1
                return
            self.batches += 1
            self.real_rows += rows
            self.padded_rows += bucket
            self.bucket_hits[bucket] = self.bucket_hits.get(bucket, 0) + 1

    def note_completed(self, latency_ms):
        with self._lock:
            self.completed += 1
            self._lat[self._lat_n % self._window] = float(latency_ms)
            self._lat_n += 1

    def set_queue_probe(self, fn):
        """Install a ``() -> int`` gauge for the current queue depth
        (the batcher points this at its deque)."""
        self._queue_probe = fn

    # -- snapshot -------------------------------------------------------
    @staticmethod
    def _pct(sorted_vals, p):
        if not sorted_vals:
            return None
        idx = min(len(sorted_vals) - 1,
                  max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
        return sorted_vals[idx]

    def snapshot(self):
        """One coherent dict of every counter/gauge/percentile — the
        ``stats()`` surface documented in docs/api/serving.md."""
        with self._lock:
            n = min(self._lat_n, self._window)
            lats = sorted(self._lat[:n])
            fill = (self.real_rows / float(self.padded_rows)
                    if self.padded_rows else None)
            out = {
                "requests": self.requests,
                "completed": self.completed,
                "rejected": self.rejected,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "batches": self.batches,
                "warmup_batches": self.warmup_batches,
                "batch_fill": round(fill, 4) if fill is not None else None,
                "compiles": self.compiles,
                "compile_tracking": self.compile_tracking,
                "bucket_hits": dict(self.bucket_hits),
                "latency_ms": {
                    "count": self.completed,
                    "mean": round(sum(lats) / n, 3) if n else None,
                    "p50": self._pct(lats, 50),
                    "p95": self._pct(lats, 95),
                    "p99": self._pct(lats, 99),
                    "max": lats[-1] if lats else None,
                },
            }
        probe = self._queue_probe
        try:
            out["queue_depth"] = int(probe()) if probe is not None else 0
        except Exception:
            out["queue_depth"] = 0
        return out
