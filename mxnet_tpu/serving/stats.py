"""Serving observability: counters, gauges, and latency percentiles.

One :class:`ServingStats` instance is shared by a ``Predictor`` and any
``DynamicBatcher`` built on it, so ``stats()`` is a single coherent
snapshot of the serving stack: request outcomes, device-launch batch
fill, queue depth, and the compile counter that pins the "zero
recompiles after warmup" contract.

Since the telemetry subsystem landed, ServingStats is a **view over
the shared** :class:`mxnet_tpu.telemetry.MetricsRegistry`: every
counter lives in a per-instance registry scope (``serving.<i>.*``), so
the process-wide Prometheus endpoint / JSONL flush sees serving
traffic without any extra wiring, while ``snapshot()`` keeps its exact
historical shape. The latency reservoir stays a local bounded ring of
the most recent samples (exact percentiles over current behavior);
each completion also lands in the scope's ``latency_ms`` histogram for
export.
"""
from __future__ import annotations

import threading

from .. import telemetry

__all__ = ["ServingStats"]


class ServingStats:
    """Thread-safe serving counters over a telemetry-registry scope,
    with a bounded latency reservoir."""

    def __init__(self, latency_window=2048, scope=None):
        self._lock = threading.Lock()
        self._window = int(latency_window)
        self._lat = [0.0] * self._window
        self._lat_n = 0            # total samples ever (ring write head)
        self.scope = scope or telemetry.registry().unique_scope("serving")
        c = self.scope.counter
        self._c_requests = c("requests")   # submitted (batcher or direct)
        self._c_completed = c("completed")
        self._c_rejected = c("rejected")   # queue-full backpressure
        self._c_timeouts = c("timeouts")   # expired before launch
        self._c_errors = c("errors")
        self._c_batches = c("batches")     # device launches (excl. warmup)
        self._c_warmup_batches = c("warmup_batches")
        self._c_real_rows = c("real_rows")     # request rows served
        self._c_padded_rows = c("padded_rows")  # bucket rows launched
        self._c_compiles = c("compiles")   # XLA traces through serving
        self._h_latency = self.scope.histogram("latency_ms")
        self._g_queue = self.scope.gauge("queue_depth")
        self.compile_tracking = True
        self.bucket_hits = {}      # bucket size -> launch count
        self._queue_probe = None   # () -> current queue depth

    # -- registry-backed counter values (internal + snapshot use) -------
    requests = telemetry.instrument_value("_c_requests")
    completed = telemetry.instrument_value("_c_completed")
    rejected = telemetry.instrument_value("_c_rejected")
    timeouts = telemetry.instrument_value("_c_timeouts")
    errors = telemetry.instrument_value("_c_errors")
    batches = telemetry.instrument_value("_c_batches")
    warmup_batches = telemetry.instrument_value("_c_warmup_batches")
    real_rows = telemetry.instrument_value("_c_real_rows")
    padded_rows = telemetry.instrument_value("_c_padded_rows")
    compiles = telemetry.instrument_value("_c_compiles")

    def release(self):
        """Drop this instance's ``serving.<i>`` scope from the shared
        registry (the counters keep working locally). Call when the
        owning Predictor is discarded in a long-lived process."""
        self.scope.release()

    # -- recorders (called by Predictor / DynamicBatcher) ---------------
    def note_compile(self):
        self._c_compiles.add()

    def note_request(self, n=1):
        self._c_requests.add(n)

    def note_reject(self):
        self._c_rejected.add()

    def note_timeout(self):
        self._c_timeouts.add()

    def note_error(self):
        self._c_errors.add()

    def note_batch(self, bucket, rows, warmup=False):
        if warmup:
            self._c_warmup_batches.add()
            return
        self._c_batches.add()
        self._c_real_rows.add(rows)
        self._c_padded_rows.add(bucket)
        with self._lock:
            self.bucket_hits[bucket] = self.bucket_hits.get(bucket, 0) + 1
        self.scope.counter("bucket_hits.%d" % bucket).add()

    def note_completed(self, latency_ms):
        latency_ms = float(latency_ms)
        self._c_completed.add()
        self._h_latency.observe(latency_ms)
        with self._lock:
            self._lat[self._lat_n % self._window] = latency_ms
            self._lat_n += 1

    def set_queue_probe(self, fn):
        """Install a ``() -> int`` gauge for the current queue depth
        (the batcher points this at its deque)."""
        self._queue_probe = fn
        self._g_queue.set_fn(fn)

    # -- snapshot -------------------------------------------------------
    @staticmethod
    def _pct(sorted_vals, p):
        if not sorted_vals:
            return None
        idx = min(len(sorted_vals) - 1,
                  max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
        return sorted_vals[idx]

    def snapshot(self):
        """One coherent dict of every counter/gauge/percentile — the
        ``stats()`` surface documented in docs/api/serving.md."""
        with self._lock:
            n = min(self._lat_n, self._window)
            lats = sorted(self._lat[:n])
            bucket_hits = dict(self.bucket_hits)
        completed = self.completed
        real_rows, padded_rows = self.real_rows, self.padded_rows
        fill = (real_rows / float(padded_rows)) if padded_rows else None
        out = {
            "requests": self.requests,
            "completed": completed,
            "rejected": self.rejected,
            "timeouts": self.timeouts,
            "errors": self.errors,
            "batches": self.batches,
            "warmup_batches": self.warmup_batches,
            "batch_fill": round(fill, 4) if fill is not None else None,
            "compiles": self.compiles,
            "compile_tracking": self.compile_tracking,
            "bucket_hits": bucket_hits,
            "latency_ms": {
                "count": completed,
                "mean": round(sum(lats) / n, 3) if n else None,
                "p50": self._pct(lats, 50),
                "p95": self._pct(lats, 95),
                "p99": self._pct(lats, 99),
                "max": lats[-1] if lats else None,
            },
        }
        probe = self._queue_probe
        try:
            out["queue_depth"] = int(probe()) if probe is not None else 0
        except Exception:
            out["queue_depth"] = 0
        return out
