"""mxnet_tpu.serving — online inference: dynamic batching, a
shape-bucketed compiled-program cache, and backpressure.

The serving half of the production stack (training half: the fused
mesh Module + durable checkpoints). Three pieces:

* :class:`Predictor` — binds a trained/loaded Module for inference
  behind a compiled-program cache keyed by padded batch-size buckets;
  ``warmup()`` pre-compiles every bucket so steady-state traffic never
  triggers an XLA compile, and served rows are bitwise identical to
  ``Module.predict``.
* :class:`DynamicBatcher` — bounded request queue + background worker
  that coalesces concurrent requests into one bucket-padded launch
  within a ``max_wait_ms`` window; queue-full rejection, per-request
  timeouts, graceful shutdown.
* :class:`ServingStats` — one snapshot (``stats()``) of latency
  p50/p95/p99 (deadline-missed requests included, by their queue age),
  batch-fill ratio, queue depth, and compile counters; with telemetry
  enabled it also retains per-request phase-decomposed traces
  (``request_traces()`` — queue-wait / coalesce / pad / device /
  resolve, exported as per-bucket histograms and Chrome-trace events).

Judged by the telemetry layer: ``DynamicBatcher(slo=SLOTracker(...))``
evaluates declared latency/error/availability objectives over
multi-window burn rates (docs/api/telemetry.md "Serving SLOs").

Quick start::

    from mxnet_tpu.serving import Predictor, DynamicBatcher

    pred = Predictor(trained_module, max_batch_size=64)   # or
    # pred = Predictor.load("ckpt_dir", data_shapes=[("data", (1, 3, 28, 28))])
    pred.warmup()                      # compile every bucket pre-traffic
    with DynamicBatcher(pred, max_queue=256, max_wait_ms=2) as srv:
        fut = srv.submit(x)            # from any number of threads
        probs = fut.result()
    print(pred.stats())

See docs/api/serving.md for semantics and field reference.
"""
from __future__ import annotations

from .batcher import DynamicBatcher
from .errors import QueueFull, RequestTimeout, ServerClosed
from .predictor import Predictor
from .stats import ServingStats

__all__ = ["Predictor", "DynamicBatcher", "ServingStats",
           "QueueFull", "RequestTimeout", "ServerClosed"]
