"""mxnet_tpu.serving — online inference: dynamic batching, a
shape-bucketed compiled-program cache, and backpressure.

The serving half of the production stack (training half: the fused
mesh Module + durable checkpoints). Three pieces:

* :class:`Predictor` — binds a trained/loaded Module for inference
  behind a compiled-program cache keyed by padded batch-size buckets;
  ``warmup()`` pre-compiles every bucket so steady-state traffic never
  triggers an XLA compile, and served rows are bitwise identical to
  ``Module.predict``.
* :class:`DynamicBatcher` — bounded request queue + background worker
  that coalesces concurrent requests into one bucket-padded launch
  within a ``max_wait_ms`` window; queue-full rejection, per-request
  timeouts, graceful shutdown. Hosts several named :class:`Tenant`
  models behind one queue (multi-model tenancy / canary rollout) with
  SLO-driven admission: a tenant whose own burn windows breach is shed
  (:class:`TenantShed`) while co-hosted tenants keep serving.
* :mod:`~mxnet_tpu.serving.cache` — the persistent compile cache:
  ``Predictor.warmup(cache_dir=...)`` serializes each bucket's
  compiled program into an atomic, crc-verified entry keyed by
  (params digest, precision mode, bucket, backend); a second replica
  warming from the same directory deserializes every bucket with ZERO
  XLA compiles and bitwise-identical served rows.
  ``MXNET_COMPILE_CACHE_DIR`` wires jax's own persistent compilation
  cache process-wide and doubles as the default AOT entry store.
* :class:`DecodeEngine` (:mod:`~mxnet_tpu.serving.decode`) —
  continuous-batching step-wise serving for autoregressive sequence
  models: bucketed-by-length prefill programs, ONE device-resident
  slot-indexed decode state written/read by jitted scatter/gather, a
  scheduler that admits/retires sequences between steps under a fixed
  decode program shape (occupancy churn never retraces), per-sequence
  TTFT / per-token :class:`~mxnet_tpu.telemetry.SLOTracker` objectives
  — and token streams bitwise equal to unbatched decode at any
  occupancy.
* :class:`ServingStats` — one snapshot (``stats()``) of latency
  p50/p95/p99 (deadline-missed requests included, by their queue age),
  batch-fill ratio, queue depth, and compile counters; with telemetry
  enabled it also retains per-request phase-decomposed traces
  (``request_traces()`` — queue-wait / coalesce / pad / device /
  resolve, exported as per-bucket histograms and Chrome-trace events).

Judged by the telemetry layer: ``DynamicBatcher(slo=SLOTracker(...))``
evaluates declared latency/error/availability objectives over
multi-window burn rates (docs/api/telemetry.md "Serving SLOs").

Quick start::

    from mxnet_tpu.serving import Predictor, DynamicBatcher

    pred = Predictor(trained_module, max_batch_size=64)   # or
    # pred = Predictor.load("ckpt_dir", data_shapes=[("data", (1, 3, 28, 28))])
    pred.warmup()                      # compile every bucket pre-traffic
    with DynamicBatcher(pred, max_queue=256, max_wait_ms=2) as srv:
        fut = srv.submit(x)            # from any number of threads
        probs = fut.result()
    print(pred.stats())

See docs/api/serving.md for semantics and field reference.
"""
from __future__ import annotations

from . import cache
from .batcher import DynamicBatcher
from .cache import ExecutableCache, enable_persistent_compile_cache
from .decode import DecodeEngine, DecodeModel, DecodeRequest, LSTMCharLM
from .errors import (QueueFull, RequestAbandoned, RequestTimeout,
                     ServerClosed, TenantShed, WorkerCrashed)
from .predictor import Predictor
from .stats import ServingStats
from .tenancy import Tenant

__all__ = ["Predictor", "DynamicBatcher", "ServingStats", "Tenant",
           "DecodeEngine", "DecodeModel", "DecodeRequest", "LSTMCharLM",
           "ExecutableCache", "enable_persistent_compile_cache",
           "QueueFull", "RequestAbandoned", "RequestTimeout",
           "ServerClosed", "TenantShed", "WorkerCrashed"]

# process-wide persistent compilation cache: MXNET_COMPILE_CACHE_DIR
# points jax's own cache (and the default AOT entry store Predictor
# .warmup uses) at a shared directory — a new replica then warms by
# deserializing instead of recompiling (docs/api/serving.md
# "Persistent compile cache")
cache._autowire()
