"""Predictor — online inference over a trained Module with a
shape-bucketed compiled-program cache.

The reference's inference story is a blocking ``Module.predict`` loop
over a whole ``DataIter`` — fine for offline eval, useless for online
traffic: every new request shape would trace+compile a fresh XLA
program (seconds to minutes), and per-request launches at batch 1 waste
the device. The Predictor solves the compile half of that problem (the
``DynamicBatcher`` solves the utilization half):

* it binds one inference Module per **batch-size bucket** (powers of
  two up to ``max_batch_size`` by default), all sharing ONE set of
  device-resident parameter buffers through the existing
  ``shared_module`` path — on the fused mesh path that is the same
  ``MeshExecutorGroup`` staging machinery training uses, so a sharded
  (GSPMD/NamedSharding) module serves from the same mesh layout it
  trained on;
* a request of ``n`` rows is zero-padded up to the smallest bucket
  ``>= n`` and the outputs sliced back to ``n`` — steady-state traffic
  therefore only ever runs the pre-compiled bucket programs, never a
  new shape (``warmup()`` pre-compiles every bucket before traffic,
  and the compile counter in ``stats()`` pins "zero recompiles after
  warmup"). Padding is row-exact: an ``is_train=False`` forward is
  row-independent, so the served rows are bitwise identical to
  ``Module.predict`` on the same inputs (pinned by tests);
* requests larger than the top bucket are chunked across launches.

Parameters are snapshotted from the source module at construction
(``device_put`` of the same host values), so serving never races
training updates; rebuild the Predictor (or construct it from a
``CheckpointManager``) to pick up new weights.
"""
from __future__ import annotations

import logging
import os
import time

import numpy as onp

from .. import ndarray as nd
from ..base import MXNetError
from ..io import DataBatch
from ..module import Module
from ..module.base_module import pad_batch_rows  # shared pad rule
from .stats import ServingStats

__all__ = ["Predictor"]


class Predictor:
    """Bind a trained/loaded :class:`Module` for online inference.

    Parameters
    ----------
    module : Module
        Source of symbol + parameters. May be a live (bound) training
        module or an unbound ``Module.load`` result; its parameters are
        snapshotted — later training steps do not leak into serving.
    data_shapes : list of (name, shape), optional
        Input descriptors; the batch dimension is replaced per bucket.
        Defaults to the source module's bound ``data_shapes``.
    buckets : list of int, optional
        Explicit batch-size buckets. Each must be a positive multiple
        of the data-parallel factor (mesh ``dp`` axis, or the context
        count). Default: powers of two from ``dp`` up to
        ``max_batch_size``.
    max_batch_size : int
        Top bucket for the default power-of-two ladder (ignored when
        ``buckets`` is given). Larger requests are chunked.
    context : list of Context, optional
        Serving devices; defaults to the source module's contexts.
    calibration : CalibrationTable, optional
        Static per-site activation ranges (``precision.quant``) for a
        ``narrow_math`` policy: required by ``int8_serve`` (the int8
        activation scales must come from a calibration pass, not from
        in-program reductions); its digest keys the executable cache.
    """

    def __init__(self, module, data_shapes=None, buckets=None,
                 max_batch_size=32, context=None, logger=None,
                 latency_window=2048, calibration=None):
        if not isinstance(module, Module):
            raise MXNetError(
                "Predictor needs a plain Module (got %s); for wrapper "
                "modules serve the underlying Module"
                % type(module).__name__)
        self.logger = logger or logging.getLogger("mxnet_tpu.serving")
        self._stats = ServingStats(latency_window=latency_window)
        import threading
        self._lock = threading.RLock()

        # -- source introspection --------------------------------------
        symbol = module.symbol
        if module.binded and module.params_initialized:
            arg_params, aux_params = module.get_params()
        elif module.params_initialized and \
                getattr(module, "_arg_params", None) is not None:
            arg_params = module._arg_params
            aux_params = module._aux_params or {}
        else:
            raise MXNetError(
                "Predictor needs initialized parameters: bind+init the "
                "module, or load it from params files / a "
                "CheckpointManager first")
        # precision-mode gate (mxnet_tpu.precision): a checkpoint
        # trained under a mode (e.g. int8_act's quantized input seam)
        # served through a module bound under a DIFFERENT policy would
        # return silent garbage, not an error — refuse up front. The
        # recorded mode rides the checkpoint manifest; live modules
        # (never loaded from a manager entry) carry no recorded mode
        # and their own policy is authoritative.
        saved_mode = getattr(module, "_ckpt_precision_mode", None)
        live_mode = getattr(module, "precision_mode", "f32")
        if saved_mode is not None and saved_mode != live_mode:
            raise MXNetError(
                "refusing to serve: checkpoint was trained under "
                "precision mode %r but the module to bind runs %r — "
                "load with the matching precision= (or drop the "
                "override so the recorded mode is adopted)"
                % (saved_mode, live_mode))
        if data_shapes is None:
            if not module.binded:
                raise MXNetError(
                    "data_shapes is required when the source module is "
                    "not bound (e.g. a Module.load result)")
            data_shapes = module.data_shapes
        # structural identity for the persistent executable cache
        # (serving.cache): symbol + param shapes/dtypes, the SAME
        # digest rule checkpoint manifests record. A manager-restored
        # module carries the recorded digest — a disagreement means the
        # params were swapped after load, and adopting a cache entry
        # keyed on either digest could serve a stale executable.
        from ..checkpoint import pack_params, params_digest
        self._params_digest = params_digest(
            symbol.tojson(), pack_params(arg_params, aux_params))
        recorded = getattr(module, "_ckpt_params_digest", None)
        if recorded is not None and recorded != self._params_digest:
            raise MXNetError(
                "refusing to serve: the module's parameters no longer "
                "match the checkpoint manifest's recorded params digest "
                "(%s... != %s...) — the params were replaced after "
                "load; rebuild the module from its checkpoint"
                % (self._params_digest[:12], recorded[:12]))
        self._data_descs = [(name, tuple(shape))
                            for name, shape in data_shapes]
        contexts = list(context) if context is not None else \
            list(module._context)

        # -- bucket ladder ---------------------------------------------
        mesh_axes = module._mesh_axes
        dp = (mesh_axes or {}).get("dp", len(contexts))
        if buckets is None:
            # the ladder starts at 2 (not 1): XLA lowers a batch-1
            # matmul as a gemv with a different accumulation order, so
            # a 1-row bucket would break the bitwise-parity contract
            # with Module.predict; padding one zero row is free
            b, buckets = max(2, int(dp)), []
            while b <= max_batch_size:
                buckets.append(b)
                b *= 2
            if not buckets:
                raise MXNetError(
                    "max_batch_size=%d is smaller than the data-parallel "
                    "factor %d — no bucket fits" % (max_batch_size, dp))
        else:
            buckets = sorted({int(b) for b in buckets})
            if not buckets:
                raise MXNetError("buckets must not be empty")
            bad = [b for b in buckets if b <= 0 or b % dp]
            if bad:
                raise MXNetError(
                    "buckets %r must be positive multiples of the "
                    "data-parallel factor %d (mesh dp axis / context "
                    "count) so every bucket shards evenly" % (bad, dp))
            if buckets[0] == 1:
                raise MXNetError(
                    "a 1-row bucket breaks the bitwise-parity contract "
                    "(XLA's batch-1 gemv lowering accumulates in a "
                    "different order); use a minimum bucket of 2 — "
                    "padding the one extra row is free")
        self._buckets = buckets

        # -- one inference module per bucket, ONE set of param buffers -
        def _shapes_at(b):
            return [(name, (b,) + shape[1:])
                    for name, shape in self._data_descs]

        # serve under the source policy's EVAL-visible fields only: the
        # forward must see the same input casts (act_cast) and compute
        # dtype the training forward saw, but training-only levers —
        # remat, optimizer-state dtype, loss scaling — are stripped so
        # an inference-only bucket never builds a segmented-remat
        # evaluator or trips the fused-path requirement. The mode NAME
        # is kept for telemetry/roofline attribution.
        src_pol = getattr(module, "_precision", None)
        serve_pol = None
        if src_pol is not None:
            from ..precision import PrecisionPolicy
            narrow = getattr(src_pol, "narrow_math", None)
            table = calibration if calibration is not None \
                else getattr(src_pol, "calibration", None)
            if narrow == "int8" and table is None:
                raise MXNetError(
                    "precision mode %r needs a CalibrationTable "
                    "(static int8 activation scales): run "
                    "precision.quant.calibrate(...) and pass the "
                    "table via Predictor(calibration=...)"
                    % src_pol.name)
            serve_pol = PrecisionPolicy(
                name=src_pol.name, compute_dtype=src_pol.compute_dtype,
                act_cast=src_pol.act_cast,
                weight_quant=getattr(src_pol, "weight_quant", None),
                narrow_math=narrow, calibration=table,
                experimental=src_pol.experimental)
        elif calibration is not None:
            raise MXNetError(
                "Predictor(calibration=...) only applies to a module "
                "bound under a narrow_math precision mode (e.g. "
                "'int8_serve')")
        self._calibration = calibration if serve_pol is None \
            else serve_pol.calibration

        def _make(extra):
            return Module(symbol, data_names=module._data_names,
                          label_names=module._label_names,
                          logger=self.logger, context=contexts,
                          compute_dtype=module._compute_dtype,
                          mesh_axes=mesh_axes,
                          param_sharding=module._param_sharding,
                          precision=serve_pol,
                          _allow_fused=module._allow_fused, **extra)

        base = _make({})
        base.bind(data_shapes=_shapes_at(buckets[-1]), for_training=False)
        base.set_params(arg_params, aux_params)
        self._modules = {buckets[-1]: base}
        for b in buckets[:-1]:
            m = _make({})
            m.bind(data_shapes=_shapes_at(b), for_training=False,
                   shared_module=base)
            self._modules[b] = m
        self._base = base
        for b, m in self._modules.items():
            self._instrument(m)
            grp = m._exec_group
            if getattr(grp, "fused", False):
                # name this bucket's programs in the process
                # ProgramInventory (telemetry.introspect): the eval
                # program registers at warmup as "serving.b<k>.fwd_eval"
                grp._inventory_owner = "serving.b%d" % b
        self._warmed = False
        self._roofline = {}   # bucket -> analyzed basis (set by warmup)

    # ------------------------------------------------------------------
    @staticmethod
    def load(source, epoch=None, data_shapes=None, data_names=("data",),
             label_names=("softmax_label",), context=None, precision=None,
             **kwargs):
        """Predictor straight from a checkpoint: ``source`` is a legacy
        prefix (``epoch`` required), a ``CheckpointManager``, or a
        checkpoint directory (``epoch`` then selects a committed step,
        default the latest). Routes through :meth:`Module.load`, so the
        symbol rides in from the manifest on the manager path — which
        also adopts the entry's recorded precision mode; an explicit
        ``precision=`` that mismatches the recorded mode is REFUSED at
        Predictor construction (a wrong-mode serve is silent garbage)."""
        mkw = {}
        if precision is not None:
            mkw["precision"] = precision
        mod = Module.load(source, epoch, data_names=list(data_names),
                          label_names=list(label_names), context=context,
                          **mkw)
        return Predictor(mod, data_shapes=data_shapes, context=context,
                         **kwargs)

    # ------------------------------------------------------------------
    @property
    def buckets(self):
        return list(self._buckets)

    @property
    def max_batch_size(self):
        return self._buckets[-1]

    @property
    def output_names(self):
        return list(self._base.output_names)

    @property
    def data_names(self):
        return [name for name, _ in self._data_descs]

    def stats(self):
        """Snapshot of the serving counters: request outcomes, latency
        percentiles, batch-fill ratio, queue depth, compile count (see
        docs/api/serving.md for field semantics)."""
        return self._stats.snapshot()

    def _instrument(self, mod):
        """Count XLA traces through this module's eval functions — each
        jit trace runs the traced Python body exactly once, so wrapping
        the evaluator closure is an honest compile counter (and catches
        any accidental new input signature, not just new buckets)."""
        grp = mod._exec_group
        if not getattr(grp, "fused", False):
            # classic per-executor path jits at executor construction;
            # traces are not observable from here
            self._stats.compile_tracking = False
            return
        stats = self._stats
        for attr in ("_eval_fn", "_pipe_eval_fn"):
            inner = getattr(grp, attr, None)
            if inner is None:
                continue

            def counted(*a, __inner=inner, **kw):
                stats.note_compile()
                return __inner(*a, **kw)

            setattr(grp, attr, counted)

    # ------------------------------------------------------------------
    def _normalize(self, data):
        """Accept a numpy/jax/NDArray array (single-input nets), a
        list/tuple in ``data_names`` order, or a name->array dict;
        return (name->f32 raw array dict, n_rows). Feature dims are
        validated against the bound shapes so a malformed request fails
        at submit time, not on the batcher thread.

        Pre-staged (device-resident) inputs — e.g. the batches a
        :class:`mxnet_tpu.data.DeviceLoader` delivers — pass through
        WITHOUT a host round trip: a jax array stays on device (the
        pad/slice rule runs device-side) and the served rows remain
        bitwise equal to the same request from host memory (pinned by
        tests/test_data_pipeline.py)."""
        names = self.data_names
        if isinstance(data, dict):
            arrays = dict(data)
        elif isinstance(data, (list, tuple)):
            arrays = dict(zip(names, data))
        else:
            if len(names) != 1:
                raise ValueError(
                    "this net has %d inputs %r; pass a dict or a list"
                    % (len(names), names))
            arrays = {names[0]: data}
        missing = [n for n in names if n not in arrays]
        if missing:
            raise ValueError("request is missing input(s) %r" % missing)
        out, rows = {}, None
        for name, shape in self._data_descs:
            v = arrays[name]
            if hasattr(v, "_read"):
                v = v._read()
            if isinstance(v, onp.ndarray) or onp.isscalar(v) or \
                    isinstance(v, (list, tuple)):
                v = onp.ascontiguousarray(v, dtype=onp.float32)
            elif v.dtype != onp.float32:
                v = v.astype(onp.float32)
            if tuple(v.shape[1:]) != tuple(shape[1:]):
                raise ValueError(
                    "input %r has row shape %r, bound shape wants %r"
                    % (name, tuple(v.shape[1:]), tuple(shape[1:])))
            if rows is None:
                rows = v.shape[0]
            elif v.shape[0] != rows:
                raise ValueError(
                    "inputs disagree on row count: %d vs %d"
                    % (v.shape[0], rows))
            out[name] = v
        if not rows:
            raise ValueError("request has zero rows")
        return out, rows

    def bucket_for(self, n):
        """Smallest bucket that fits ``n`` rows (the top bucket for
        oversized requests — those are chunked)."""
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    # ------------------------------------------------------------------
    @property
    def params_digest(self):
        """Structural identity of (symbol, param shapes/dtypes) —
        the executable-cache key component checkpoint manifests record
        as ``params_digest``."""
        return self._params_digest

    def warmup_report(self):
        """Per-bucket outcome of the last :meth:`warmup`:
        ``{bucket: {"warmup_ms", "source"}}`` where ``source`` is
        ``"deserialized"`` (persistent-cache hit, zero XLA work),
        ``"compiled"`` (AOT compile + entry stored), or ``"jit"`` (no
        cache directory — classic lazy trace)."""
        return {b: dict(r) for b, r in
                getattr(self, "_warmup_report", {}).items()}

    def warmup(self, cache_dir=None):
        """Bring every bucket to a launchable executable BEFORE
        traffic; afterwards steady-state serving performs zero XLA
        compiles (``stats()['compiles']`` stays frozen — pinned by
        tests/test_serving.py). Returns the stats snapshot.

        ``cache_dir`` activates the persistent executable cache
        (module docstring of :mod:`mxnet_tpu.serving.cache`): each
        bucket either DESERIALIZES a crc-verified cache entry keyed by
        ``(params digest, precision mode, bucket, input signature,
        backend)`` — zero XLA compiles, the replica warm start — or
        compiles ahead-of-time and commits the entry atomically for
        the next replica. Any key mismatch (drifted params digest,
        wrong precision mode, different backend, corrupt or ``.tmp-*``
        entry) falls back LOUDLY to a fresh compile; a stale
        executable is never served silently. Defaults to
        ``$MXNET_COMPILE_CACHE_DIR/aot`` when that env var is set;
        explicit ``cache_dir`` values get an ``aot/`` subdirectory so
        jax's own persistent-cache files can share the root.

        Per-bucket compile/deserialize wall time publishes as
        ``serving.<i>.b<bucket>.warmup_ms`` gauges (also in
        ``stats()["warmup_ms"]``), hits/misses count into both the
        serving scope and ``compile.cache_hits``/``cache_misses``, and
        warmup traces are attributed to ``compile.warmup_compiles`` —
        never the training ``compile.retraces`` stream."""
        from .. import telemetry
        from . import cache as _cache
        if cache_dir is None:
            root = os.environ.get("MXNET_COMPILE_CACHE_DIR")
            cache_dir = os.path.join(root, "aot") if root else None
        else:
            cache_dir = os.path.join(str(cache_dir), "aot")
        store = _cache.ExecutableCache(cache_dir) if cache_dir else None
        watch = telemetry.compile_watch()
        for m in self._modules.values():
            watch.attach(m)
        report = {}
        with self._lock, watch.warmup_scope():
            for b in self._buckets:
                t0 = time.perf_counter()
                source = None
                if store is not None:
                    source = self._warm_bucket(b, store, watch)
                zeros = {name: onp.zeros((b,) + shape[1:], onp.float32)
                         for name, shape in self._data_descs}
                self._run_bucket(b, zeros, b, warmup=True)
                ms = (time.perf_counter() - t0) * 1000.0
                self._stats.note_warmup_bucket(b, ms, source)
                report[b] = {"warmup_ms": round(ms, 3),
                             "source": source or "jit"}
            self._warmed = True
            self._resolve_roofline()
        self._warmup_report = report
        return self.stats()

    def _warm_args(self, grp, bucket):
        """The exact ``(params, aux, inputs, rng)`` call structure a
        bucket launch uses — zeros staged through the SAME ``_stage``
        rule as traffic, so the lowered avals/shardings match every
        later request bitwise."""
        zeros = {name: onp.zeros((bucket,) + shape[1:], onp.float32)
                 for name, shape in self._data_descs}
        batch = DataBatch(
            data=[nd.NDArray(zeros[name])
                  for name, _ in self._data_descs],
            label=None, pad=0)
        inputs = grp._stage(batch, is_train=False)
        params = {n: buf._read() for n, buf in grp._param_dict.items()}
        aux = {n: buf._read() for n, buf in grp._aux_dict.items()}
        return params, aux, inputs, onp.zeros((2,), onp.uint32)

    def _bucket_cache_key(self, grp, bucket):
        from . import cache as _cache
        backend = _cache.backend_signature(
            mesh_axes=grp.mesh_axes, n_dev=int(grp.mesh.devices.size),
            device_kind=grp._device_kind, platform=grp._platform)
        input_sig = _cache.input_signature(self._data_descs)
        if self._calibration is not None:
            # two calibration passes may produce different static
            # scales — and therefore different programs — under the
            # same mode name and params digest: the table digest keeps
            # their executables apart
            input_sig += ";calib=%s" % self._calibration.digest()
        return _cache.cache_key(
            self._params_digest, grp.precision_mode_name(), bucket,
            input_sig, backend)

    def _warm_bucket(self, bucket, store, watch):
        """AOT-warm one bucket through the persistent executable
        cache: deserialize the entry (``"deserialized"``) or compile
        ahead-of-time and commit it (``"compiled"``). Either way the
        resulting executable is INSTALLED as the bucket's program —
        steady-state launches call it directly, with the jit wrapper
        (and any chance of a re-trace) out of the request path."""
        from . import cache as _cache
        grp = self._modules[bucket]._exec_group
        if not getattr(grp, "fused", False):
            return None   # classic per-executor path: nothing to AOT
        key = self._bucket_cache_key(grp, bucket)
        loaded, source = None, "compiled"
        try:
            payload, in_tree, out_tree = store.load(key)
            from jax.experimental import serialize_executable as _se
            loaded = _se.deserialize_and_load(payload, in_tree,
                                              out_tree)
            source = "deserialized"
        except _cache.CacheMiss as e:
            log = self.logger.info if e.reason == "absent" \
                else self.logger.warning
            log("serving bucket %d: executable cache %s — falling "
                "back to a fresh compile (%s)", bucket, e.reason,
                e.detail or store.path_for(key))
        except Exception as e:  # noqa: BLE001 - any deserialize failure
            self.logger.warning(
                "serving bucket %d: cached executable failed to "
                "deserialize (%s) — falling back to a fresh compile",
                bucket, e)
        if loaded is None:
            cached = grp._jits.get("fwd_eval")
            if cached is not None and not hasattr(cached, "lower"):
                # a previously installed (deserialized/AOT) executable
                # can't be re-lowered; drop it so _get_jit rebuilds the
                # traceable jit wrapper — re-warming after an evicted
                # entry must fall back to a fresh compile, not crash
                del grp._jits["fwd_eval"]
            fn = grp._get_jit("fwd_eval")
            # staged zeros + param reads are only needed to lower a
            # fresh compile — building them above the cache load would
            # add a device staging per bucket to every warm start
            args = self._warm_args(grp, bucket)
            # the lower() trace runs the instrumented evaluator body:
            # the compile counts into stats()['compiles'] and (via the
            # warmup scope) compile.warmup_compiles
            compiled = fn.lower(*args).compile()
            try:
                from jax.experimental import serialize_executable as _se
                payload, in_tree, out_tree = _se.serialize(compiled)
                store.store(key, payload, in_tree, out_tree)
            except Exception as e:  # noqa: BLE001 - cache is best-effort
                self.logger.warning(
                    "serving bucket %d: could not persist the compiled "
                    "executable (%s) — the next replica will recompile",
                    bucket, e)
            loaded = compiled
        grp._jits["fwd_eval"] = loaded
        if source == "deserialized":
            watch.note_cache_hit()
        else:
            watch.note_cache_miss()
        self._register_warm_program(grp, bucket, loaded, key, source)
        return source

    def _register_warm_program(self, grp, bucket, compiled, key,
                               source):
        """Thread the warm bucket through the introspection inventory:
        an ANALYTIC entry measured off the live executable (XLA cost
        analysis works on deserialized executables too), carrying the
        cache key + warm source in its meta — ``programs.*`` reports
        and the serving roofline gauges keep working on a warm replica
        whose jit handles never traced."""
        try:
            from .. import telemetry
            analysis = telemetry.analyze_compiled(compiled)
            name = telemetry.inventory().register(
                "%s.fwd_eval" % grp._inventory_owner, kind="fwd_eval",
                n_dev=int(grp.mesh.devices.size),
                device_kind=grp._device_kind,
                flops=analysis.get("flops"),
                bytes_accessed=analysis.get("bytes_accessed"),
                meta={"batch_size": bucket,
                      "mesh_axes": dict(grp.mesh_axes),
                      "warm_source": source, "cache_key": dict(key)})
            grp._program_notes.add("fwd_eval")
            grp._program_names["fwd_eval"] = name
        except Exception:  # noqa: BLE001 - introspection never breaks warmup
            pass

    def release(self):
        """Drop this Predictor's ``serving.<i>`` registry scope (see
        :meth:`ServingStats.release`) — call when discarding a
        Predictor in a long-lived multi-tenant process."""
        self._stats.release()

    def _resolve_roofline(self):
        """Per-bucket FLOPs/bytes from the program inventory
        (telemetry.introspect), resolved HERE in warmup — the analysis
        pass lowers through the jit trace cache and must never run on
        the request path. ``_run_bucket`` then publishes live
        ``serving.<i>.b<bucket>.mfu`` / ``achieved_hbm_gbps`` /
        ``bound_by`` gauges from pure host arithmetic — one triple PER
        BUCKET, so mixed-size traffic stays attributable on a scrape
        (a shared gauge would be last-launch-wins). Skipped (gauges
        absent) when telemetry is disabled."""
        from .. import telemetry
        if not telemetry.enabled():
            return
        scope = self._stats.scope
        self._roofline_gauges = {}
        for b, m in self._modules.items():
            basis_fn = getattr(m._exec_group, "program_basis", None)
            if basis_fn is None:
                continue
            try:
                basis = basis_fn(("fwd_eval",))
            except Exception:  # noqa: BLE001 - diagnostics only
                basis = None
            if basis:
                self._roofline[b] = basis
                self._roofline_gauges[b] = {
                    "mfu": scope.gauge("b%d.mfu" % b),
                    "achieved_hbm_gbps": scope.gauge(
                        "b%d.achieved_hbm_gbps" % b),
                    "bound_by": scope.gauge("b%d.bound_by" % b),
                }

    def predict(self, data):
        """Serve one request synchronously (no batching): pad to the
        bucket, launch, slice. Returns a single numpy array for
        single-output nets, else a list in ``output_names`` order.
        Thread-safe; for concurrent callers prefer a
        :class:`DynamicBatcher`, which coalesces them into fewer,
        fuller launches."""
        from .. import telemetry
        tracing = telemetry.enabled()
        arrays, rows = self._normalize(data)
        t0 = time.perf_counter()
        self._stats.note_request()
        timing = {} if tracing else None
        outs = self._predict_rows(arrays, rows, timing=timing)
        t1 = time.perf_counter()
        self._stats.note_completed((t1 - t0) * 1000.0)
        if tracing:
            # direct path: no queue, no coalescing — the trace is pad +
            # device + the residual dispatch/slice overhead
            self._stats.note_trace(
                self._stats.new_request_id(), rows,
                self.bucket_for(rows), {
                    "pad_ms": timing.get("pad_ms", 0.0),
                    "device_ms": timing.get("device_ms", 0.0),
                    "resolve_ms": max(
                        (t1 - t0) * 1000.0 - timing.get("pad_ms", 0.0)
                        - timing.get("device_ms", 0.0), 0.0)})
        return outs[0] if len(outs) == 1 else outs

    def _predict_rows(self, arrays, rows, timing=None):
        """Serve ``rows`` normalized rows; always returns the list of
        per-output numpy arrays. The batcher calls this directly (it
        does its own request accounting). ``timing`` (a dict) receives
        accumulated ``pad_ms`` / ``device_ms`` clocks for the request
        trace — chunked oversized requests accumulate across launches."""
        from .. import faults as _faults
        if _faults.armed():
            # device-slowdown seam (kind=delay): a straggling or
            # thermally-throttled device — the latency lands in the
            # device_ms phase and the SLO burn windows, bytes unchanged
            _faults.check("serving.device", rows=rows)
        parts = []
        with self._lock:
            start = 0
            while start < rows:
                take = min(rows - start, self._buckets[-1])
                chunk = {k: v[start:start + take]
                         for k, v in arrays.items()} if (start or
                                                         take < rows) \
                    else arrays
                parts.append(self._run_bucket(self.bucket_for(take),
                                              chunk, take,
                                              timing=timing))
                start += take
        if len(parts) == 1:
            return parts[0]
        return [onp.concatenate([p[i] for p in parts])
                for i in range(len(parts[0]))]

    def _run_bucket(self, bucket, arrays, rows, warmup=False,
                    timing=None):
        """One device launch at ``bucket``: zero-pad the request rows
        up to the bucket's bound shape (the same ``pad_batch_rows``
        rule the predict/score epoch-tail fix uses) and slice the
        outputs back to the real rows."""
        from .. import telemetry
        mod = self._modules[bucket]
        t_pad = time.perf_counter() if timing is not None else 0.0
        batch = DataBatch(
            data=[nd.NDArray(pad_batch_rows(arrays[name], bucket))
                  for name, _ in self._data_descs],
            label=None, pad=bucket - rows)
        basis = self._roofline.get(bucket) if not warmup else None
        if timing is not None:
            t0 = time.perf_counter()
            timing["pad_ms"] = timing.get("pad_ms", 0.0) \
                + (t0 - t_pad) * 1000.0
        else:
            t0 = time.perf_counter() if basis else 0.0
        with telemetry.span("serving.launch", bucket=bucket, rows=rows):
            mod.forward(batch, is_train=False)
            outs = [o.asnumpy()[:rows] for o in mod.get_outputs()]
        if timing is not None:
            timing["device_ms"] = timing.get("device_ms", 0.0) \
                + (time.perf_counter() - t0) * 1000.0
        if basis:
            # live serving roofline: the bucket program's analyzed
            # FLOPs/bytes over this launch's wall clock (dispatch +
            # readback — the honest served rate). Host arithmetic only.
            r = telemetry.roofline(
                basis["flops_per_step"], basis["bytes_per_step"],
                time.perf_counter() - t0,
                basis["peak_tflops"], basis["peak_hbm_gbps"])
            gauges = self._roofline_gauges[bucket]
            gauges["mfu"].set(round(r["mfu"], 6))
            gauges["achieved_hbm_gbps"].set(
                round(r["achieved_hbm_gbps"], 3))
            gauges["bound_by"].set(r["bound_by_code"])
        self._stats.note_batch(bucket, rows, warmup=warmup)
        return outs
