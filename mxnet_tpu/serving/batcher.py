"""DynamicBatcher — coalesce concurrent requests into full device
launches, with backpressure.

A device serving one request at a time runs at batch-1 utilization; a
device serving whenever "enough" requests arrive runs near its training
throughput. The batcher sits between the two: client threads ``submit``
requests into a **bounded** queue and get a future back; a background
worker coalesces whatever is queued — up to the Predictor's top bucket
— within a ``max_wait_ms`` window measured from the first queued
request, launches ONE bucket-padded device call through the Predictor,
and routes each slice of the output back to its caller's future.

Overload degrades instead of OOMing:

* queue full -> ``submit`` raises :class:`QueueFull` synchronously
  (backpressure; the request is never enqueued);
* a request older than ``timeout_ms`` is dropped at launch time and its
  future carries :class:`RequestTimeout`;
* ``shutdown(drain=True)`` stops intake, serves out the queue, and
  joins the worker; ``drain=False`` fails pending futures with
  :class:`ServerClosed`.

The batcher shares its Predictor's :class:`ServingStats`, so
``stats()`` shows queue depth, batch-fill ratio, and per-request
latency percentiles for the whole stack — percentiles that INCLUDE
deadline-missed requests (an expired request's queue age is a latency
sample, so p99 does not under-report exactly under overload).

Judgment-layer hooks:

* every request carries a stable id; with telemetry enabled its life
  is recorded as a phase-decomposed trace (queue-wait, coalesce-wait,
  pad, device, resolve) into the stats trace ring, the per-bucket
  phase histograms, and the Chrome-trace span timeline — a p99 blowup
  is attributable to queueing vs device time (docs/api/serving.md
  "Request traces");
* ``slo=`` attaches a :class:`mxnet_tpu.telemetry.SLOTracker`: every
  outcome (ok / error / timeout / queue-full reject) is recorded
  against the declared objectives and ``slo_breached()`` surfaces the
  multi-window burn-rate breach state (the admission decision that
  will consume it is a later PR).
"""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future

from .errors import QueueFull, RequestTimeout, ServerClosed

__all__ = ["DynamicBatcher"]


class _Request:
    __slots__ = ("arrays", "rows", "future", "deadline", "t_submit",
                 "id", "t_popped")

    def __init__(self, arrays, rows, future, deadline, t_submit,
                 req_id=None):
        self.arrays = arrays
        self.rows = rows
        self.future = future
        self.deadline = deadline
        self.t_submit = t_submit
        self.id = req_id
        self.t_popped = t_submit   # set when the worker dequeues it


class DynamicBatcher:
    """Bounded request queue + coalescing worker over a Predictor.

    Parameters
    ----------
    predictor : Predictor
        The bucketed inference engine requests are served through.
    max_queue : int
        Queue capacity in requests; beyond it ``submit`` rejects
        (:class:`QueueFull`).
    max_wait_ms : float
        Coalescing window measured from the FIRST queued request: the
        worker launches as soon as the top bucket is full or the window
        closes, whichever comes first. 0 serves whatever is queued
        immediately (lowest latency, lowest fill).
    timeout_ms : float, optional
        Per-request deadline; requests still queued past it fail with
        :class:`RequestTimeout` instead of occupying a launch.
    start : bool
        Start the worker thread immediately (default). ``start=False``
        lets tests (and staged deployments) fill the queue first.
    metrics_port : int, optional
        Serve the process-wide telemetry registry as a Prometheus
        ``GET /metrics`` endpoint (stdlib ``http.server``) for the
        batcher's lifetime — ``0`` picks a free port, readable as
        ``.metrics_server.port``. The serving counters live in the
        registry (``ServingStats`` is a view over it), so a scraper
        pointed here sees queue depth, latency histogram, batch fill,
        and compiles live.
    slo : mxnet_tpu.telemetry.SLOTracker, optional
        Declared serving objectives. The batcher records every request
        outcome — completions with their latency, deadline misses with
        their queue age, errors, queue-full rejects — so the tracker's
        ``slo.*`` burn-rate gauges judge THIS batcher's traffic;
        :meth:`slo_breached` surfaces the breach state.
    """

    def __init__(self, predictor, max_queue=256, max_wait_ms=2.0,
                 timeout_ms=None, start=True, metrics_port=None,
                 slo=None):
        self._pred = predictor
        self._stats = predictor._stats
        self.slo = slo
        self.metrics_server = None
        if metrics_port is not None:
            from .. import telemetry
            self.metrics_server = telemetry.MetricsServer(
                telemetry.registry(), port=int(metrics_port))
        self._max_queue = int(max_queue)
        self._max_wait = max(0.0, float(max_wait_ms)) / 1000.0
        self._timeout = (float(timeout_ms) / 1000.0
                         if timeout_ms is not None else None)
        self._max_rows = predictor.max_batch_size
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        self._thread = None
        self._stats.set_queue_probe(lambda: len(self._queue))
        if start:
            self.start()

    # ------------------------------------------------------------------
    def start(self):
        """Start (or restart after ``start=False``) the worker thread."""
        with self._cond:
            if self._closed:
                raise ServerClosed("batcher is shut down")
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._worker, name="mxnet-tpu-serving-batcher",
                daemon=True)
            self._thread.start()

    def submit(self, data, timeout_ms=None):
        """Enqueue one request; returns a ``concurrent.futures.Future``
        resolving to the request's outputs (single array for
        single-output nets, else a list). Raises :class:`ServerClosed`
        after shutdown and :class:`QueueFull` when the bounded queue is
        at capacity — the backpressure signal. Malformed requests raise
        ``ValueError`` here, on the caller's thread."""
        arrays, rows = self._pred._normalize(data)
        t = time.perf_counter()
        limit = self._timeout if timeout_ms is None else \
            float(timeout_ms) / 1000.0
        req = _Request(arrays, rows, Future(),
                       t + limit if limit is not None else None, t,
                       req_id=self._stats.new_request_id())
        with self._cond:
            if self._closed:
                raise ServerClosed("batcher is shut down")
            full = len(self._queue) >= self._max_queue
            if not full:
                self._queue.append(req)
                self._stats.note_request()
                self._cond.notify_all()
        if full:
            # accounting OUTSIDE the condition lock: the SLO record can
            # trigger a bounded window scan, and overload — when rejects
            # fire — is exactly when the worker must not stall behind it
            self._stats.note_reject()
            if self.slo is not None:
                self.slo.record(outcome="reject")
            raise QueueFull(
                "serving queue at capacity (%d requests) — shed "
                "load or retry with backoff" % self._max_queue)
        return req.future

    def predict(self, data, timeout=None, timeout_ms=None):
        """Blocking convenience: ``submit`` + ``Future.result``.
        ``timeout`` (seconds) bounds the caller-side wait; ``timeout_ms``
        overrides the batcher's per-request deadline."""
        return self.submit(data, timeout_ms=timeout_ms).result(timeout)

    def stats(self):
        return self._pred.stats()

    # ------------------------------------------------------------------
    def shutdown(self, drain=True, timeout=None):
        """Stop intake and end the worker. ``drain=True`` serves every
        already-queued request first (graceful); ``drain=False`` fails
        them with :class:`ServerClosed`. Idempotent."""
        with self._cond:
            already = self._closed
            self._closed = True
            if not drain or self._thread is None:
                # nobody will serve these — fail them out loud
                while self._queue:
                    req = self._queue.popleft()
                    self._stats.note_error()
                    req.future.set_exception(
                        ServerClosed("batcher shut down before launch"))
            self._cond.notify_all()
            thread, self._thread = self._thread, None
        if thread is not None and not already:
            thread.join(timeout)
        server, self.metrics_server = self.metrics_server, None
        if server is not None:
            server.close()

    def close(self):
        self.shutdown(drain=True)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)

    # ------------------------------------------------------------------
    def _worker(self):
        while True:
            reqs = self._gather()
            if reqs is None:
                return
            if reqs:
                self._launch(reqs)

    def _gather(self):
        """Block for the first request, then coalesce more until the
        top bucket is full, the ``max_wait_ms`` window (from the first
        request) closes, or the next request would overflow the bucket.
        Returns the live (non-expired, non-cancelled) requests, or None
        when shut down with an empty queue."""
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                # untimed: submit() and shutdown() both notify, so an
                # idle server parks instead of polling
                self._cond.wait()
            reqs = [self._queue.popleft()]
            reqs[0].t_popped = time.perf_counter()
            rows = reqs[0].rows
            window_end = reqs[0].t_submit + self._max_wait
            while rows < self._max_rows:
                if self._queue:
                    if rows + self._queue[0].rows > self._max_rows:
                        break
                    nxt = self._queue.popleft()
                    nxt.t_popped = time.perf_counter()
                    reqs.append(nxt)
                    rows += nxt.rows
                    continue
                remaining = window_end - time.perf_counter()
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(remaining)
        from .. import telemetry
        now = time.perf_counter()
        live = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                age_ms = (now - r.t_submit) * 1000.0
                # the miss IS a latency outcome: its age reaches the
                # reservoir/histogram (p99 must reflect overload) and
                # spends SLO error budget
                self._stats.note_timeout(age_ms)
                if self.slo is not None:
                    self.slo.record(age_ms, "timeout")
                if telemetry.enabled():
                    self._stats.note_trace(
                        r.id, r.rows, None,
                        {"queue_wait_ms": age_ms}, outcome="timeout")
                if r.future.set_running_or_notify_cancel():
                    # guard like the live path: set_exception on a
                    # caller-CANCELLED future raises InvalidStateError
                    # and would kill the worker thread for good
                    r.future.set_exception(RequestTimeout(
                        "request %s expired after %.1f ms in queue"
                        % (r.id, age_ms)))
            elif r.future.set_running_or_notify_cancel():
                live.append(r)
        return live

    def _launch(self, reqs):
        import numpy as onp

        from .. import telemetry
        tracing = telemetry.enabled()
        total = sum(r.rows for r in reqs)
        t_launch = time.perf_counter()
        timing = {} if tracing else None
        try:
            if len(reqs) == 1:
                arrays = reqs[0].arrays
            else:
                names = list(reqs[0].arrays)
                arrays = {k: onp.concatenate([r.arrays[k] for r in reqs])
                          for k in names}
            outs = self._pred._predict_rows(arrays, total, timing=timing)
        except BaseException as e:  # noqa: B036 — futures must resolve
            for r in reqs:
                self._stats.note_error()
                if self.slo is not None:
                    self.slo.record(outcome="error")
                if tracing:
                    self._trace(r, None, timing, t_launch,
                                time.perf_counter(), outcome="error")
                r.future.set_exception(e)
            return
        t_outs = time.perf_counter()
        off = 0
        for r in reqs:
            res = [o[off:off + r.rows] for o in outs]
            off += r.rows
            r.future.set_result(res[0] if len(res) == 1 else res)
            now = time.perf_counter()
            lat_ms = (now - r.t_submit) * 1000.0
            self._stats.note_completed(lat_ms)
            if self.slo is not None:
                self.slo.record(lat_ms, "ok")
            if tracing:
                self._trace(r, self._pred.bucket_for(total), timing,
                            t_launch, t_outs, t_done=now)

    def _trace(self, r, bucket, timing, t_launch, t_outs, t_done=None,
               outcome="ok"):
        """One request's phase decomposition. The shared launch phases
        (pad, device) are what every coalesced request experienced;
        queue/coalesce/resolve are the request's own clocks — so each
        trace's phase sum tracks ITS end-to-end latency."""
        timing = timing or {}
        t_done = t_outs if t_done is None else t_done
        phases = {
            "queue_wait_ms": (r.t_popped - r.t_submit) * 1000.0,
            "coalesce_wait_ms": (t_launch - r.t_popped) * 1000.0,
            "pad_ms": timing.get("pad_ms", 0.0),
            "device_ms": timing.get("device_ms", 0.0),
            # normalize/concat overhead before the pad plus the
            # slice-and-resolve after the outputs landed
            "resolve_ms": max(
                (t_done - t_launch) * 1000.0
                - timing.get("pad_ms", 0.0)
                - timing.get("device_ms", 0.0), 0.0),
        }
        self._stats.note_trace(r.id, r.rows, bucket, phases,
                               outcome=outcome)

    def slo_breached(self):
        """Whether the attached :class:`SLOTracker` reports an active
        multi-window burn-rate breach (False without one) — the signal
        a later admission-control layer will act on."""
        return self.slo is not None and self.slo.breached()
