"""DynamicBatcher — coalesce concurrent requests into full device
launches, with backpressure, multi-model tenancy, and SLO-driven
admission.

A device serving one request at a time runs at batch-1 utilization; a
device serving whenever "enough" requests arrive runs near its training
throughput. The batcher sits between the two: client threads ``submit``
requests into a **bounded** queue and get a future back; a background
worker coalesces whatever is queued — up to the tenant Predictor's top
bucket — within a ``max_wait_ms`` window measured from the first queued
request, launches ONE bucket-padded device call through that tenant's
Predictor, and routes each slice of the output back to its caller's
future.

One batcher can host SEVERAL named models (:class:`Tenant` — or
several checkpoint generations of one model, for canary rollout)
behind the same queue: requests route by tenant name, launches
coalesce within a tenant, the worker serves the highest-priority
backlog first, and every tenant keeps its own ``serving.<i>.*`` stats
scope and ``slo.<name>.*`` burn-rate gauges so a p99 regression stays
attributable per tenant.

Overload degrades instead of OOMing:

* queue full -> ``submit`` raises :class:`QueueFull` synchronously
  (backpressure; the request is never enqueued);
* a request older than ``timeout_ms`` is dropped at launch time and its
  future carries :class:`RequestTimeout`;
* a tenant whose own SLO fast+slow burn windows are in breach is SHED
  (unless protected): new submits raise :class:`TenantShed`, queued
  requests drop at dequeue time with their queue age traced — only the
  breached tenant; co-hosted tenants keep serving
  (tenancy module docstring has the full admission policy);
* ``shutdown(drain=True)`` stops intake, serves out the queue, and
  joins the worker; ``drain=False`` fails pending futures with
  :class:`ServerClosed`.

The single-tenant spelling is unchanged: ``DynamicBatcher(pred,
slo=...)`` hosts one default tenant and ``stats()`` returns its
Predictor's snapshot — percentiles that INCLUDE deadline-missed and
worker-shed requests (their queue age is a latency sample, so p99 does
not under-report exactly under overload).

Judgment-layer hooks:

* every request carries a stable id; with telemetry enabled its life
  is recorded as a phase-decomposed trace (queue-wait, coalesce-wait,
  pad, device, resolve) into the tenant's stats trace ring, the
  per-bucket phase histograms, and the Chrome-trace span timeline —
  never-launched outcomes (timeout, shed) land their queue age in the
  bucket-free ``phase_queue_wait_ms`` histogram;
* ``slo=`` / per-tenant trackers record every outcome (ok / error /
  timeout / queue-full reject) against the declared objectives;
  ``slo_breached()`` surfaces the burn-rate breach state the admission
  policy above consumes.
"""
from __future__ import annotations

import collections
import logging
import os
import threading
import time
from concurrent.futures import Future, InvalidStateError

from .. import faults as _faults
from .errors import (QueueFull, RequestTimeout, ServerClosed, TenantShed,
                     WorkerCrashed)
from .tenancy import Tenant

__all__ = ["DynamicBatcher"]


class _Request:
    __slots__ = ("arrays", "rows", "future", "deadline", "t_submit",
                 "id", "t_popped")

    def __init__(self, arrays, rows, future, deadline, t_submit,
                 req_id=None):
        self.arrays = arrays
        self.rows = rows
        self.future = future
        self.deadline = deadline
        self.t_submit = t_submit
        self.id = req_id
        self.t_popped = t_submit   # set when the worker dequeues it


class DynamicBatcher:
    """Bounded request queue + coalescing worker over one or more
    tenant Predictors.

    Parameters
    ----------
    predictor : Predictor, optional
        Single-tenant spelling: hosts one ``"default"`` tenant.
        Mutually exclusive with ``tenants=``.
    max_queue : int
        Queue capacity in requests, shared across tenants; beyond it
        ``submit`` rejects (:class:`QueueFull`).
    max_wait_ms : float
        Coalescing window measured from the FIRST queued request of a
        launch: the worker launches as soon as the tenant's top bucket
        is full or the window closes, whichever comes first. 0 serves
        whatever is queued immediately (lowest latency, lowest fill).
    timeout_ms : float, optional
        Per-request deadline; requests still queued past it fail with
        :class:`RequestTimeout` instead of occupying a launch.
    start : bool
        Start the worker thread immediately (default). ``start=False``
        lets tests (and staged deployments) fill the queue first.
    metrics_port : int, optional
        Serve the process-wide telemetry registry as a Prometheus
        ``GET /metrics`` endpoint (stdlib ``http.server``) for the
        batcher's lifetime — ``0`` picks a free port, readable as
        ``.metrics_server.port``. Every tenant's serving counters live
        in the registry, so a scraper pointed here sees queue depth,
        latency histograms, batch fill, and compiles per tenant.
    slo : mxnet_tpu.telemetry.SLOTracker, optional
        Single-tenant spelling: objectives for the default tenant
        (every outcome recorded; breach drives admission).
    tenants : dict, optional
        ``name -> Predictor | Tenant`` — the multi-model spelling.
        Plain Predictors wrap as ``Tenant(name, predictor)``; pass
        :class:`Tenant` objects to attach per-tenant SLOs, priorities,
        and shed protection. Mutually exclusive with ``predictor``.
    """

    def __init__(self, predictor=None, max_queue=256, max_wait_ms=2.0,
                 timeout_ms=None, start=True, metrics_port=None,
                 slo=None, tenants=None):
        if tenants:
            if predictor is not None or slo is not None:
                raise ValueError(
                    "pass either a single predictor (+ slo) or "
                    "tenants=, not both")
            resolved = collections.OrderedDict()
            for name, spec in tenants.items():
                if isinstance(spec, Tenant):
                    if spec.name != str(name):
                        raise ValueError(
                            "tenant key %r names a Tenant(%r) — keys "
                            "and Tenant names must agree"
                            % (name, spec.name))
                    resolved[str(name)] = spec
                else:
                    resolved[str(name)] = Tenant(name, spec)
            seen = {}
            for name, ten in resolved.items():
                prev = seen.setdefault(id(ten.predictor), name)
                if prev != name:
                    raise ValueError(
                        "tenants %r and %r share one Predictor "
                        "instance — their stats scopes and queue "
                        "gauge would silently merge; build one "
                        "Predictor per tenant (two Predictors over "
                        "one module share device params)"
                        % (prev, name))
            self._tenants = resolved
        else:
            if predictor is None:
                raise ValueError(
                    "DynamicBatcher needs a predictor (or tenants=)")
            self._tenants = collections.OrderedDict(
                [("default", Tenant("default", predictor, slo=slo))])
        self._default = next(iter(self._tenants)) \
            if len(self._tenants) == 1 else None
        # single-tenant back-compat surface
        self._pred = self._tenants[self._default].predictor \
            if self._default else None
        self.metrics_server = None
        if metrics_port is not None:
            from .. import telemetry
            self.metrics_server = telemetry.MetricsServer(
                telemetry.registry(), port=int(metrics_port))
        self._max_queue = int(max_queue)
        self._max_wait = max(0.0, float(max_wait_ms)) / 1000.0
        self._timeout = (float(timeout_ms) / 1000.0
                         if timeout_ms is not None else None)
        self._queues = {name: collections.deque()
                        for name in self._tenants}
        self._n_queued = 0
        self._cond = threading.Condition()
        self._closed = False
        self._thread = None
        # worker supervision: requests the worker has popped for the
        # CURRENT gather/launch cycle (worker thread only) — on an
        # escaped exception these are the futures that would otherwise
        # hang forever, so the supervisor fails them loudly and
        # restarts the loop (bounded by MXNET_SERVE_MAX_WORKER_RESTARTS)
        self._popped = []
        self._popped_tenant = None
        self._max_worker_restarts = int(os.environ.get(
            "MXNET_SERVE_MAX_WORKER_RESTARTS", "100"))
        self._logger = logging.getLogger("mxnet_tpu.serving")
        for name, ten in self._tenants.items():
            ten.stats.set_queue_probe(
                lambda q=self._queues[name]: len(q))
        if start:
            self.start()

    # ------------------------------------------------------------------
    @property
    def slo(self):
        """The default tenant's SLOTracker (single-tenant back-compat;
        None in multi-tenant mode — read per-tenant via
        :meth:`tenant`)."""
        return self._tenants[self._default].slo if self._default \
            else None

    def tenants(self):
        """The hosted tenant names, in registration order."""
        return list(self._tenants)

    def tenant(self, name):
        """The named :class:`Tenant` (KeyError for unknown names)."""
        return self._tenants[name]

    def add_tenant(self, tenant):
        """Admit a new :class:`Tenant` at RUNTIME (the canary-rollout
        hook ``mxnet_tpu.autopilot`` drives): the tenant gets its own
        queue and joins the priority schedule on the next gather.
        Admission never disturbs existing clients — a single-tenant
        batcher's default route keeps pointing at the ORIGINAL tenant,
        so un-named ``submit()`` calls are unaffected by a canary
        joining. Rejects duplicate names and a Predictor instance
        another tenant already serves (their stats scopes would
        silently merge). Returns the tenant."""
        if not isinstance(tenant, Tenant):
            raise TypeError("add_tenant needs a Tenant (got %s)"
                            % type(tenant).__name__)
        with self._cond:
            if self._closed:
                raise ServerClosed("batcher is shut down")
            if tenant.name in self._tenants:
                raise ValueError("tenant %r is already hosted"
                                 % tenant.name)
            for name, ten in self._tenants.items():
                if ten.predictor is tenant.predictor:
                    raise ValueError(
                        "tenant %r would share tenant %r's Predictor "
                        "instance — build one Predictor per tenant"
                        % (tenant.name, name))
            self._tenants[tenant.name] = tenant
            self._queues[tenant.name] = collections.deque()
            tenant.stats.set_queue_probe(
                lambda q=self._queues[tenant.name]: len(q))
            self._cond.notify_all()
        return tenant

    def remove_tenant(self, name):
        """Stop hosting the named tenant (the canary-rollback hook):
        its queue is detached and still-queued requests fail with
        :class:`ServerClosed` — a rolled-back canary's backlog must
        never launch. In-flight requests the worker already popped
        complete normally. The default route re-resolves when the
        removal leaves ONE tenant. Returns the removed tenant."""
        with self._cond:
            if name not in self._tenants:
                raise ValueError("unknown tenant %r (hosted: %r)"
                                 % (name, list(self._tenants)))
            ten = self._tenants.pop(name)
            q = self._queues.pop(name)
            while q:
                req = q.popleft()
                self._n_queued -= 1
                ten.stats.note_error()
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(ServerClosed(
                        "tenant %r removed before request %s launched"
                        % (name, req.id)))
            if self._default == name or self._default is None:
                self._default = next(iter(self._tenants)) \
                    if len(self._tenants) == 1 else None
                self._pred = self._tenants[self._default].predictor \
                    if self._default else None
            self._cond.notify_all()
        return ten

    def replace_tenant(self, name, tenant):
        """ATOMICALLY swap the named route to a new :class:`Tenant`
        (the canary-promotion hook): requests already queued under the
        name stay queued and launch through the NEW tenant's Predictor
        — there is no window where the route doesn't resolve. The new
        tenant must carry the same name; the caller owns shape
        compatibility (a promotion serves the same model family).
        Returns the replaced tenant."""
        if not isinstance(tenant, Tenant):
            raise TypeError("replace_tenant needs a Tenant (got %s)"
                            % type(tenant).__name__)
        if tenant.name != str(name):
            raise ValueError(
                "replace_tenant(%r) got a Tenant named %r — the route "
                "name is the identity" % (name, tenant.name))
        with self._cond:
            if name not in self._tenants:
                raise ValueError("unknown tenant %r (hosted: %r)"
                                 % (name, list(self._tenants)))
            for other, ten in self._tenants.items():
                if other != name and ten.predictor is tenant.predictor:
                    raise ValueError(
                        "tenant %r would share tenant %r's Predictor "
                        "instance — remove that tenant first"
                        % (name, other))
            old = self._tenants[name]
            self._tenants[name] = tenant
            tenant.stats.set_queue_probe(
                lambda q=self._queues[name]: len(q))
            if self._default == name:
                self._pred = tenant.predictor
            self._cond.notify_all()
        return old

    def _resolve(self, tenant):
        if tenant is None:
            if self._default is None:
                raise ValueError(
                    "this batcher hosts tenants %r — submit(..., "
                    "tenant=<name>) must name one" % list(self._tenants))
            return self._tenants[self._default]
        try:
            return self._tenants[tenant]
        except KeyError:
            raise ValueError("unknown tenant %r (hosted: %r)"
                             % (tenant, list(self._tenants))) from None

    # ------------------------------------------------------------------
    def start(self):
        """Start (or restart after ``start=False``) the worker thread."""
        with self._cond:
            if self._closed:
                raise ServerClosed("batcher is shut down")
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._worker, name="mxnet-tpu-serving-batcher",
                daemon=True)
            self._thread.start()

    def submit(self, data, timeout_ms=None, tenant=None):
        """Enqueue one request for ``tenant`` (the sole tenant when
        omitted); returns a ``concurrent.futures.Future`` resolving to
        the request's outputs (single array for single-output nets,
        else a list). Raises :class:`ServerClosed` after shutdown,
        :class:`QueueFull` when the bounded queue is at capacity (the
        backpressure signal), and :class:`TenantShed` while the
        tenant's own SLO burn windows are in breach (admission sheds
        the breached tenant only). Malformed requests raise
        ``ValueError`` here, on the caller's thread."""
        from .. import telemetry
        ten = self._resolve(tenant)
        arrays, rows = ten.predictor._normalize(data)
        if self._closed:
            # fast-path spelling of the locked check below: a dead
            # server must answer ServerClosed (stop), never TenantShed
            # (back off and retry), and must not mutate shed stats
            raise ServerClosed("batcher is shut down")
        if ten.shed_active():
            # admission shed: decided before the queue, so the request
            # costs the device nothing; the decision is still recorded
            # (counter + trace) so a shed spike is attributable
            ten.stats.note_shed()
            if telemetry.enabled():
                ten.stats.note_trace(ten.stats.new_request_id(), rows,
                                     None, {}, outcome="shed")
            raise TenantShed(
                "tenant %r shed: its SLO fast+slow burn windows are in "
                "breach — back off, or route to a protected tenant"
                % ten.name)
        t = time.perf_counter()
        limit = self._timeout if timeout_ms is None else \
            float(timeout_ms) / 1000.0
        req = _Request(arrays, rows, Future(),
                       t + limit if limit is not None else None, t,
                       req_id=ten.stats.new_request_id())
        # queue-flood seam: a fired rule makes THIS submit see the
        # queue at capacity — the deterministic stand-in for a burst
        # arriving faster than the worker drains (clients must observe
        # the same QueueFull backpressure either way)
        flood = _faults.armed() and _faults.fires("serving.queue_flood",
                                                  tenant=ten.name)
        with self._cond:
            if self._closed:
                raise ServerClosed("batcher is shut down")
            full = flood or self._n_queued >= self._max_queue
            if not full:
                self._queues[ten.name].append(req)
                self._n_queued += 1
                ten.stats.note_request()
                self._cond.notify_all()
        if full:
            # accounting OUTSIDE the condition lock: the SLO record can
            # trigger a bounded window scan, and overload — when rejects
            # fire — is exactly when the worker must not stall behind it
            ten.stats.note_reject()
            if ten.slo is not None:
                ten.slo.record(outcome="reject")
            raise QueueFull(
                "serving queue at capacity (%d requests) — shed "
                "load or retry with backoff" % self._max_queue)
        return req.future

    def predict(self, data, timeout=None, timeout_ms=None, tenant=None):
        """Blocking convenience: ``submit`` + ``Future.result``.
        ``timeout`` (seconds) bounds the caller-side wait; ``timeout_ms``
        overrides the batcher's per-request deadline."""
        return self.submit(data, timeout_ms=timeout_ms,
                           tenant=tenant).result(timeout)

    def stats(self, tenant=None):
        """The named tenant's stats snapshot; with one tenant and no
        name, its snapshot (the historical single-tenant shape); with
        several and no name, ``{tenant: snapshot}``."""
        if tenant is not None:
            return self._resolve(tenant).predictor.stats()
        if self._default is not None:
            return self._pred.stats()
        return {name: ten.predictor.stats()
                for name, ten in self._tenants.items()}

    # ------------------------------------------------------------------
    def shutdown(self, drain=True, timeout=None):
        """Stop intake and end the worker. ``drain=True`` serves every
        already-queued request first (graceful); ``drain=False`` fails
        them with :class:`ServerClosed`. Idempotent."""
        with self._cond:
            already = self._closed
            self._closed = True
            if not drain or self._thread is None:
                # nobody will serve these — fail them out loud
                for name, q in self._queues.items():
                    ten = self._tenants[name]
                    while q:
                        req = q.popleft()
                        self._n_queued -= 1
                        ten.stats.note_error()
                        req.future.set_exception(ServerClosed(
                            "batcher shut down before launch"))
            self._cond.notify_all()
            thread, self._thread = self._thread, None
        if thread is not None and not already and \
                thread is not threading.current_thread():
            # the give-up path calls shutdown FROM the worker thread;
            # a thread cannot join itself
            thread.join(timeout)
        server, self.metrics_server = self.metrics_server, None
        if server is not None:
            server.close()

    def close(self):
        self.shutdown(drain=True)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)

    # ------------------------------------------------------------------
    def _worker(self):
        """The supervised worker loop. Device/model errors are handled
        INSIDE :meth:`_launch` (each future gets the exception); this
        loop guards against everything else — a bug or injected fault
        escaping the gather/launch path used to kill the thread
        silently, leaving every queued future hanging forever. Now the
        implicated in-flight requests fail loudly with
        :class:`WorkerCrashed`, the tenant's ``worker_restarts``
        counter increments, and the loop restarts to serve the rest of
        the queue; only after ``MXNET_SERVE_MAX_WORKER_RESTARTS``
        consecutive crash cycles does the batcher give up and close."""
        restarts = 0
        while True:
            self._popped = []
            self._popped_tenant = None
            try:
                gathered = self._gather()
                if gathered is None:
                    return
                ten, reqs = gathered
                if reqs:
                    self._launch(ten, reqs)
                restarts = 0
            except BaseException as exc:  # noqa: BLE001 — supervised
                if isinstance(exc, (SystemExit, KeyboardInterrupt)):
                    raise
                restarts += 1
                self._on_worker_crash(exc, restarts)
                if restarts >= self._max_worker_restarts:
                    self._logger.critical(
                        "serving worker crashed %d times; closing the "
                        "batcher", restarts)
                    self.shutdown(drain=False, timeout=0)
                    return

    def _on_worker_crash(self, exc, restarts):
        """Fail the crash cycle's in-flight futures with a descriptive
        error and count the restart — nothing a client holds may hang."""
        ten = self._popped_tenant
        self._logger.exception(
            "serving worker crashed (restart %d, tenant %r, %d "
            "in-flight request(s)): %r", restarts,
            ten.name if ten is not None else None, len(self._popped),
            exc)
        if ten is not None:
            ten.stats.note_worker_restart()
        for r in self._popped:
            fut = r.future
            if not fut.done():
                # queued-popped futures still need the PENDING->RUNNING
                # transition; ones already RUNNING (the _gather live
                # path did it) take set_exception directly. A
                # concurrently cancelled/resolved future raises
                # InvalidStateError below — it no longer hangs anyone.
                if not fut.running():
                    try:
                        fut.set_running_or_notify_cancel()
                    except (InvalidStateError, RuntimeError):
                        pass
                err = WorkerCrashed(
                    "serving worker crashed while request %s was "
                    "in flight (%r); the worker restarted — "
                    "resubmit" % (r.id, exc))
                err.__cause__ = exc   # the documented retryability probe
                try:
                    fut.set_exception(err)
                except InvalidStateError:
                    continue
                if ten is not None:
                    ten.stats.note_error()
                    if ten.slo is not None:
                        ten.slo.record(outcome="error")

    def _pick_tenant(self):
        """Name of the tenant to serve next: highest priority wins,
        oldest head request breaks ties — priority orders service,
        FIFO holds within a tenant. None when every queue is empty.
        Caller holds the condition lock."""
        best, best_key = None, None
        for name, q in self._queues.items():
            if not q:
                continue
            key = (-self._tenants[name].priority, q[0].t_submit)
            if best_key is None or key < best_key:
                best, best_key = name, key
        return best

    def _gather(self):
        """Block for the first request, pick its tenant, then coalesce
        more of THAT tenant's requests until its top bucket is full,
        the ``max_wait_ms`` window (from the first request) closes, or
        the next request would overflow the bucket. Returns ``(tenant,
        live requests)`` — live excludes expired, cancelled, and (for
        a breached tenant) shed requests — or None when shut down with
        an empty queue."""
        with self._cond:
            while True:
                name = self._pick_tenant()
                if name is not None:
                    break
                if self._closed:
                    return None
                # untimed: submit() and shutdown() both notify, so an
                # idle server parks instead of polling
                self._cond.wait()
            ten = self._tenants[name]
            q = self._queues[name]
            first = q.popleft()
            self._n_queued -= 1
            first.t_popped = time.perf_counter()
            # once popped, only this worker can resolve the future —
            # the supervision list is what the crash handler fails
            self._popped_tenant = ten
            self._popped.append(first)
            reqs, rows = [first], first.rows
            max_rows = ten.predictor.max_batch_size
            window_end = first.t_submit + self._max_wait
            while rows < max_rows:
                if q:
                    if rows + q[0].rows > max_rows:
                        break
                    nxt = q.popleft()
                    self._n_queued -= 1
                    nxt.t_popped = time.perf_counter()
                    self._popped.append(nxt)
                    reqs.append(nxt)
                    rows += nxt.rows
                    continue
                remaining = window_end - time.perf_counter()
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(remaining)
        from .. import telemetry
        tracing = telemetry.enabled()
        now = time.perf_counter()
        if ten.shed_active():
            # worker-side shed: the breach began (or was detected)
            # after these queued; dropping them now keeps a breached
            # tenant's backlog from occupying launches the healthy
            # tenants need. The queue age is a latency outcome the
            # client experienced — reservoir + shed histogram + trace.
            for r in reqs:
                age_ms = (now - r.t_submit) * 1000.0
                ten.stats.note_shed(age_ms)
                if tracing:
                    ten.stats.note_trace(
                        r.id, r.rows, None,
                        {"queue_wait_ms": age_ms}, outcome="shed")
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(TenantShed(
                        "request %s shed after %.1f ms in queue: "
                        "tenant %r is in SLO breach"
                        % (r.id, age_ms, ten.name)))
            return ten, []
        live = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                age_ms = (now - r.t_submit) * 1000.0
                # the miss IS a latency outcome: its age reaches the
                # reservoir/histogram (p99 must reflect overload) and
                # spends SLO error budget
                ten.stats.note_timeout(age_ms)
                if ten.slo is not None:
                    ten.slo.record(age_ms, "timeout")
                if tracing:
                    ten.stats.note_trace(
                        r.id, r.rows, None,
                        {"queue_wait_ms": age_ms}, outcome="timeout")
                if r.future.set_running_or_notify_cancel():
                    # guard like the live path: set_exception on a
                    # caller-CANCELLED future raises InvalidStateError
                    # and would kill the worker thread for good
                    r.future.set_exception(RequestTimeout(
                        "request %s expired after %.1f ms in queue"
                        % (r.id, age_ms)))
            elif r.future.set_running_or_notify_cancel():
                live.append(r)
        return ten, live

    def _launch(self, ten, reqs):
        import numpy as onp

        from .. import telemetry
        tracing = telemetry.enabled()
        total = sum(r.rows for r in reqs)
        if _faults.armed():
            # worker-death seam: raises OUTSIDE the per-launch error
            # handling below, so the exception escapes to the
            # supervisor exactly like an unexpected bug would
            _faults.check("serving.worker", tenant=ten.name,
                          rows=total, requests=len(reqs))
        t_launch = time.perf_counter()
        timing = {} if tracing else None
        try:
            if len(reqs) == 1:
                arrays = reqs[0].arrays
            else:
                names = list(reqs[0].arrays)
                arrays = {k: onp.concatenate([r.arrays[k] for r in reqs])
                          for k in names}
            outs = ten.predictor._predict_rows(arrays, total,
                                               timing=timing)
        except BaseException as e:  # noqa: B036 — futures must resolve
            for r in reqs:
                ten.stats.note_error()
                if ten.slo is not None:
                    ten.slo.record(outcome="error")
                if tracing:
                    self._trace(ten, r, None, timing, t_launch,
                                time.perf_counter(), outcome="error")
                r.future.set_exception(e)
            return
        t_outs = time.perf_counter()
        off = 0
        for r in reqs:
            res = [o[off:off + r.rows] for o in outs]
            off += r.rows
            r.future.set_result(res[0] if len(res) == 1 else res)
            now = time.perf_counter()
            lat_ms = (now - r.t_submit) * 1000.0
            ten.stats.note_completed(lat_ms)
            if ten.slo is not None:
                ten.slo.record(lat_ms, "ok")
            if tracing:
                self._trace(ten, r, ten.predictor.bucket_for(total),
                            timing, t_launch, t_outs, t_done=now)

    def _trace(self, ten, r, bucket, timing, t_launch, t_outs,
               t_done=None, outcome="ok"):
        """One request's phase decomposition. The shared launch phases
        (pad, device) are what every coalesced request experienced;
        queue/coalesce/resolve are the request's own clocks — so each
        trace's phase sum tracks ITS end-to-end latency."""
        timing = timing or {}
        t_done = t_outs if t_done is None else t_done
        phases = {
            "queue_wait_ms": (r.t_popped - r.t_submit) * 1000.0,
            "coalesce_wait_ms": (t_launch - r.t_popped) * 1000.0,
            "pad_ms": timing.get("pad_ms", 0.0),
            "device_ms": timing.get("device_ms", 0.0),
            # normalize/concat overhead before the pad plus the
            # slice-and-resolve after the outputs landed
            "resolve_ms": max(
                (t_done - t_launch) * 1000.0
                - timing.get("pad_ms", 0.0)
                - timing.get("device_ms", 0.0), 0.0),
        }
        ten.stats.note_trace(r.id, r.rows, bucket, phases,
                             outcome=outcome)

    def slo_breached(self, tenant=None):
        """Whether the named tenant's :class:`SLOTracker` reports an
        active multi-window burn-rate breach — or, with no name,
        whether ANY hosted tenant's does (False without trackers).
        This is the state the admission policy sheds on."""
        if tenant is not None:
            ten = self._resolve(tenant)
            return ten.slo is not None and ten.slo.breached()
        return any(t.slo is not None and t.slo.breached()
                   for t in self._tenants.values())
