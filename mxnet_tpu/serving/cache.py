"""Persistent serving compile cache — replica warm start as a
deserialize, not a recompile.

A new serving replica today cold-starts by compiling the entire bucket
ladder from scratch: on the bs128 ResNet-50 operating point that is
tens of seconds of XLA work per process before the first request is
served, which makes elastic autoscale against the ``slo.*`` burn-rate
gauges useless in practice. This module removes that wall in two
layers:

* **process-wide jax compilation cache** — ``MXNET_COMPILE_CACHE_DIR``
  (or :func:`enable_persistent_compile_cache`) points jax's own
  persistent compilation cache (``jax_compilation_cache_dir``) at a
  shared directory, so EVERY jit in the process — train step, augment
  program, serving buckets — reuses compiled artifacts across
  processes when the backend supports it.
* **explicit AOT executable cache** — ``Predictor.warmup(cache_dir=)``
  serializes each bucket's compiled program via
  ``jax.experimental.serialize_executable`` into an atomic,
  crc-verified :class:`ExecutableCache` entry. A second replica
  warming from the same directory deserializes every bucket and
  performs **zero** XLA compiles (CompileWatch-pinned), with served
  rows bitwise equal to the cold-start replica.

The cache key is the contract. An entry is keyed by

* ``params_digest`` — sha256 of the symbol JSON + every parameter's
  name/shape/dtype (:func:`mxnet_tpu.checkpoint.params_digest`, the
  SAME rule checkpoint manifests record), so an architecture drift
  refuses the entry while two checkpoints of one architecture share
  executables (parameter VALUES are runtime inputs);
* ``precision_mode`` — the resolved policy name; an executable built
  under ``int8_act``'s input quantization served under ``f32`` would
  be silent garbage, exactly the failure mode the keying must make
  impossible;
* ``bucket`` + ``input_sig`` — the padded batch size and the input
  row shapes/dtypes the program was specialized to;
* ``backend_sig`` — platform, device kind, device count, mesh axes,
  and the jax/jaxlib versions; executables are not portable across
  any of those.

Every mismatch path — drifted digest, wrong mode, different backend,
truncated or bit-flipped entry, a crashed ``.tmp-*`` partial — falls
back LOUDLY to a fresh compile (warning naming the drifted field); a
stale executable is never served silently. Entries commit with the
checkpoint subsystem's atomic idiom: write to a ``.tmp-*`` sibling,
fsync, ``os.replace`` — a ``.tmp-*`` file is structurally never
loadable.
"""
from __future__ import annotations

import glob
import json
import logging
import os
import pickle
import uuid
import zlib

__all__ = ["CacheMiss", "ExecutableCache", "cache_key",
           "enable_persistent_compile_cache", "backend_signature"]

_MAGIC = b"MXTPUEXEC1\n"
_FORMAT = 1
_TMP_PREFIX = ".tmp-"
_SUFFIX = ".mxexec"

logger = logging.getLogger("mxnet_tpu.serving")

# key fields that must match field-by-field for an entry to load; the
# order is the order mismatch warnings report them in
KEY_FIELDS = ("params_digest", "precision_mode", "bucket", "input_sig",
              "backend_sig")


def enable_persistent_compile_cache(cache_dir):
    """Point jax's process-wide persistent compilation cache at
    ``cache_dir`` (created if missing) and drop the min-compile-time /
    min-entry-size floors so the small serving-bucket programs qualify.
    Called automatically at import when ``MXNET_COMPILE_CACHE_DIR`` is
    set; safe to call again with the same directory. Returns True when
    the cache was wired, False when this jax build lacks it."""
    import jax
    cache_dir = os.path.abspath(str(cache_dir))
    os.makedirs(cache_dir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception as e:  # noqa: BLE001 - optional jax feature
        logger.warning("persistent compilation cache unavailable in "
                       "this jax build: %s", e)
        return False
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001 - knob name varies by version
            pass
    return True


def _autowire():
    """Import-time twin of :func:`enable_persistent_compile_cache`:
    honor ``MXNET_COMPILE_CACHE_DIR`` process-wide. The SAME directory
    also serves as the default AOT entry store for
    ``Predictor.warmup()`` (entries live under ``<dir>/aot/``)."""
    path = os.environ.get("MXNET_COMPILE_CACHE_DIR")
    if path:
        enable_persistent_compile_cache(path)


def backend_signature(mesh_axes=None, n_dev=1, device_kind=None,
                      platform=None):
    """The executable-portability boundary as one stable string:
    platform, device kind, device count, mesh layout, jax + jaxlib
    versions. Two processes agreeing on this string may exchange
    serialized executables; any component drift refuses the entry."""
    import jax
    import jaxlib
    if platform is None:
        platform = jax.default_backend()
    parts = [
        "platform=%s" % platform,
        "device_kind=%s" % (device_kind or ""),
        "n_dev=%d" % int(n_dev),
        "mesh=%s" % json.dumps(dict(mesh_axes or {}), sort_keys=True),
        "jax=%s" % jax.__version__,
        "jaxlib=%s" % getattr(jaxlib, "__version__", "?"),
    ]
    return ";".join(parts)


def cache_key(params_digest, precision_mode, bucket, input_sig,
              backend_sig):
    """The full entry key as a plain dict (KEY_FIELDS order)."""
    return {
        "params_digest": str(params_digest),
        "precision_mode": str(precision_mode),
        "bucket": int(bucket),
        "input_sig": str(input_sig),
        "backend_sig": str(backend_sig),
    }


def input_signature(data_descs):
    """Canonical string of the input ROW shapes the bucket programs
    are specialized to (batch dim excluded — that is the bucket)."""
    return ";".join("%s:%s" % (name, tuple(shape[1:]))
                    for name, shape in sorted(data_descs))


class CacheMiss(Exception):
    """An entry could not be loaded. ``reason`` is one of ``absent``
    (first run — informational), ``key-mismatch`` (an entry exists for
    this bucket but was built under a different key — loud), or
    ``corrupt`` (truncated / bit-flipped / unreadable — loud)."""

    def __init__(self, reason, detail=""):
        self.reason = reason
        self.detail = detail
        super().__init__("%s%s" % (reason, (": " + detail) if detail
                                   else ""))


def _entry_name(key):
    """Filename for a key: every key field participates (digest/mode
    spelled for humans, the full key hashed in), so a different key can
    never resolve to the same file — correctness by construction; the
    header check below is defense in depth."""
    import hashlib
    full = hashlib.sha256(
        "|".join(str(key[f]) for f in KEY_FIELDS)
        .encode("utf-8")).hexdigest()[:16]
    mode = "".join(c if c.isalnum() else "_"
                   for c in key["precision_mode"])[:24]
    return "%s-%s-b%d-%s%s" % (key["params_digest"][:12], mode,
                               key["bucket"], full, _SUFFIX)


class ExecutableCache(object):
    """Directory of atomic, crc-verified serialized-executable entries.

    One entry = one ``(payload, in_tree, out_tree)`` trio from
    ``jax.experimental.serialize_executable.serialize``, framed as::

        MXTPUEXEC1\\n
        <json header line: format, key fields, payload size, crc32>\\n
        <pickled payload bytes>

    Commit is atomic (``.tmp-*`` sibling + fsync + ``os.replace``, the
    checkpoint subsystem's idiom); readers only ever open the exact
    final name, so a crashed partial is invisible — ``.tmp-*`` is never
    loadable, structurally and by the explicit guard in :meth:`load`.
    """

    def __init__(self, directory):
        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)

    # -- paths ----------------------------------------------------------
    def path_for(self, key):
        return os.path.join(self.directory, _entry_name(key))

    def entries(self):
        """Committed entry filenames (``.tmp-*`` partials excluded)."""
        return sorted(
            os.path.basename(p)
            for p in glob.glob(os.path.join(self.directory,
                                            "*" + _SUFFIX))
            if not os.path.basename(p).startswith(_TMP_PREFIX))

    def sweep_partials(self):
        """Remove crashed ``.tmp-*`` partials (writer-side hygiene)."""
        for p in glob.glob(os.path.join(self.directory,
                                        _TMP_PREFIX + "*")):
            try:
                os.remove(p)
            except OSError:
                pass

    # -- store ----------------------------------------------------------
    def store(self, key, payload, in_tree, out_tree):
        """Commit one entry atomically; returns its path. The pickled
        blob carries the serialized executable plus its arg/result
        treedefs (both picklable in jax>=0.4)."""
        from ..checkpoint.serialize import fsync_dir
        blob = pickle.dumps((payload, in_tree, out_tree),
                            protocol=pickle.HIGHEST_PROTOCOL)
        header = dict(key)
        header["format"] = _FORMAT
        header["size"] = len(blob)
        header["crc32"] = zlib.crc32(blob) & 0xFFFFFFFF
        final = self.path_for(key)
        tmp = os.path.join(self.directory, "%s%s-%s" % (
            _TMP_PREFIX, os.path.basename(final), uuid.uuid4().hex[:8]))
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(json.dumps(header, sort_keys=True).encode("utf-8"))
            f.write(b"\n")
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        fsync_dir(self.directory)
        from .. import faults as _faults
        if _faults.armed():
            # poisoned-entry seam: corrupt the COMMITTED entry (a
            # storage fault after a clean commit) — the next replica's
            # load must refuse it loudly (CacheMiss "corrupt") and
            # fall back to a fresh compile, never serve stale bytes
            _faults.corrupt_file("serving.cache", self.directory,
                                 pattern=os.path.basename(final),
                                 bucket=key["bucket"])
        return final

    # -- load -----------------------------------------------------------
    def load(self, key):
        """Load and verify one entry -> ``(payload, in_tree,
        out_tree)``. Raises :class:`CacheMiss` on any failure —
        ``key-mismatch`` names the drifted field(s) when an entry for
        this bucket exists under a different key, so the fallback
        compile is loud about WHY."""
        path = self.path_for(key)
        name = os.path.basename(path)
        if name.startswith(_TMP_PREFIX):   # structural; belt and braces
            raise CacheMiss("corrupt", "refusing .tmp-* partial %s"
                            % name)
        if not os.path.exists(path):
            drift = self._describe_drift(key)
            if drift:
                raise CacheMiss("key-mismatch", drift)
            raise CacheMiss("absent", name)
        try:
            with open(path, "rb") as f:
                magic = f.read(len(_MAGIC))
                if magic != _MAGIC:
                    raise CacheMiss("corrupt", "%s: bad magic" % name)
                header = json.loads(f.readline().decode("utf-8"))
                blob = f.read()
        except CacheMiss:
            raise
        except Exception as e:  # noqa: BLE001 - any read/parse failure
            raise CacheMiss("corrupt", "%s: %s" % (name, e)) from e
        if header.get("format") != _FORMAT:
            raise CacheMiss("corrupt", "%s: format %r" % (
                name, header.get("format")))
        bad = [f for f in KEY_FIELDS if header.get(f) != key[f]]
        if bad:
            raise CacheMiss("key-mismatch", "%s: header disagrees on %s"
                            % (name, ", ".join(bad)))
        if len(blob) != header.get("size"):
            raise CacheMiss("corrupt", "%s: truncated (%d of %s bytes)"
                            % (name, len(blob), header.get("size")))
        if (zlib.crc32(blob) & 0xFFFFFFFF) != header.get("crc32"):
            raise CacheMiss("corrupt", "%s: crc32 mismatch" % name)
        try:
            payload, in_tree, out_tree = pickle.loads(blob)
        except Exception as e:  # noqa: BLE001 - any unpickle failure
            raise CacheMiss("corrupt", "%s: unpickle: %s"
                            % (name, e)) from e
        return payload, in_tree, out_tree

    def _describe_drift(self, key):
        """When the exact entry is absent but OTHER entries exist for
        this bucket, say which key fields drifted (the loud half of the
        fallback). Returns "" when the directory simply has no entry
        for the bucket (a plain first-run miss)."""
        want_b = "-b%d-" % key["bucket"]
        for name in self.entries():
            if want_b not in name:
                continue
            try:
                with open(os.path.join(self.directory, name), "rb") as f:
                    if f.read(len(_MAGIC)) != _MAGIC:
                        continue
                    header = json.loads(f.readline().decode("utf-8"))
            except Exception:  # noqa: BLE001 - diagnostics only
                continue
            bad = [fld for fld in KEY_FIELDS
                   if header.get(fld) != key[fld]]
            if bad:
                return ("entry %s exists for bucket %d but was built "
                        "under a different %s (e.g. %s=%r, want %r)"
                        % (name, key["bucket"], ", ".join(bad), bad[0],
                           header.get(bad[0]), key[bad[0]]))
        return ""
