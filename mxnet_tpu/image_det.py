"""Detection data pipeline: box-aware augmentation + RecordIO iterator.

Reference counterparts:
- ``src/io/image_det_aug_default.cc`` (DefaultImageDetAugmenter +
  ImageDetLabel): random crop samplers with IOU/coverage constraints,
  box-projecting pad, coordinate-flipping mirror, force/shrink/fit resize.
- ``src/io/iter_image_det_recordio.cc`` (ImageDetRecordIter): recordio
  parsing of variable-length detection labels + batching with -1 padding.

Host-side work (decode + augmentation geometry) is numpy on the CPU — the
same division of labor as the reference's OpenCV path; the device only
sees the assembled batch.

Label wire format (image_det_aug_default.cc:238-261)::

    [header_width, object_width, (extra header...),
     id, xmin, ymin, xmax, ymax, (extra...),   # object 0
     id, xmin, ymin, xmax, ymax, (extra...),   # object 1 ...]

Coordinates are normalized to [0, 1] relative to the image.
"""
from __future__ import annotations

import random

import numpy as onp

from . import ndarray as nd
from . import recordio
from .io import DataBatch, DataDesc, DataIter
from .image import _resize, imdecode

__all__ = ["DetLabel", "DetAugmenter", "ImageDetRecordIter"]


class DetLabel(object):
    """Structured view of a raw detection label vector (ImageDetLabel,
    image_det_aug_default.cc:194). Objects are an (N, object_width) float
    array with columns [id, xmin, ymin, xmax, ymax, extra...]."""

    def __init__(self, raw):
        raw = onp.asarray(raw, dtype=onp.float32).ravel()
        if raw.size < 7:
            raise ValueError("detection label needs >= 7 floats "
                             "(2 header + 5 per object), got %d" % raw.size)
        header_width = int(raw[0])
        self.object_width = int(raw[1])
        if header_width < 2 or self.object_width < 5:
            raise ValueError("invalid detection label header (%d, %d)"
                             % (header_width, self.object_width))
        body = raw[header_width:]
        if body.size % self.object_width:
            raise ValueError("label body %d not divisible by object width "
                             "%d" % (body.size, self.object_width))
        self.header = raw[:header_width].copy()
        self.objects = body.reshape(-1, self.object_width).copy()

    def to_array(self):
        return onp.concatenate([self.header, self.objects.ravel()])

    # ------------------------------------------------------------ geometry
    def project(self, box):
        """Re-express all boxes relative to region ``box`` = (x, y, w, h),
        clipping to [0, 1] (ImageDetObject::Project)."""
        x, y, w, h = box
        o = self.objects
        o[:, 1] = onp.maximum(0.0, (o[:, 1] - x) / w)
        o[:, 2] = onp.maximum(0.0, (o[:, 2] - y) / h)
        o[:, 3] = onp.minimum(1.0, (o[:, 3] - x) / w)
        o[:, 4] = onp.minimum(1.0, (o[:, 4] - y) / h)

    def mirror(self):
        """Flip x-coordinates (ImageDetObject::HorizontalFlip)."""
        o = self.objects
        left = 1.0 - o[:, 3].copy()
        o[:, 3] = 1.0 - o[:, 1]
        o[:, 1] = left

    def _ious(self, box):
        x, y, w, h = box
        o = self.objects
        ix = onp.maximum(0.0, onp.minimum(o[:, 3], x + w)
                         - onp.maximum(o[:, 1], x))
        iy = onp.maximum(0.0, onp.minimum(o[:, 4], y + h)
                         - onp.maximum(o[:, 2], y))
        inter = ix * iy
        area_o = (o[:, 3] - o[:, 1]) * (o[:, 4] - o[:, 2])
        return inter, area_o

    def try_crop(self, box, min_overlap=0.0, max_overlap=1.0,
                 min_sample_coverage=0.0, max_sample_coverage=1.0,
                 min_object_coverage=0.0, max_object_coverage=1.0,
                 emit_mode="center", emit_overlap_thresh=0.3):
        """Validate crop ``box`` against the constraint set; on success,
        drop boxes outside the crop (per ``emit_mode``) and project the
        rest. Returns False (unmodified) if constraints fail or no box
        survives (ImageDetLabel::TryCrop)."""
        if len(self.objects) == 0:
            return True
        x, y, w, h = box
        inter, area_o = self._ious(box)
        area_c = w * h
        iou = inter / (area_c + area_o - inter + 1e-12)
        cov_sample = inter / (area_c + 1e-12)
        cov_object = inter / (area_o + 1e-12)
        constrained = (min_overlap > 0.0 or max_overlap < 1.0
                       or min_sample_coverage > 0.0
                       or max_sample_coverage < 1.0
                       or min_object_coverage > 0.0
                       or max_object_coverage < 1.0)
        if constrained:
            ok = onp.ones(len(self.objects), dtype=bool)
            if min_overlap > 0.0 or max_overlap < 1.0:
                ok &= (iou >= min_overlap) & (iou <= max_overlap)
            if min_sample_coverage > 0.0 or max_sample_coverage < 1.0:
                ok &= ((cov_sample >= min_sample_coverage)
                       & (cov_sample <= max_sample_coverage))
            if min_object_coverage > 0.0 or max_object_coverage < 1.0:
                ok &= ((cov_object >= min_object_coverage)
                       & (cov_object <= max_object_coverage))
            if not ok.any():
                return False
        # emit: which boxes stay in the cropped sample
        if emit_mode == "center":
            cx = (self.objects[:, 1] + self.objects[:, 3]) * 0.5
            cy = (self.objects[:, 2] + self.objects[:, 4]) * 0.5
            keep = ((cx >= x) & (cx <= x + w) & (cy >= y) & (cy <= y + h))
        elif emit_mode == "overlap":
            keep = cov_object > emit_overlap_thresh
        else:
            raise ValueError("unknown crop_emit_mode %r" % emit_mode)
        if not keep.any():
            return False
        self.objects = self.objects[keep]
        self.project(box)
        return True

    def try_pad(self, box):
        """Project boxes into the enlarged canvas ``box`` (TryPad)."""
        self.project(box)
        return True


class DetAugmenter(object):
    """Box-aware augmentation chain (DefaultImageDetAugmenter,
    image_det_aug_default.cc:383-660). Applies, in reference order:
    color jitter -> mirror -> pad -> crop samplers -> resize mode."""

    def __init__(self, data_shape,
                 resize=-1,
                 rand_crop_prob=0.0, num_crop_sampler=1,
                 min_crop_scales=(0.0,), max_crop_scales=(1.0,),
                 min_crop_aspect_ratios=(1.0,), max_crop_aspect_ratios=(1.0,),
                 min_crop_overlaps=(0.0,), max_crop_overlaps=(1.0,),
                 min_crop_sample_coverages=(0.0,),
                 max_crop_sample_coverages=(1.0,),
                 min_crop_object_coverages=(0.0,),
                 max_crop_object_coverages=(1.0,),
                 max_crop_trials=(25,),
                 crop_emit_mode="center", emit_overlap_thresh=0.3,
                 rand_pad_prob=0.0, max_pad_scale=1.0, fill_value=127,
                 rand_mirror_prob=0.0,
                 random_brightness_prob=0.0, max_random_brightness=0.0,
                 random_contrast_prob=0.0, max_random_contrast=0.0,
                 resize_mode="force", seed=0):
        def per_sampler(v):
            v = list(v) if isinstance(v, (list, tuple)) else [v]
            if num_crop_sampler > 1 and len(v) == 1:
                v = v * num_crop_sampler
            if len(v) != num_crop_sampler:
                raise ValueError("# of parameters/crop_samplers mismatch")
            return v

        self.data_shape = tuple(data_shape)
        self.resize = resize
        self.rand_crop_prob = rand_crop_prob
        self.num_crop_sampler = num_crop_sampler
        self.min_crop_scales = per_sampler(min_crop_scales)
        self.max_crop_scales = per_sampler(max_crop_scales)
        self.min_crop_aspect_ratios = per_sampler(min_crop_aspect_ratios)
        self.max_crop_aspect_ratios = per_sampler(max_crop_aspect_ratios)
        self.min_crop_overlaps = per_sampler(min_crop_overlaps)
        self.max_crop_overlaps = per_sampler(max_crop_overlaps)
        self.min_crop_sample_coverages = per_sampler(
            min_crop_sample_coverages)
        self.max_crop_sample_coverages = per_sampler(
            max_crop_sample_coverages)
        self.min_crop_object_coverages = per_sampler(
            min_crop_object_coverages)
        self.max_crop_object_coverages = per_sampler(
            max_crop_object_coverages)
        self.max_crop_trials = per_sampler(max_crop_trials)
        self.crop_emit_mode = crop_emit_mode
        self.emit_overlap_thresh = emit_overlap_thresh
        self.rand_pad_prob = rand_pad_prob
        self.max_pad_scale = max_pad_scale
        self.fill_value = fill_value
        self.rand_mirror_prob = rand_mirror_prob
        self.random_brightness_prob = random_brightness_prob
        self.max_random_brightness = max_random_brightness
        self.random_contrast_prob = random_contrast_prob
        self.max_random_contrast = max_random_contrast
        self.resize_mode = resize_mode
        self.rng = random.Random(seed)

    # ------------------------------------------------------------- pieces
    def _generate_crop_box(self, idx, img_aspect, r=None):
        """GenerateCropBox (image_det_aug_default.cc:459)."""
        r = r if r is not None else self.rng
        scale = r.uniform(self.min_crop_scales[idx],
                         self.max_crop_scales[idx]) + 1e-12
        min_ratio = max(self.min_crop_aspect_ratios[idx] / img_aspect,
                        scale * scale)
        max_ratio = min(self.max_crop_aspect_ratios[idx] / img_aspect,
                        1.0 / (scale * scale))
        if min_ratio > max_ratio:
            return None
        ratio = (r.uniform(min_ratio, max_ratio)) ** 0.5
        w = min(1.0, scale * ratio)
        h = min(1.0, scale / ratio)
        x0 = r.uniform(0.0, 1.0 - w)
        y0 = r.uniform(0.0, 1.0 - h)
        return (x0, y0, w, h)

    def _generate_pad_box(self, threshold=1.05, r=None):
        """GeneratePadBox (image_det_aug_default.cc:479)."""
        r = r if r is not None else self.rng
        scale = r.uniform(1.0, self.max_pad_scale)
        if scale < threshold:
            return None
        x0 = r.uniform(0.0, scale - 1.0)
        y0 = r.uniform(0.0, scale - 1.0)
        return (-x0, -y0, scale, scale)

    # -------------------------------------------------------------- apply
    def __call__(self, img, label, rng=None):
        """img: HWC uint8; label: DetLabel (modified in place). Returns the
        augmented image (reference Process, same op order). ``rng`` lets
        callers pass a per-sample engine (the reference keeps per-thread
        prnds_[tid]) so threaded decode stays deterministic."""
        r = rng if rng is not None else self.rng
        if self.resize > 0:
            h, w = img.shape[:2]
            if h > w:
                img = _resize(img, self.resize, self.resize * h // w)
            else:
                img = _resize(img, self.resize * w // h, self.resize)

        # color jitter (boxes unaffected)
        if (self.random_brightness_prob > 0
                and r.random() < self.random_brightness_prob):
            delta = r.uniform(-1, 1) * self.max_random_brightness
            img = onp.clip(img.astype(onp.float32) + delta, 0,
                           255).astype(onp.uint8)
        if (self.random_contrast_prob > 0
                and r.random() < self.random_contrast_prob):
            c = r.uniform(-1, 1) * self.max_random_contrast
            img = onp.clip(img.astype(onp.float32) * (1.0 + c), 0,
                           255).astype(onp.uint8)

        # mirror
        if (self.rand_mirror_prob > 0
                and r.random() < self.rand_mirror_prob):
            label.mirror()
            img = img[:, ::-1]

        # pad out to a larger canvas, boxes projected into it
        if self.rand_pad_prob > 0 and self.max_pad_scale > 1.0:
            if r.random() < self.rand_pad_prob:
                box = self._generate_pad_box(r=r)
                if box is not None:
                    label.try_pad(box)
                    x, y, s = box[0], box[1], box[2]
                    h, w = img.shape[:2]
                    canvas = onp.full((int(s * h), int(s * w), img.shape[2]),
                                      self.fill_value, dtype=img.dtype)
                    top, left = int(-y * h), int(-x * w)
                    canvas[top:top + h, left:left + w] = img
                    img = canvas

        # constrained random crop: shuffle samplers, first success wins
        if self.rand_crop_prob > 0 and self.num_crop_sampler > 0:
            if r.random() < self.rand_crop_prob:
                order = list(range(self.num_crop_sampler))
                r.shuffle(order)
                done = False
                for idx in order:
                    if done:
                        break
                    for _ in range(self.max_crop_trials[idx]):
                        h, w = img.shape[:2]
                        box = self._generate_crop_box(idx, w / h, r=r)
                        if box is None:
                            continue
                        x, y, bw, bh = box
                        # reject degenerate sub-pixel crops before the
                        # label commit: the final resize can't handle a
                        # 0-sized slice
                        y0, y1 = int(y * h), int((y + bh) * h)
                        x0, x1 = int(x * w), int((x + bw) * w)
                        if y1 - y0 < 1 or x1 - x0 < 1:
                            continue
                        if label.try_crop(
                                box, self.min_crop_overlaps[idx],
                                self.max_crop_overlaps[idx],
                                self.min_crop_sample_coverages[idx],
                                self.max_crop_sample_coverages[idx],
                                self.min_crop_object_coverages[idx],
                                self.max_crop_object_coverages[idx],
                                self.crop_emit_mode,
                                self.emit_overlap_thresh):
                            img = img[y0:y1, x0:x1]
                            done = True
                            break

        # final resize to data_shape
        _, th, tw = self.data_shape
        h, w = img.shape[:2]
        if self.resize_mode == "force":
            img = _resize(img, tw, th)
        elif self.resize_mode in ("shrink", "fit"):
            if self.resize_mode == "fit" or h > th or w > tw:
                ratio = min(th / h, tw / w)
                img = _resize(img, max(1, int(w * ratio)),
                              max(1, int(h * ratio)))
            # place into the fixed canvas and project boxes into it
            h, w = img.shape[:2]
            canvas = onp.full((th, tw, img.shape[2]), self.fill_value,
                              dtype=img.dtype)
            canvas[:h, :w] = img
            label.project((0.0, 0.0, tw / w, th / h))
            img = canvas
        else:
            raise ValueError("unknown resize_mode %r" % self.resize_mode)
        return img


class ImageDetRecordIter(DataIter):
    """RecordIO detection iterator (iter_image_det_recordio.cc:563).

    Emits data (B, C, H, W) float32 and label (B, max_objects,
    object_width): each row [id, xmin, ymin, xmax, ymax, extra...], rows
    padded with -1 (the reference's BatchLoader pads the flattened vector
    the same way; MultiBoxTarget treats id<0 as padding).
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 label_pad_width=0, shuffle=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0,
                 round_batch=True, data_name="data", label_name="label",
                 preprocess_threads=4, seed=0, **aug_kwargs):
        from concurrent.futures import ThreadPoolExecutor

        from . import runtime
        super().__init__(batch_size)
        # mmap'd indexed reads + threaded decode, same machinery as
        # ImageRecordIter (the reference's parser/prefetcher split)
        self.rec = runtime.RecordFile(path_imgrec)
        self.pool = ThreadPoolExecutor(max_workers=preprocess_threads)
        self.data_shape = tuple(data_shape)
        self.shuffle = shuffle
        self.round_batch = round_batch
        self.mean = onp.array([mean_r, mean_g, mean_b], onp.float32)
        self.std = onp.array([std_r, std_g, std_b], onp.float32)
        self.scale = scale
        self.rng = random.Random(seed)
        self._base_seed = seed
        self._epoch = -1  # reset() below brings it to 0
        self.aug = DetAugmenter(data_shape, seed=seed, **aug_kwargs)

        # scan for max label width (iter_image_det_recordio.cc:270
        # max_label_width pass) unless caller fixed label_pad_width
        self.object_width = None
        max_obj = 1
        for i in range(len(self.rec)):
            header, _ = recordio.unpack(self.rec.read(i))
            lab = DetLabel(onp.asarray(header.label))
            if self.object_width is None:
                self.object_width = lab.object_width
            elif self.object_width != lab.object_width:
                raise ValueError("inconsistent object widths in recordio")
            max_obj = max(max_obj, len(lab.objects))
        if self.object_width is None:
            raise ValueError("empty detection recordio %s" % path_imgrec)
        if label_pad_width:
            padded_obj = (label_pad_width // self.object_width)
            if padded_obj < max_obj:
                raise ValueError(
                    "label_pad_width %d too small for %d objects of width "
                    "%d" % (label_pad_width, max_obj, self.object_width))
            max_obj = padded_obj
        self.max_objects = max_obj

        self.seq = list(range(len(self.rec)))
        self.cur = 0
        self.data_name = data_name
        self.label_name = label_name
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc(
            label_name, (batch_size, self.max_objects, self.object_width))]
        self.reset()

    def reset(self):
        if self.shuffle:
            self.rng.shuffle(self.seq)
        self.cur = 0
        self._epoch += 1

    def _load_one(self, idx):
        header, payload = recordio.unpack(self.rec.read(idx))
        if payload[:6] == b"\x93NUMPY":
            # raw-npy fallback payload written by pack_img without cv2
            import io as _io
            img = onp.load(_io.BytesIO(bytes(payload)), allow_pickle=False)
        else:
            img = imdecode(payload)  # RGB
        if img.ndim == 2:
            img = onp.stack([img] * 3, axis=-1)
        label = DetLabel(onp.asarray(header.label))
        # per-sample engine keyed on (iterator seed, sample, epoch):
        # deterministic regardless of decode-thread scheduling (the
        # reference keeps per-thread prnds_[tid])
        rng = random.Random(hash((self._base_seed, idx, self._epoch)))
        img = self.aug(img, label, rng=rng)
        out = onp.full((self.max_objects, self.object_width), -1.0,
                       onp.float32)
        n = min(len(label.objects), self.max_objects)
        out[:n] = label.objects[:n]
        return img, out

    def next(self):
        if self.cur >= len(self.seq):
            raise StopIteration
        idxs = self.seq[self.cur:self.cur + self.batch_size]
        self.cur += self.batch_size
        pad = self.batch_size - len(idxs)
        if pad > 0:
            # the batch is ALWAYS full-size (provide_data contract); pad
            # says how many tail entries are filler. round_batch wraps to
            # the head (reference BatchLoader round_batch_), otherwise the
            # last real sample repeats.
            idxs = idxs + (self.seq[:pad] if self.round_batch
                           else [idxs[-1]] * pad)
        samples = list(self.pool.map(self._load_one, idxs))
        imgs = onp.stack([s[0] for s in samples]).astype(onp.float32)
        imgs = (imgs - self.mean) / (self.std / self.scale)
        data = imgs.transpose(0, 3, 1, 2)
        labels = onp.stack([s[1] for s in samples])
        return DataBatch([nd.array(data)], [nd.array(labels)], pad=pad,
                         index=onp.asarray(idxs, dtype=onp.int64))
