"""Network visualization (python/mxnet/visualization.py): print_summary +
plot_network (graphviz optional — falls back to returning DOT source).
"""
from __future__ import annotations

import json

from .symbol import Symbol

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64,
                                                                  .74, 1.)):
    """Print a layer summary table (visualization.py print_summary)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    aux_names = set(symbol.list_auxiliary_states())
    counted = set()  # variable node ids already attributed (weight tying)

    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)

    total_params = 0

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        if op != "null":
            inputs = node["inputs"]
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                if input_node["op"] != "null" or item[0] in heads:
                    pre_node.append(input_name)
        nonlocal total_params
        cur_param = 0
        if op != "null":
            for item in node["inputs"]:
                input_node = nodes[item[0]]
                # trainable parameters only: skip data/labels, BN moving
                # stats (auxiliary states), and variables already counted
                # at another consumer (weight tying)
                if input_node["op"] == "null" and \
                        not input_node["name"].endswith("label") and \
                        input_node["name"] != "data" and \
                        input_node["name"] not in aux_names and \
                        item[0] not in counted:
                    # a variable's internal output is named either bare
                    # or with the _output suffix depending on position
                    vshape = shape_dict.get(input_node["name"]) or \
                        shape_dict.get(input_node["name"] + "_output")
                    if vshape:
                        counted.add(item[0])
                        n = 1
                        for d in vshape:
                            n *= int(d)
                        cur_param += n
        total_params += cur_param
        name = node["name"]
        first_connection = "" if not pre_node else pre_node[0]
        fields = ["%s(%s)" % (name, op), str(out_shape), cur_param,
                  first_connection]
        print_row(fields, positions)
        for i in range(1, len(pre_node)):
            fields = ["", "", "", pre_node[i]]
            print_row(fields, positions)

    heads = set(h[0] for h in conf["heads"])
    for node in nodes:
        out_shape = None
        op = node["op"]
        if op != "null":
            key = node["name"] + "_output"
            if show_shape and key in shape_dict:
                out_shape = shape_dict[key]
        print_layer_summary(node, out_shape)
    print("=" * line_length)
    if show_shape:
        print("Total params: {:,}".format(total_params))
        print("_" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph (or DOT text if graphviz isn't installed)."""
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    aux_names = set(symbol.list_auxiliary_states())
    counted = set()  # variable node ids already attributed (weight tying)
    hidden = set()
    if hide_weights:
        for node in nodes:
            if node["op"] == "null" and (
                    node["name"].endswith("_weight")
                    or node["name"].endswith("_bias")
                    or node["name"].endswith("_gamma")
                    or node["name"].endswith("_beta")
                    or node["name"].endswith("_moving_mean")
                    or node["name"].endswith("_moving_var")):
                hidden.add(node["name"])

    lines = ["digraph %s {" % title.replace(" ", "_")]
    for i, node in enumerate(nodes):
        if node["name"] in hidden:
            continue
        label = node["name"] if node["op"] == "null" else \
            "%s\\n%s" % (node["op"], node["name"])
        shape_attr = "oval" if node["op"] == "null" else "box"
        lines.append('  n%d [label="%s", shape=%s];' % (i, label, shape_attr))
    for i, node in enumerate(nodes):
        for item in node.get("inputs", []):
            src = nodes[item[0]]
            if src["name"] in hidden:
                continue
            lines.append("  n%d -> n%d;" % (item[0], i))
    lines.append("}")
    dot_src = "\n".join(lines)
    try:
        from graphviz import Source
        return Source(dot_src, format=save_format)
    except ImportError:
        return dot_src
