"""Operator registry — the single registration point per op.

TPU-native redesign of the reference's *two* registration regimes (NNVM
``FCompute`` stateless ops + legacy stateful ``OperatorProperty``,
include/mxnet/op_attr_types.h:33-63 and include/mxnet/operator.h): here every
op is one record with

* ``fcompute(attrs, inputs, octx) -> [jnp outputs]`` — a pure JAX function
  (jnp/lax/pallas).  Gradients come from whole-graph ``jax.vjp`` so no per-op
  backward registration exists; ops with non-standard gradients (losses whose
  backward ignores head grads, e.g. SoftmaxOutput) wrap themselves in
  ``jax.custom_vjp`` inside their fcompute.
* shape/type inference: by default derived automatically with
  ``jax.eval_shape`` over fcompute; layer ops that must infer *parameter*
  shapes from data (FullyConnected's weight etc.) register a custom
  ``infer_shape`` with the reference's bidirectional-fill contract
  (returns (in_shapes, out_shapes, aux_shapes)).
* aux state (BatchNorm moving stats): declared via ``aux_names``; fcompute
  receives aux arrays appended to inputs and returns aux updates appended to
  outputs (the executor writes them back, replacing FMutateInputs).
* randomness: ``needs_rng`` ops receive a JAX PRNG key in ``octx.rng``
  (replaces the per-ctx kRandom resource, include/mxnet/resource.h:18-24).
"""
from __future__ import annotations

import ast
import re

import numpy as onp

from .base import MXNetError

__all__ = ["OpDef", "OpContext", "register", "get_op", "list_ops", "parse_attrs"]

_OP_REGISTRY = {}


class OpContext:
    """Per-invocation context handed to fcompute.

    Replaces the reference OpContext (include/mxnet/op_attr_types.h) —
    is_train flag + RunContext/Resources — with is_train + a PRNG key.
    """

    __slots__ = ("is_train", "rng")

    def __init__(self, is_train=False, rng=None):
        self.is_train = is_train
        self.rng = rng


class OpDef:
    """One registered operator."""

    def __init__(self, name, fcompute, arg_names=("data",), out_names=("output",),
                 aux_names=(), attr_types=None, infer_shape=None,
                 needs_rng=False, variable_args=None, num_outputs=None,
                 alias=(), backward_ignores_head_grads=False,
                 required_attrs=()):
        self.name = name
        self.fcompute = fcompute
        # arg_names may be a callable(attrs) -> names for ops whose input
        # list depends on attrs (no_bias, prelu's gamma, ...), mirroring
        # OperatorProperty::ListArguments(param).
        self.arg_names = arg_names if callable(arg_names) else tuple(arg_names)
        self.out_names = tuple(out_names)
        self.aux_names = tuple(aux_names)
        self.attr_types = attr_types or {}
        self._infer_shape = infer_shape
        self.needs_rng = needs_rng
        # attr key holding the (variable) number of inputs, e.g. Concat's
        # ``num_args`` (key_var_num_args in the reference registry).
        self.variable_args = variable_args
        self._num_outputs = num_outputs  # int, or callable(attrs)->int
        self.alias = tuple(alias)
        self.backward_ignores_head_grads = backward_ignores_head_grads
        # attrs with no usable default (dmlc::Parameter's .set_default-less
        # fields report "required" through GetAtomicSymbolInfo)
        self.required_attrs = tuple(required_attrs)

    # -- arity -------------------------------------------------------------
    def list_arguments(self, attrs=None):
        if self.variable_args is not None:
            n = int((attrs or {}).get(self.variable_args, 1))
            return ["arg%d" % i for i in range(n)]
        if callable(self.arg_names):
            return list(self.arg_names(attrs or {}))
        return list(self.arg_names)

    def list_outputs(self, attrs=None):
        n = self.num_outputs(attrs)
        if n == len(self.out_names):
            return list(self.out_names)
        return ["%s%d" % (self.out_names[0], i) for i in range(n)]

    def list_auxiliary_states(self, attrs=None):
        return list(self.aux_names)

    def num_inputs(self, attrs=None):
        return len(self.list_arguments(attrs))

    def num_outputs(self, attrs=None):
        n = self._num_outputs
        if n is None:
            return len(self.out_names)
        if callable(n):
            return n(attrs or {})
        return n

    # -- inference ---------------------------------------------------------
    def infer_shape(self, attrs, in_shapes, aux_shapes=None):
        """Return (in_shapes, out_shapes, aux_shapes), filling unknowns.

        Mirrors OperatorProperty::InferShape's bidirectional contract
        (include/mxnet/operator.h); defaults to forward-only inference via
        jax.eval_shape when every input shape is known.
        """
        if self._infer_shape is not None:
            return self._infer_shape(attrs, list(in_shapes),
                                     list(aux_shapes or []))
        if any(s is None for s in in_shapes):
            return list(in_shapes), None, list(aux_shapes or [])
        out_shapes = [s.shape for s in self.abstract_eval(
            attrs, [_ShapeOnly(s) for s in in_shapes])]
        return list(in_shapes), out_shapes, list(aux_shapes or [])

    def abstract_eval(self, attrs, in_avals, is_train=False):
        """jax.eval_shape over fcompute; returns list of ShapeDtypeStruct."""
        import jax

        structs = [jax.ShapeDtypeStruct(a.shape, getattr(a, "dtype", onp.float32))
                   for a in in_avals]
        octx = OpContext(is_train=is_train,
                         rng=jax.ShapeDtypeStruct((2,), onp.uint32)
                         if self.needs_rng else None)

        def f(*xs):
            outs = self.fcompute(attrs, list(xs), octx)
            return tuple(outs)

        return list(jax.eval_shape(f, *structs))

    def __repr__(self):
        return "OpDef(%s)" % self.name


class _ShapeOnly:
    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype=onp.float32):
        self.shape = tuple(shape)
        self.dtype = dtype


def f32_precision(x):
    """Matmul/conv precision for mxnet float32 semantics on TPU.

    XLA:TPU lowers f32 contractions to bf16xbf16 passes by default
    (~1e-2 relative error); the reference's f32 ops compute true f32 on
    GPU, so f32 inputs here request 'highest' (float32 accumulation).
    bf16/other dtypes keep the default fast path — the bench's
    compute_dtype="bfloat16" route is unaffected. Verified by
    tools/check_consistency_tpu.py (cpu<->tpu oracle).
    """
    import numpy as _np
    return "highest" if _np.dtype(x.dtype) == _np.float32 else None


def register(name, **kwargs):
    """Decorator: register ``fcompute`` under ``name`` (+ aliases)."""

    def _reg(fcompute):
        op = OpDef(name, fcompute, **kwargs)
        _OP_REGISTRY[name] = op
        for a in op.alias:
            _OP_REGISTRY[a] = op
        return fcompute

    return _reg


def get_op(name):
    try:
        return _OP_REGISTRY[name]
    except KeyError:
        raise MXNetError("Operator %s is not registered" % name)


def list_ops():
    return sorted(_OP_REGISTRY)


# ---------------------------------------------------------------------------
# attr parsing — replaces dmlc::Parameter string reflection
# ---------------------------------------------------------------------------
_TUPLE_RE = re.compile(r"^\(.*\)$|^\[.*\]$")


def _parse_value(v, ty=None):
    if ty is not None and not isinstance(v, str):
        if ty is bool:
            return bool(v)
        if ty in (int, float):
            return ty(v)
        if ty is tuple and isinstance(v, (list, tuple)):
            return tuple(v)
        if ty is str:
            return str(v)
        return v
    if not isinstance(v, str):
        return v
    s = v.strip()
    if ty is str:
        return s
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        val = ast.literal_eval(s)
        if isinstance(val, list):
            val = tuple(val)
        if ty is not None and ty is not tuple and not isinstance(val, tuple):
            try:
                val = ty(val)
            except (TypeError, ValueError):
                pass
        return val
    except (ValueError, SyntaxError):
        return s


def parse_attrs(op, attrs):
    """Parse raw attrs (possibly all-string, from JSON) to typed python."""
    out = {}
    for k, v in attrs.items():
        out[k] = _parse_value(v, op.attr_types.get(k))
    return out


# ---------------------------------------------------------------------------
# current device mesh — how mesh-aware ops (MoE, RingAttention) learn the
# sharding context they trace under.  MeshExecutorGroup wraps its
# evaluator closures in use_mesh(mesh), so the contextvar is set exactly
# while the op fcomputes trace (and harmlessly during execution); the
# classic per-device executor leaves it None and the ops take their
# single-device paths.  Thread-local by contextvar semantics, so
# concurrently-bound groups on different threads cannot cross-talk.
# ---------------------------------------------------------------------------
import contextlib as _contextlib
import contextvars as _contextvars

_CURRENT_MESH = _contextvars.ContextVar("mxnet_tpu_current_mesh",
                                        default=None)


def current_mesh():
    """The Mesh the enclosing evaluator traces under, or None."""
    return _CURRENT_MESH.get()


@_contextlib.contextmanager
def use_mesh(mesh):
    tok = _CURRENT_MESH.set(mesh)
    try:
        yield
    finally:
        _CURRENT_MESH.reset(tok)
