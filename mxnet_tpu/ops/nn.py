"""Neural-network layer operators.

TPU-native equivalents of the reference's legacy stateful layer ops
(src/operator/*-inl.h, registered MXNET_REGISTER_OP_PROPERTY). Stateful
``Operator`` objects become pure functions; BatchNorm's mutable aux state
(moving mean/var) is expressed as explicit aux inputs/outputs; loss layers
whose backward ignores head gradients (SoftmaxOutput & friends) use
``jax.custom_vjp`` so whole-graph ``jax.vjp`` reproduces reference gradients.
"""
from __future__ import annotations

import numpy as onp

from ..registry import register, f32_precision


def _jnp():
    import jax.numpy as jnp
    return jnp


def _prod(xs):
    p = 1
    for x in xs:
        p *= x
    return p


# ---------------------------------------------------------------------------
# FullyConnected (src/operator/fully_connected-inl.h:60-133)
# ---------------------------------------------------------------------------
def _fc_args(attrs):
    return ("data", "weight") if attrs.get("no_bias", False) else \
        ("data", "weight", "bias")


def _fc_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    nh = int(attrs["num_hidden"])
    if data is not None:
        in_shapes[1] = (nh, _prod(data[1:]))
        if not attrs.get("no_bias", False):
            if len(in_shapes) > 2:
                in_shapes[2] = (nh,)
        return in_shapes, [(data[0], nh)], aux
    return in_shapes, None, aux


@register("FullyConnected", arg_names=_fc_args,
          attr_types={"num_hidden": int, "no_bias": bool},
          required_attrs=("num_hidden",), infer_shape=_fc_infer)
def _fully_connected(attrs, ins, octx):
    """Y = X·Wᵀ + b. Flattens input to 2-D like the reference; the matmul is
    the MXU fast path (reference: mshadow dot() + repmat)."""
    jnp = _jnp()
    x = ins[0]
    w = ins[1]
    if w.dtype != x.dtype:
        # dtype propagation (reference infer_type): reduced-precision
        # activations pull the f32 parameters down to the compute dtype
        w = w.astype(x.dtype)
    x2 = x.reshape((x.shape[0], -1))
    # narrow-math seam (precision.quant): under an active trace scope
    # this GEMM lowers to a native int8/fp8 dot (or collects
    # calibration ranges); inactive scope -> None -> the wide dot below
    from ..precision import quant as _quant
    import jax.lax as _laxmod
    y = _quant.narrow_dot(jnp, _laxmod, x2, w, f32_precision(x2))
    if y is None:
        y = jnp.dot(x2, w.T, precision=f32_precision(x2))
    if not attrs.get("no_bias", False):
        y = y + ins[2].astype(y.dtype)[None, :]
    return [y]


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------
@register("Activation", attr_types={"act_type": str})
def _activation(attrs, ins, octx):
    """relu/sigmoid/tanh/softrelu (src/operator/activation-inl.h)."""
    jnp = _jnp()
    x = ins[0]
    t = attrs.get("act_type", "relu")
    if t == "relu":
        return [jnp.maximum(x, 0)]
    if t == "sigmoid":
        return [1.0 / (1.0 + jnp.exp(-x))]
    if t == "tanh":
        return [jnp.tanh(x)]
    if t == "softrelu":
        return [jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(x, 0)]
    raise ValueError("unknown act_type %s" % t)


def _leaky_args(attrs):
    return ("data", "gamma") if attrs.get("act_type") == "prelu" else ("data",)


@register("LeakyReLU", arg_names=_leaky_args,
          attr_types={"act_type": str, "slope": float, "lower_bound": float,
                      "upper_bound": float},
          needs_rng=True)
def _leaky_relu(attrs, ins, octx):
    """leaky/prelu/elu/rrelu (src/operator/leaky_relu-inl.h)."""
    import jax
    jnp = _jnp()
    x = ins[0]
    t = attrs.get("act_type", "leaky")
    slope = float(attrs.get("slope", 0.25))
    if t == "leaky":
        return [jnp.where(x > 0, x, slope * x)]
    if t == "elu":
        return [jnp.where(x > 0, x, slope * (jnp.exp(x) - 1.0))]
    if t == "prelu":
        gamma = ins[1].reshape((1, -1) + (1,) * (x.ndim - 2))
        return [jnp.where(x > 0, x, gamma * x)]
    if t == "rrelu":
        lo = float(attrs.get("lower_bound", 0.125))
        hi = float(attrs.get("upper_bound", 0.334))
        if octx.is_train:
            a = jax.random.uniform(octx.rng, x.shape, dtype=x.dtype,
                                   minval=lo, maxval=hi)
        else:
            a = (lo + hi) / 2.0
        return [jnp.where(x > 0, x, a * x)]
    raise ValueError("unknown act_type %s" % t)


def _softmax(jnp, x, axis):
    m = jnp.max(x, axis=axis, keepdims=True)
    e = jnp.exp(x - m)
    return e / jnp.sum(e, axis=axis, keepdims=True)


@register("softmax", attr_types={"axis": int, "temperature": float})
def _softmax_op(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    tmp = attrs.get("temperature") or 1.0
    return [_softmax(jnp, x / tmp, int(attrs.get("axis", -1)))]


@register("log_softmax", attr_types={"axis": int})
def _log_softmax(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    axis = int(attrs.get("axis", -1))
    m = jnp.max(x, axis=axis, keepdims=True)
    s = x - m
    return [s - jnp.log(jnp.sum(jnp.exp(s), axis=axis, keepdims=True))]


@register("SoftmaxActivation", attr_types={"mode": str})
def _softmax_activation(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    if attrs.get("mode", "instance") == "channel":
        return [_softmax(jnp, x, 1)]
    return [_softmax(jnp, x.reshape((x.shape[0], -1)), -1).reshape(x.shape)]


# ---------------------------------------------------------------------------
# Loss layers — custom VJP, backward ignores head grads
# ---------------------------------------------------------------------------
def _normalizer(jnp, attrs, label, valid_mask):
    norm = attrs.get("normalization", "null")
    if norm == "batch":
        return float(_prod(label.shape))
    if norm == "valid":
        return jnp.maximum(jnp.sum(valid_mask), 1.0)
    return 1.0


def _softmax_out_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    if in_shapes[1] is None:
        if attrs.get("multi_output", False):
            in_shapes[1] = (data[0],) + tuple(data[2:])
        elif attrs.get("preserve_shape", False):
            in_shapes[1] = tuple(data[:-1])
        else:
            in_shapes[1] = (data[0],)
    return in_shapes, [tuple(data)], aux


@register("SoftmaxOutput", arg_names=("data", "label"),
          attr_types={"grad_scale": float, "ignore_label": float,
                      "multi_output": bool, "use_ignore": bool,
                      "preserve_shape": bool, "normalization": str,
                      "out_grad": bool, "smooth_alpha": float},
          infer_shape=_softmax_out_infer,
          backward_ignores_head_grads=True, alias=("Softmax",))
def _softmax_output(attrs, ins, octx):
    """Softmax forward; backward = (p - onehot(label)) * grad_scale
    (src/operator/softmax_output-inl.h). Gradient w.r.t. data only — the
    incoming head gradient is ignored (out_grad=False path)."""
    import jax
    jnp = _jnp()

    multi = attrs.get("multi_output", False)
    grad_scale = float(attrs.get("grad_scale", 1.0))
    use_ignore = attrs.get("use_ignore", False)
    ignore_label = float(attrs.get("ignore_label", -1.0))

    @jax.custom_vjp
    def f(data, label):
        return _fwd_only(data)

    def _fwd_only(data):
        if multi:
            return _softmax(jnp, data, 1)
        return _softmax(jnp, data.reshape((data.shape[0], -1)),
                        -1).reshape(data.shape)

    def f_fwd(data, label):
        out = _fwd_only(data)
        return out, (out, label)

    def f_bwd(res, g):
        out, label = res
        if label.shape == out.shape:  # dense label distribution
            grad = out - label
            valid = jnp.ones(label.shape[:1], out.dtype)
        elif multi:
            # out: (n, c, d...), label: (n, d...)
            lab = label.astype("int32")
            onehot = (lab[:, None] == jnp.arange(out.shape[1]).reshape(
                (1, -1) + (1,) * (out.ndim - 2))).astype(out.dtype)
            grad = out - onehot
            valid = jnp.ones(lab.shape, out.dtype)
            if use_ignore:
                keep = (label != ignore_label).astype(out.dtype)
                grad = grad * keep[:, None]
                valid = keep
        else:
            lab = label.reshape(-1).astype("int32")
            flat = out.reshape((-1, out.shape[-1]))
            onehot = (lab[:, None] == jnp.arange(flat.shape[-1])).astype(
                out.dtype)
            grad = flat - onehot
            valid = jnp.ones(lab.shape, out.dtype)
            if use_ignore:
                keep = (lab.astype(out.dtype) != ignore_label).astype(out.dtype)
                grad = grad * keep[:, None]
                valid = keep
            grad = grad.reshape(out.shape)
        norm = _normalizer(jnp, attrs, label, valid)
        grad = grad * (grad_scale / norm)
        return grad.astype(out.dtype), jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return [f(ins[0], ins[1] if len(ins) > 1 else
              jnp.zeros(ins[0].shape[:1], ins[0].dtype))]


def _label_like_data_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    if in_shapes[1] is None:
        in_shapes[1] = tuple(data)
    return in_shapes, [tuple(data)], aux


def _make_reg_output(name, fwd_fn, grad_fn):
    @register(name, arg_names=("data", "label"),
              attr_types={"grad_scale": float},
              infer_shape=_label_like_data_infer,
              backward_ignores_head_grads=True)
    def _f(attrs, ins, octx, _fwd=fwd_fn, _grad=grad_fn):
        import jax
        jnp = _jnp()
        scale = float(attrs.get("grad_scale", 1.0))

        @jax.custom_vjp
        def f(data, label):
            return _fwd(jnp, data)

        def f_fwd(data, label):
            return _fwd(jnp, data), (data, label)

        def f_bwd(res, g):
            data, label = res
            out = _fwd(jnp, data)
            num = _prod(label.shape[1:]) or 1
            grad = _grad(jnp, out, label.reshape(out.shape)) * \
                onp.asarray(scale / num, out.dtype)
            return grad, jnp.zeros_like(label)

        f.defvjp(f_fwd, f_bwd)
        return [f(ins[0], ins[1])]
    return _f


# (src/operator/regression_output-inl.h)
_make_reg_output("LinearRegressionOutput",
                 lambda jnp, d: d,
                 lambda jnp, o, l: o - l)
_make_reg_output("LogisticRegressionOutput",
                 lambda jnp, d: 1.0 / (1.0 + jnp.exp(-d)),
                 lambda jnp, o, l: o - l)
_make_reg_output("MAERegressionOutput",
                 lambda jnp, d: d,
                 lambda jnp, o, l: jnp.sign(o - l))


def _svm_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    if in_shapes[1] is None:
        in_shapes[1] = (data[0],)
    return in_shapes, [tuple(data)], aux


@register("SVMOutput", arg_names=("data", "label"),
          attr_types={"margin": float, "regularization_coefficient": float,
                      "use_linear": bool},
          infer_shape=_svm_infer,
          backward_ignores_head_grads=True)
def _svm_output(attrs, ins, octx):
    """Hinge-loss output layer (src/operator/svm_output-inl.h)."""
    import jax
    jnp = _jnp()
    margin = float(attrs.get("margin", 1.0))
    reg = float(attrs.get("regularization_coefficient", 1.0))
    linear = attrs.get("use_linear", False)

    @jax.custom_vjp
    def f(data, label):
        return data

    def f_fwd(data, label):
        return data, (data, label)

    def f_bwd(res, g):
        data, label = res
        lab = label.astype("int32")
        onehot = (lab[:, None] == jnp.arange(data.shape[1])).astype(data.dtype)
        sign = 2.0 * onehot - 1.0  # +1 at true class, -1 elsewhere
        viol = (margin - sign * data) > 0
        if linear:
            grad = jnp.where(viol, -sign * reg, 0.0)
        else:
            grad = jnp.where(viol, -2.0 * reg * sign * (margin - sign * data),
                             0.0)
        return grad.astype(data.dtype), jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return [f(ins[0], ins[1])]


@register("MakeLoss", attr_types={"grad_scale": float, "normalization": str,
                                  "valid_thresh": float},
          backward_ignores_head_grads=True,
          alias=("make_loss",))
def _make_loss(attrs, ins, octx):
    """Forward identity; backward seeds grad_scale (src/operator/make_loss-inl.h)."""
    import jax
    jnp = _jnp()
    scale = float(attrs.get("grad_scale", 1.0))
    norm = attrs.get("normalization", "null")

    @jax.custom_vjp
    def f(data):
        return data

    def f_fwd(data):
        return data, (data,)

    def f_bwd(res, g):
        (data,) = res
        denom = float(_prod(data.shape)) if norm == "batch" else 1.0
        return (jnp.full(data.shape, scale / denom, data.dtype),)

    f.defvjp(f_fwd, f_bwd)
    return [f(ins[0])]


# ---------------------------------------------------------------------------
# Dropout (src/operator/dropout-inl.h) — mask from the executor-threaded PRNG
# ---------------------------------------------------------------------------
@register("Dropout", attr_types={"p": float}, needs_rng=True)
def _dropout(attrs, ins, octx):
    import jax
    jnp = _jnp()
    x = ins[0]
    p = float(attrs.get("p", 0.5))
    if not octx.is_train or p <= 0.0:
        return [x]
    keep = 1.0 - p
    mask = jax.random.bernoulli(octx.rng, keep, x.shape)
    return [jnp.where(mask, x / onp.asarray(keep, x.dtype),
                      onp.asarray(0.0, x.dtype))]


# ---------------------------------------------------------------------------
# BatchNorm (src/operator/batch_norm-inl.h) — aux moving stats in/out
# ---------------------------------------------------------------------------
def _bn_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    c = data[1] if len(data) > 1 else data[0]
    for i in (1, 2):
        if i < len(in_shapes):
            in_shapes[i] = (c,)
    aux = [(c,), (c,)]
    return in_shapes, [tuple(data)], aux


def _exact_stats():
    import os
    return os.environ.get("MXNET_BN_EXACT_STATS", "0") == "1"


def _bn_train_core_make():
    """Build the train-mode BatchNorm core with a hand-derived VJP.

    Why not let autodiff handle it (it did, rounds 1-3): ResNet-class
    training on TPU is HBM-bandwidth-bound (PERF.md roofline), and
    XLA's lowering of the autodiff backward re-reads the activation
    several extra times (materialized casts, separate reductions, a
    separate ReLU-mask pass).  The hand VJP is the minimal-traffic
    schedule — backward pass 1 reads (dout, x) once for both
    reductions, pass 2 reads (dout, x) once more and writes dx,
    recomputing x_hat and the fused-ReLU mask in-register instead of
    re-reading saved normalized values.  Measured on a 5× conv+BN+ReLU
    chain at [128,256,56,56]: 10.73 → 8.67 GB accessed per step, with
    gradients equal to autodiff within bf16 rounding.  (Statistics use
    the running-mean-centered ONE-pass form — rounding differs from the
    reference two-pass values by ~1e-7 relative, bounded by the
    8dev-vs-1dev gradient-equality test; see the comment in _fwd.)

    ``relu=True`` is the graph-fusion entry (executor fuse_bn_relu):
    BatchNorm→Activation(relu) pairs collapse into this core so the
    backward never touches the post-activation tensor at all.

    The (mean, var) outputs carry zero cotangent by construction —
    their only consumer is the moving-stat EMA, which the caller
    stop_gradients (reference parity: batch_norm-inl.h backward
    ignores out_grad on mean/var).
    """
    import jax
    from functools import partial

    jnp = _jnp()

    def _norm_shapes(x):
        axes = tuple(i for i in range(x.ndim) if i != 1)
        n = 1
        for i in axes:
            n *= x.shape[i]
        bshape = (1, -1) + (1,) * (x.ndim - 2)
        return axes, n, bshape

    def _fwd(x, gamma, beta, c, eps, fix_gamma, relu):
        f32 = jnp.float32
        axes, n, bshape = _norm_shapes(x)
        xf = x.astype(f32)
        if _exact_stats():
            # MXNET_BN_EXACT_STATS=1: reference two-pass statistics.
            # Immune to the one-pass cancellation hazard at ANY offset
            # (cost: one extra full read of x per BatchNorm).  Set it
            # BEFORE building the module — the choice is baked into the
            # compiled program at trace time.
            mean = jnp.mean(xf, axis=axes)
            var = jnp.mean(jnp.square(xf - mean.reshape(bshape)),
                           axis=axes)
        else:
            # centered one-pass statistics (the default): both
            # reductions share ONE sweep over x (and XLA fuses them into
            # the producing conv's epilogue), unlike the two-pass
            # mean-then-var chain, which forces a second full HBM read.
            # The naive one-pass form E[x²]-E[x]² cancels mean² against
            # E[x²] in f32 — variance evaporates when |mean| >> std —
            # so the sweep is centered by c, the running mean (a free
            # [C] input): once stats warm up the correction term
            # (E[x-c])² is ~0 and var is carried by the (x-c)² sum
            # alone.  The identity var = E[(x-c)²] - (E[x-c])² is exact
            # for ANY c, and c carries zero gradient.
            #
            # Residual hazard, accepted UNGUARDED as the default: while
            # c is cold (fresh init) this is plain one-pass, which
            # loses the variance in f32 when |mean|/std exceeds ~1000
            # (raw pixels are κ~5 — fine; a 300K±0.5K sensor channel is
            # not).  The JAX ecosystem norm (flax/haiku BN, jnp.var) is
            # the UNcentered one-pass everywhere, so this default is
            # strictly more robust; users with extreme-offset inputs
            # take the exact branch above via MXNET_BN_EXACT_STATS=1
            # (docs/how_to/env_var.md).  Rejected alternatives, all
            # measured on ResNet-50/v5e: lax.cond exact fallback
            # (+3 ms/step cond serialization, and capturing the f32
            # view costs +25 GB), strided-subsample center (gather
            # defeats the conv-epilogue reduce fusion, +22 GB), Welford
            # pairwise lax.reduce (60x slower — custom combiners do not
            # vectorize).
            xc = xf - c.reshape(bshape)
            m1 = jnp.sum(xc, axis=axes) / n
            m2 = jnp.sum(xc * xc, axis=axes) / n
            mean = c + m1
            var = jnp.maximum(m2 - m1 * m1, 0.0)
        # shared tail — ONE copy so the fwd pre-activation expression
        # can never diverge between stat modes (_bwd recomputes the
        # ReLU mask with this exact expression)
        rstd = jax.lax.rsqrt(var + eps)
        g = (jnp.ones_like(gamma) if fix_gamma else gamma).astype(f32)
        scale = g * rstd
        shift = beta.astype(f32) - mean * scale
        y = xf * scale.reshape(bshape) + shift.reshape(bshape)
        if relu:
            y = jnp.maximum(y, 0.0)
        return (y.astype(x.dtype), mean, var), (x, gamma, beta, mean,
                                                rstd, c)

    def _bwd(eps, fix_gamma, relu, res, cots):
        # cots = (dout, dmean, dvar); dmean/dvar are structurally zero
        # (EMA consumers are stop_gradient'ed) and are ignored
        dout = cots[0]
        x, gamma, beta, mean, rstd, _c = res
        f32 = jnp.float32
        axes, n, bshape = _norm_shapes(x)
        g = (jnp.ones_like(gamma) if fix_gamma else gamma).astype(f32)
        xf = x.astype(f32)
        xhat = (xf - mean.reshape(bshape)) * rstd.reshape(bshape)
        du = dout.astype(f32)
        if relu:
            # recompute the pre-activation with the SAME expression the
            # forward used (xf*scale + shift, not xhat*g + beta): the
            # two round differently at |y| ~ ulp, and a flipped ReLU
            # mask is a discontinuous gradient change
            scale = g * rstd
            shift = beta.astype(f32) - mean * scale
            y = xf * scale.reshape(bshape) + shift.reshape(bshape)
            du = jnp.where(y > 0, du, 0.0)
        dbeta = jnp.sum(du, axis=axes)
        dgamma = jnp.sum(du * xhat, axis=axes)
        dx = (du - (dbeta / n).reshape(bshape)
              - xhat * (dgamma / n).reshape(bshape)) \
            * (g * rstd).reshape(bshape)
        dg = (jnp.zeros_like(gamma) if fix_gamma
              else dgamma.astype(gamma.dtype))
        # zero cotangent for the centering constant: mean = c + E[x-c],
        # so the true derivative w.r.t. c is identically 0
        return (dx.astype(x.dtype), dg, dbeta.astype(beta.dtype),
                jnp.zeros_like(_c))

    @partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
    def core(x, gamma, beta, c, eps, fix_gamma, relu):
        return _fwd(x, gamma, beta, c, eps, fix_gamma, relu)[0]

    core.defvjp(_fwd, _bwd)
    return core


_BN_TRAIN_CORE = None


def _bn_train_core(x, gamma, beta, c, eps, fix_gamma, relu):
    global _BN_TRAIN_CORE
    if _BN_TRAIN_CORE is None:
        _BN_TRAIN_CORE = _bn_train_core_make()
    return _BN_TRAIN_CORE(x, gamma, beta, c, eps, fix_gamma, relu)


@register("BatchNorm", arg_names=("data", "gamma", "beta"),
          aux_names=("moving_mean", "moving_var"),
          attr_types={"eps": float, "momentum": float, "fix_gamma": bool,
                      "use_global_stats": bool, "output_mean_var": bool},
          infer_shape=_bn_infer,
          # CuDNNBatchNorm: the reference's cudnn-path registration
          # (cudnn_batch_norm.cc) — same semantics, kept so its
          # checkpoints/symbols load
          alias=("CuDNNBatchNorm",))
def _batch_norm(attrs, ins, octx):
    """Normalize over all axes but channel (axis 1). In training, use batch
    stats and update moving stats (returned as aux updates; the executor
    writes them back — replacing FMutateInputs on aux states)."""
    import jax
    jnp = _jnp()
    x, gamma, beta, mmean, mvar = ins
    eps = float(attrs.get("eps", 1e-3))
    mom = float(attrs.get("momentum", 0.9))
    fix_gamma = attrs.get("fix_gamma", True)
    use_global = attrs.get("use_global_stats", False)

    # mixed-precision contract (AMP standard): statistics + normalization
    # math run in f32 even for bf16 activations — the moving-stat EMA
    # increment (1-mom)*x is at bf16's quantization floor, so bf16 stats
    # would random-walk instead of converge — and the output is cast back
    # to the activation dtype so dtype-strict consumers (lax.conv) are
    # happy in both train (batch-stat) and eval (moving-stat) modes.
    xdt = x.dtype
    f32 = jnp.float32
    fused_relu = bool(attrs.get("_fused_relu", False))
    if octx.is_train and not use_global:
        # hand-VJP core: one-pass f32 stats, minimal-traffic backward,
        # optional fused ReLU (see _bn_train_core_make)
        c = jax.lax.stop_gradient(mmean.astype(f32))
        out, mean, var = _bn_train_core(x, gamma, beta, c, eps,
                                        bool(fix_gamma), fused_relu)
        # remat tag (mxnet_tpu.precision "offload_bn_stats" policy):
        # name the per-channel statistics so a segmented-checkpoint
        # backward built with save_only_these_names("bn_stats") keeps
        # them across segment boundaries instead of replaying the stat
        # sweeps. Outside such a policy checkpoint_name is identity —
        # bitwise-neutral for every other mode (pinned by the existing
        # parity suites).
        from jax.ad_checkpoint import checkpoint_name
        mean = checkpoint_name(mean, "bn_stats")
        var = checkpoint_name(var, "bn_stats")
        new_mmean = (mmean * mom +
                     jax.lax.stop_gradient(mean).astype(mmean.dtype) *
                     (1 - mom))
        new_mvar = (mvar * mom +
                    jax.lax.stop_gradient(var).astype(mvar.dtype) *
                    (1 - mom))
        return [out, new_mmean, new_mvar]
    xf = x.astype(f32)
    axes = tuple(i for i in range(x.ndim) if i != 1)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    mean, var = mmean.astype(f32), mvar.astype(f32)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    out = (xf - mean.reshape(bshape)) / jnp.sqrt(var.reshape(bshape) + eps)
    out = (out * g.astype(f32).reshape(bshape) +
           beta.astype(f32).reshape(bshape))
    if fused_relu:
        out = jnp.maximum(out, 0.0)
    return [out.astype(xdt), mmean, mvar]


def _in_infer(attrs, in_shapes, aux):
    d = in_shapes[0]
    if d is not None:
        in_shapes[1] = (d[1],)
        in_shapes[2] = (d[1],)
        return in_shapes, [tuple(d)], aux
    return in_shapes, None, aux


@register("InstanceNorm", arg_names=("data", "gamma", "beta"),
          attr_types={"eps": float}, infer_shape=_in_infer)
def _instance_norm(attrs, ins, octx):
    jnp = _jnp()
    x, gamma, beta = ins
    eps = float(attrs.get("eps", 1e-3))
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    out = (x - mean) / jnp.sqrt(var + eps)
    return [out * gamma.reshape(bshape) + beta.reshape(bshape)]


@register("L2Normalization", attr_types={"eps": float, "mode": str})
def _l2_normalization(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    eps = float(attrs.get("eps", 1e-10))
    mode = attrs.get("mode", "instance")
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
        keep = True
    elif mode == "channel":
        axes = (1,)
        keep = True
    elif mode == "spatial":
        axes = tuple(range(2, x.ndim))
        keep = True
    else:
        raise ValueError("unknown mode " + mode)
    denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=keep) + eps)
    return [x / denom]


@register("LRN", attr_types={"alpha": float, "beta": float, "knorm": float,
                             "nsize": int})
def _lrn(attrs, ins, octx):
    """Local response norm across channels (src/operator/lrn-inl.h)."""
    import jax
    jnp = _jnp()
    x = ins[0]
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    knorm = float(attrs.get("knorm", 2.0))
    nsize = int(attrs.get("nsize", 5))
    sq = jnp.square(x)
    half = nsize // 2
    window_sum = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add,
        window_dimensions=(1, nsize) + (1,) * (x.ndim - 2),
        window_strides=(1,) * x.ndim,
        padding=((0, 0), (half, half)) + ((0, 0),) * (x.ndim - 2))
    return [x / jnp.power(knorm + (alpha / nsize) * window_sum, beta)]


@register("IdentityAttachKLSparseReg",
          attr_types={"sparseness_target": float, "penalty": float,
                      "momentum": float})
def _identity_kl_sparse(attrs, ins, octx):
    # Forward identity; the sparse-reg penalty shapes gradients in the
    # reference — approximated as pure identity pending demand.
    return [ins[0]]


def _sce_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is not None and in_shapes[1] is None:
        in_shapes[1] = (data[0],)
    return in_shapes, [(1,)], aux


@register("softmax_cross_entropy", arg_names=("data", "label"),
          infer_shape=_sce_infer)
def _softmax_cross_entropy(attrs, ins, octx):
    """Scalar -sum(log softmax(data)[i, label_i])
    (src/operator/loss_binary_op.cc:11); gradient flows through jax.vjp."""
    import jax
    jnp = _jnp()
    data, label = ins
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = jnp.clip(label.astype("int32"), 0, data.shape[-1] - 1)
    picked = jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return [-jnp.sum(picked).reshape((1,))]
