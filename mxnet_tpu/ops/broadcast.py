"""Broadcast binary ops, broadcast_axis/to, and reductions.

Covers src/operator/tensor/elemwise_binary_broadcast_op.cc and
broadcast_reduce_op_value.cc (+ kernels tensor/broadcast_reduce-inl.h).
XLA handles broadcasting/reduction natively; no hand tiling needed.
"""
from __future__ import annotations

import numpy as onp

from ..registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _bcast(name, fn):
    @register(name, arg_names=("lhs", "rhs"))
    def _f(attrs, ins, octx, _fn=fn):
        return [_fn(_jnp(), ins[0], ins[1])]
    return _f


_BCAST_TABLE = {
    "broadcast_add": lambda jnp, a, b: a + b,
    "broadcast_plus": lambda jnp, a, b: a + b,
    "broadcast_sub": lambda jnp, a, b: a - b,
    "broadcast_minus": lambda jnp, a, b: a - b,
    "broadcast_mul": lambda jnp, a, b: a * b,
    "broadcast_div": lambda jnp, a, b: a / b,
    "broadcast_mod": lambda jnp, a, b: jnp.mod(a, b),
    "broadcast_power": lambda jnp, a, b: jnp.power(a, b),
    "broadcast_maximum": lambda jnp, a, b: jnp.maximum(a, b),
    "broadcast_minimum": lambda jnp, a, b: jnp.minimum(a, b),
    "broadcast_hypot": lambda jnp, a, b: jnp.hypot(a, b),
    "broadcast_equal": lambda jnp, a, b: (a == b).astype(a.dtype),
    "broadcast_not_equal": lambda jnp, a, b: (a != b).astype(a.dtype),
    "broadcast_greater": lambda jnp, a, b: (a > b).astype(a.dtype),
    "broadcast_greater_equal": lambda jnp, a, b: (a >= b).astype(a.dtype),
    "broadcast_lesser": lambda jnp, a, b: (a < b).astype(a.dtype),
    "broadcast_lesser_equal": lambda jnp, a, b: (a <= b).astype(a.dtype),
}

for _name, _fn in _BCAST_TABLE.items():
    _bcast(_name, _fn)


@register("broadcast_axis", attr_types={"axis": tuple, "size": tuple},
          alias=("broadcast_axes",))
def _broadcast_axis(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    axes = attrs.get("axis", ())
    sizes = attrs.get("size", ())
    if not isinstance(axes, tuple):
        axes = (axes,)
    if not isinstance(sizes, tuple):
        sizes = (sizes,)
    shape = list(x.shape)
    for ax, sz in zip(axes, sizes):
        shape[ax] = sz
    return [jnp.broadcast_to(x, tuple(shape))]


@register("broadcast_to", attr_types={"shape": tuple})
def _broadcast_to(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    tgt = list(attrs["shape"])
    for i, t in enumerate(tgt):
        if t == 0:
            tgt[i] = x.shape[i]
    return [jnp.broadcast_to(x, tuple(tgt))]


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------
def _norm_axis(attrs, ndim):
    axis = attrs.get("axis", None)
    if axis is None or axis == ():
        return None
    if isinstance(axis, (int, float)):
        axis = (int(axis),)
    return tuple(int(a) % ndim if a < 0 else int(a) for a in axis)


def _reduce(name, fn, alias=()):
    @register(name, attr_types={"axis": tuple, "keepdims": bool}, alias=alias)
    def _f(attrs, ins, octx, _fn=fn):
        jnp = _jnp()
        x = ins[0]
        axis = _norm_axis(attrs, x.ndim)
        keepdims = bool(attrs.get("keepdims", False))
        return [_fn(jnp, x, axis, keepdims)]
    return _f


_REDUCE_TABLE = {
    "sum": (lambda jnp, x, a, k: jnp.sum(x, axis=a, keepdims=k), ("sum_axis",)),
    "mean": (lambda jnp, x, a, k: jnp.mean(x, axis=a, keepdims=k), ()),
    "prod": (lambda jnp, x, a, k: jnp.prod(x, axis=a, keepdims=k), ()),
    "max": (lambda jnp, x, a, k: jnp.max(x, axis=a, keepdims=k), ("max_axis",)),
    "min": (lambda jnp, x, a, k: jnp.min(x, axis=a, keepdims=k), ("min_axis",)),
    "nansum": (lambda jnp, x, a, k: jnp.nansum(x, axis=a, keepdims=k), ()),
    "nanprod": (lambda jnp, x, a, k: jnp.nanprod(x, axis=a, keepdims=k), ()),
}

for _name, (_fn, _al) in _REDUCE_TABLE.items():
    _reduce(_name, _fn, _al)


@register("norm")
def _norm(attrs, ins, octx):
    jnp = _jnp()
    return [jnp.sqrt(jnp.sum(jnp.square(ins[0]))).reshape((1,))]


@register("argmax", attr_types={"axis": int, "keepdims": bool})
def _argmax(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    axis = attrs.get("axis")
    keepdims = bool(attrs.get("keepdims", False))
    if axis is None:
        return [jnp.argmax(x.reshape(-1)).astype(x.dtype).reshape((1,))]
    r = jnp.argmax(x, axis=int(axis)).astype(x.dtype)
    if keepdims:
        r = jnp.expand_dims(r, int(axis))
    return [r]


@register("argmin", attr_types={"axis": int, "keepdims": bool})
def _argmin(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    axis = attrs.get("axis")
    keepdims = bool(attrs.get("keepdims", False))
    if axis is None:
        return [jnp.argmin(x.reshape(-1)).astype(x.dtype).reshape((1,))]
    r = jnp.argmin(x, axis=int(axis)).astype(x.dtype)
    if keepdims:
        r = jnp.expand_dims(r, int(axis))
    return [r]


@register("argmax_channel")
def _argmax_channel(attrs, ins, octx):
    """argmax over axis 1 returning same dtype (used by Accuracy metric;
    src/operator/tensor/broadcast_reduce_op_index.cc)."""
    jnp = _jnp()
    x = ins[0]
    return [jnp.argmax(x, axis=-1).astype(x.dtype)]


def _pick_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    axis = int(attrs.get("axis", -1))
    keepdims = bool(attrs.get("keepdims", False))
    if axis < 0:
        axis += len(data)
    idx_shape = tuple(d for i, d in enumerate(data) if i != axis)
    if in_shapes[1] is None:
        in_shapes[1] = idx_shape
    out = tuple(1 if i == axis else d for i, d in enumerate(data)) \
        if keepdims else idx_shape
    return in_shapes, [out], aux


@register("pick", arg_names=("data", "index"),
          attr_types={"axis": int, "keepdims": bool},
          infer_shape=_pick_infer)
def _pick(attrs, ins, octx):
    """Pick elements along ``axis`` by per-position indices, clip mode
    (src/operator/tensor/broadcast_reduce_op_index.cc:92 ``pick``)."""
    jnp = _jnp()
    data, index = ins
    axis = int(attrs.get("axis", -1))
    keepdims = bool(attrs.get("keepdims", False))
    if axis < 0:
        axis += data.ndim
    idx = jnp.clip(index.astype("int32"), 0, data.shape[axis] - 1)
    idx = idx.reshape(data.shape[:axis] + (1,) + data.shape[axis + 1:])
    out = jnp.take_along_axis(data, idx, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return [out]
