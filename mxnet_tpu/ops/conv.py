"""Convolution / pooling / spatial operators — XLA conv path.

The reference's cuDNN(MIOpen) convolution stack (src/operator/convolution-inl.h,
cudnn_convolution-inl.h, im2col.h/.cuh) collapses into
``lax.conv_general_dilated``: XLA tiles these onto the MXU directly, replacing
algo selection + im2col. Layout is NCHW to match the reference's default.
"""
from __future__ import annotations

import numpy as onp

from ..registry import register, f32_precision


def _jnp():
    import jax.numpy as jnp
    return jnp


def _lax():
    import jax.lax as lax
    return lax


def _tup(v, n):
    if isinstance(v, (int, float)):
        return (int(v),) * n
    t = tuple(int(x) for x in v)
    return t


def _conv_args(attrs):
    return ("data", "weight") if attrs.get("no_bias", False) else \
        ("data", "weight", "bias")


def _conv_out_dim(i, k, p, s, d):
    return (i + 2 * p - d * (k - 1) - 1) // s + 1


def _conv_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    nd = len(data) - 2
    kernel = _tup(attrs["kernel"], nd)
    stride = _tup(attrs.get("stride", 1), nd)
    pad = _tup(attrs.get("pad", 0), nd)
    dilate = _tup(attrs.get("dilate", 1), nd)
    nf = int(attrs["num_filter"])
    ng = int(attrs.get("num_group", 1))
    in_shapes[1] = (nf, data[1] // ng) + kernel
    if not attrs.get("no_bias", False) and len(in_shapes) > 2:
        in_shapes[2] = (nf,)
    out_sp = tuple(_conv_out_dim(data[2 + i], kernel[i], pad[i], stride[i],
                                 dilate[i]) for i in range(nd))
    return in_shapes, [(data[0], nf) + out_sp], aux


@register("Convolution", arg_names=_conv_args,
          attr_types={"kernel": tuple, "stride": tuple, "dilate": tuple,
                      "pad": tuple, "num_filter": int, "num_group": int,
                      "workspace": int, "no_bias": bool, "cudnn_tune": str,
                      "cudnn_off": bool, "layout": str},
          required_attrs=("kernel", "num_filter"),
          infer_shape=_conv_infer, alias=("Convolution_v1",))
def _convolution(attrs, ins, octx):
    lax = _lax()
    x, w = ins[0], ins[1]
    if w.dtype != x.dtype:
        # dtype propagation (reference infer_type): reduced-precision
        # activations pull the f32 parameters down to the compute dtype
        w = w.astype(x.dtype)
    nd = x.ndim - 2
    stride = _tup(attrs.get("stride", 1), nd)
    pad = _tup(attrs.get("pad", 0), nd)
    dilate = _tup(attrs.get("dilate", 1), nd)
    ng = int(attrs.get("num_group", 1))
    spec = "NCHW"[:2 + nd] if nd <= 2 else "NCDHW"
    if nd == 1:
        spec_in, spec_k, spec_out = "NCH", "OIH", "NCH"
    elif nd == 2:
        spec_in, spec_k, spec_out = "NCHW", "OIHW", "NCHW"
    else:
        spec_in, spec_k, spec_out = "NCDHW", "OIDHW", "NCDHW"
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    (spec_in, spec_k, spec_out))
    conv_kwargs = dict(window_strides=stride,
                       padding=[(p, p) for p in pad],
                       rhs_dilation=dilate, dimension_numbers=dn,
                       feature_group_count=ng,
                       precision=f32_precision(x))
    # narrow-math seam (precision.quant): native int8 conv (or
    # calibration collection) under an active trace scope
    from ..precision import quant as _quant
    y = _quant.narrow_conv(_jnp(), lax, x, w, conv_kwargs)
    if y is None:
        y = lax.conv_general_dilated(x, w, **conv_kwargs)
    if not attrs.get("no_bias", False):
        b = ins[2]
        # keep the compute dtype: a f32 bias would silently promote a
        # bf16 activation stream back to f32 (dtype propagation, as for
        # the weight above)
        y = y + b.astype(y.dtype).reshape((1, -1) + (1,) * nd)
    return [y]


def _deconv_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    nd = len(data) - 2
    kernel = _tup(attrs["kernel"], nd)
    stride = _tup(attrs.get("stride", 1), nd)
    pad = _tup(attrs.get("pad", 0), nd)
    adj = _tup(attrs.get("adj", 0), nd)
    nf = int(attrs["num_filter"])
    ng = int(attrs.get("num_group", 1))
    in_shapes[1] = (data[1], nf // ng) + kernel
    if not attrs.get("no_bias", True) and len(in_shapes) > 2:
        in_shapes[2] = (nf,)
    out_sp = tuple((data[2 + i] - 1) * stride[i] - 2 * pad[i] + kernel[i]
                   + adj[i] for i in range(nd))
    return in_shapes, [(data[0], nf) + out_sp], aux


def _deconv_args(attrs):
    # Deconvolution's no_bias defaults to True in the reference
    return ("data", "weight") if attrs.get("no_bias", True) else \
        ("data", "weight", "bias")


@register("Deconvolution", arg_names=_deconv_args,
          attr_types={"kernel": tuple, "stride": tuple, "pad": tuple,
                      "adj": tuple, "target_shape": tuple, "num_filter": int,
                      "num_group": int, "workspace": int, "no_bias": bool},
          required_attrs=("kernel", "num_filter"),
          infer_shape=_deconv_infer)
def _deconvolution(attrs, ins, octx):
    """Transposed convolution = conv with lhs dilation
    (src/operator/deconvolution-inl.h). Weight layout (C_in, C_out/g, k...)."""
    lax = _lax()
    jnp = _jnp()
    x, w = ins[0], ins[1]
    if w.dtype != x.dtype:
        # dtype propagation (reference infer_type): reduced-precision
        # activations pull the f32 parameters down to the compute dtype
        w = w.astype(x.dtype)
    nd = x.ndim - 2
    stride = _tup(attrs.get("stride", 1), nd)
    pad = _tup(attrs.get("pad", 0), nd)
    adj = _tup(attrs.get("adj", 0), nd)
    kernel = _tup(attrs["kernel"], nd)
    ng = int(attrs.get("num_group", 1))
    # flip spatial dims and swap I/O to express deconv as dilated conv
    w_flip = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    if ng == 1:
        w_t = jnp.swapaxes(w_flip, 0, 1)  # (C_out, C_in, k...)
    else:
        ci, cog = w.shape[0], w.shape[1]
        w_g = w_flip.reshape((ng, ci // ng, cog) + w.shape[2:])
        w_t = jnp.swapaxes(w_g, 1, 2).reshape((ng * cog, ci // ng) + w.shape[2:])
    if nd == 1:
        specs = ("NCH", "OIH", "NCH")
    elif nd == 2:
        specs = ("NCHW", "OIHW", "NCHW")
    else:
        specs = ("NCDHW", "OIDHW", "NCDHW")
    dn = lax.conv_dimension_numbers(x.shape, w_t.shape, specs)
    y = lax.conv_general_dilated(
        x, w_t, window_strides=(1,) * nd,
        padding=[(kernel[i] - 1 - pad[i], kernel[i] - 1 - pad[i] + adj[i])
                 for i in range(nd)],
        lhs_dilation=stride, dimension_numbers=dn, feature_group_count=ng,
        precision=f32_precision(x))
    if not attrs.get("no_bias", True) and len(ins) > 2:
        y = y + ins[2].astype(y.dtype).reshape((1, -1) + (1,) * nd)
    return [y]


def _pool_out_dim(i, k, p, s, convention):
    if convention == "full":
        return int(onp.ceil(float(i + 2 * p - k) / s)) + 1
    return (i + 2 * p - k) // s + 1


def _pool_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    if attrs.get("global_pool", False):
        return in_shapes, [tuple(data[:2]) + (1,) * (len(data) - 2)], aux
    nd = len(data) - 2
    kernel = _tup(attrs["kernel"], nd)
    stride = _tup(attrs.get("stride", 1), nd)
    pad = _tup(attrs.get("pad", 0), nd)
    conv = attrs.get("pooling_convention", "valid")
    out_sp = tuple(_pool_out_dim(data[2 + i], kernel[i], pad[i], stride[i],
                                 conv) for i in range(nd))
    return in_shapes, [tuple(data[:2]) + out_sp], aux


@register("Pooling",
          attr_types={"kernel": tuple, "stride": tuple, "pad": tuple,
                      "pool_type": str, "global_pool": bool,
                      "pooling_convention": str, "cudnn_off": bool},
          infer_shape=_pool_infer, alias=("Pooling_v1",))
def _pooling(attrs, ins, octx):
    """max/avg/sum pooling via lax.reduce_window (src/operator/pooling-inl.h,
    src/operator/nn/pool.h). avg divides by the full window size including
    padding, matching mshadow's pool<red::sum>/k behaviour."""
    lax = _lax()
    jnp = _jnp()
    x = ins[0]
    nd = x.ndim - 2
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool", False):
        kernel = tuple(x.shape[2:])
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = _tup(attrs["kernel"], nd)
        stride = _tup(attrs.get("stride", 1), nd)
        pad = _tup(attrs.get("pad", 0), nd)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    conv = attrs.get("pooling_convention", "valid")
    pads = [(0, 0), (0, 0)]
    for i in range(nd):
        lo = pad[i]
        hi = pad[i]
        if conv == "full":
            out = _pool_out_dim(x.shape[2 + i], kernel[i], pad[i], stride[i],
                                "full")
            need = (out - 1) * stride[i] + kernel[i] - x.shape[2 + i] - lo
            hi = max(need, 0)
        pads.append((lo, hi))
    if ptype == "max":
        # note: bfloat16 is a custom numpy dtype (kind 'V'), so test for
        # integer-ness rather than float-ness
        init = onp.iinfo(onp.dtype(x.dtype)).min \
            if onp.issubdtype(onp.dtype(x.dtype), onp.integer) else -onp.inf
        y = lax.reduce_window(x, onp.asarray(init, x.dtype), lax.max, window,
                              strides, pads)
    else:
        y = lax.reduce_window(x, onp.asarray(0, x.dtype), lax.add, window,
                              strides, pads)
        if ptype == "avg":
            ksize = 1
            for k in kernel:
                ksize *= k
            y = y / onp.asarray(ksize, x.dtype)
    return [y]


@register("UpSampling", variable_args="num_args",
          attr_types={"scale": int, "sample_type": str, "num_filter": int,
                      "multi_input_mode": str, "num_args": int})
def _upsampling(attrs, ins, octx):
    """Nearest/bilinear upsampling (src/operator/upsampling-inl.h)."""
    jnp = _jnp()
    scale = int(attrs.get("scale", 2))
    stype = attrs.get("sample_type", "nearest")
    outs = []
    for x in ins:
        if stype == "nearest":
            y = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        else:
            import jax
            y = jax.image.resize(
                x, x.shape[:2] + (x.shape[2] * scale, x.shape[3] * scale),
                method="bilinear")
        outs.append(y)
    if len(outs) == 1:
        return outs
    mode = attrs.get("multi_input_mode", "concat")
    if mode == "sum":
        t = outs[0]
        for o in outs[1:]:
            t = t + o
        return [t]
    return [jnp.concatenate(outs, axis=1)]


@register("Pad", attr_types={"mode": str, "pad_width": tuple,
                             "constant_value": float},
          alias=("pad",))
def _pad(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    pw = attrs["pad_width"]
    pairs = [(int(pw[2 * i]), int(pw[2 * i + 1])) for i in range(x.ndim)]
    mode = attrs.get("mode", "constant")
    if mode == "constant":
        return [jnp.pad(x, pairs, mode="constant",
                        constant_values=float(attrs.get("constant_value", 0)))]
    if mode == "edge":
        return [jnp.pad(x, pairs, mode="edge")]
    if mode == "reflect":
        return [jnp.pad(x, pairs, mode="reflect")]
    raise ValueError("unknown pad mode " + mode)


def _crop_args(attrs):
    return ("data", "crop_like") if int(attrs.get("num_args", 1)) == 2 \
        else ("data",)


def _crop_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    if int(attrs.get("num_args", 1)) == 2 and in_shapes[1] is not None:
        hw = in_shapes[1][2:]
    else:
        hw = _tup(attrs.get("h_w", (0, 0)), 2)
    return in_shapes, [tuple(data[:2]) + tuple(hw)], aux


@register("Crop", arg_names=_crop_args,
          attr_types={"offset": tuple, "h_w": tuple, "center_crop": bool,
                      "num_args": int},
          infer_shape=_crop_infer)
def _crop_op(attrs, ins, octx):
    """Spatial crop (src/operator/crop-inl.h)."""
    x = ins[0]
    if int(attrs.get("num_args", 1)) == 2:
        th, tw = ins[1].shape[2], ins[1].shape[3]
    else:
        th, tw = _tup(attrs["h_w"], 2)
    if attrs.get("center_crop", False):
        oy = (x.shape[2] - th) // 2
        ox = (x.shape[3] - tw) // 2
    else:
        oy, ox = _tup(attrs.get("offset", (0, 0)), 2)
    return [x[:, :, oy:oy + th, ox:ox + tw]]


@register("ROIPooling", arg_names=("data", "rois"),
          attr_types={"pooled_size": tuple, "spatial_scale": float})
def _roi_pooling(attrs, ins, octx):
    """ROI max pooling (src/operator/roi_pooling-inl.h). Computed with a
    mask-reduction over the feature map per output bin — static shapes for
    XLA; a Pallas kernel is the planned fast path."""
    import jax
    jnp = _jnp()
    data, rois = ins
    ph, pw = _tup(attrs["pooled_size"], 2)
    scale = float(attrs["spatial_scale"])
    N, C, H, W = data.shape

    ys = jnp.arange(H, dtype=data.dtype)
    xs = jnp.arange(W, dtype=data.dtype)

    def one_roi(roi):
        batch = roi[0].astype("int32")
        x1 = jnp.round(roi[1] * scale)
        y1 = jnp.round(roi[2] * scale)
        x2 = jnp.round(roi[3] * scale)
        y2 = jnp.round(roi[4] * scale)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        fmap = data[batch]  # (C, H, W)

        def one_bin(iy, ix):
            hstart = jnp.floor(y1 + iy * bin_h)
            hend = jnp.ceil(y1 + (iy + 1) * bin_h)
            wstart = jnp.floor(x1 + ix * bin_w)
            wend = jnp.ceil(x1 + (ix + 1) * bin_w)
            mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend) &
                    (xs[None, :] >= wstart) & (xs[None, :] < wend))
            neg = onp.asarray(-1e30, data.dtype)
            vals = jnp.where(mask[None], fmap, neg)
            m = jnp.max(vals, axis=(1, 2))
            return jnp.where(jnp.any(mask), m, onp.asarray(0, data.dtype))

        iys = jnp.arange(ph)
        ixs = jnp.arange(pw)
        bins = jax.vmap(lambda iy: jax.vmap(lambda ix: one_bin(iy, ix))(ixs))(iys)
        return jnp.transpose(bins, (2, 0, 1))  # (C, ph, pw)

    out = jax.vmap(one_roi)(rois)
    return [out]


@register("GridGenerator", attr_types={"transform_type": str,
                                       "target_shape": tuple})
def _grid_generator(attrs, ins, octx):
    """Affine/warp grid generation (src/operator/grid_generator-inl.h).
    Output grid in [-1,1] coords, shape (n, 2, h, w)."""
    jnp = _jnp()
    ttype = attrs.get("transform_type", "affine")
    if ttype == "affine":
        h, w = _tup(attrs["target_shape"], 2)
        theta = ins[0].reshape((-1, 2, 3))
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx.reshape(-1), gy.reshape(-1),
                            ones.reshape(-1)], axis=0)  # (3, h*w)
        out = jnp.einsum("nij,jk->nik", theta, coords,
                     precision=f32_precision(theta))  # (n, 2, h*w)
        return [out.reshape((-1, 2, h, w))]
    # warp: input is flow (n, 2, h, w) added to identity grid
    flow = ins[0]
    n, _, h, w = flow.shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)
    base = jnp.stack([gx, gy], axis=0)[None]
    norm = jnp.asarray([(w - 1) / 2.0, (h - 1) / 2.0],
                       flow.dtype).reshape((1, 2, 1, 1))
    return [base + flow / norm]


def _bilinear_sample(jnp, data, grid):
    """Sample data (n,c,h,w) at grid (n,2,gh,gw) in [-1,1]; zero padding."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0  # (n, gh, gw)
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yy, xx):
        valid = ((yy >= 0) & (yy < h) & (xx >= 0) & (xx < w))
        yc = jnp.clip(yy, 0, h - 1).astype("int32")
        xc = jnp.clip(xx, 0, w - 1).astype("int32")
        # (n, gh, gw) indices into (n, c, h, w) -> (n, c, gh, gw)
        bidx = jnp.arange(n).reshape((n, 1, 1))
        vals = data[bidx, :, yc, xc]  # (n, gh, gw, c)
        vals = jnp.where(valid[..., None], vals, 0.0)
        return jnp.transpose(vals, (0, 3, 1, 2))

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx_ = wx[:, None]
    wy_ = wy[:, None]
    top = v00 * (1 - wx_) + v01 * wx_
    bot = v10 * (1 - wx_) + v11 * wx_
    return top * (1 - wy_) + bot * wy_


@register("BilinearSampler", arg_names=("data", "grid"))
def _bilinear_sampler(attrs, ins, octx):
    """(src/operator/bilinear_sampler-inl.h) — gather-based bilinear warp."""
    jnp = _jnp()
    return [_bilinear_sample(jnp, ins[0], ins[1])]


def _st_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    in_shapes[1] = (data[0], 6)
    h, w = _tup(attrs["target_shape"], 2)
    return in_shapes, [(data[0], data[1], h, w)], aux


@register("SpatialTransformer", arg_names=("data", "loc"),
          attr_types={"target_shape": tuple, "transform_type": str,
                      "sampler_type": str},
          infer_shape=_st_infer)
def _spatial_transformer(attrs, ins, octx):
    """Affine spatial transformer (src/operator/spatial_transformer-inl.h)."""
    jnp = _jnp()
    data, loc = ins
    h, w = _tup(attrs["target_shape"], 2)
    theta = loc.reshape((-1, 2, 3))
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gx, gy = jnp.meshgrid(xs, ys)
    coords = jnp.stack([gx.reshape(-1), gy.reshape(-1),
                        jnp.ones_like(gx).reshape(-1)], axis=0)
    grid = jnp.einsum("nij,jk->nik", theta, coords,
                      precision=f32_precision(theta)).reshape((-1, 2, h, w))
    return [_bilinear_sample(jnp, data, grid)]
