"""Operator library — importing this package registers every op.

Layout mirrors the reference src/operator/ families:
elemwise/broadcast/matrix -> tensor/*; nn/conv -> the legacy layer ops;
optimizer_ops -> optimizer_op.cc; sample -> sample_op.h; rnn -> cuDNN RNN
replaced with lax.scan.
"""
from . import elemwise  # noqa: F401
from . import broadcast  # noqa: F401
from . import matrix  # noqa: F401
from . import init_ops  # noqa: F401
from . import sample  # noqa: F401
from . import nn  # noqa: F401
from . import conv  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn_op  # noqa: F401
from . import contrib  # noqa: F401
from . import detection  # noqa: F401
from . import sequence_loss  # noqa: F401
from . import parallel_ops  # noqa: F401
from .. import operator  # noqa: F401  (registers the Custom op)
