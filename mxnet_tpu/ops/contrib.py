"""Contrib operators (src/operator/contrib/): fft/ifft, count_sketch,
MultiBox* detection ops, Proposal. Registered under the ``_contrib_`` prefix
like the reference.
"""
from __future__ import annotations

import numpy as onp

from ..registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _fft_infer(attrs, in_shapes, aux):
    d = in_shapes[0]
    if d is None:
        return in_shapes, None, aux
    return in_shapes, [tuple(d[:-1]) + (d[-1] * 2,)], aux


@register("_contrib_fft", attr_types={"compute_size": int},
          infer_shape=_fft_infer, alias=("fft",))
def _fft(attrs, ins, octx):
    """FFT over the last dim; complex output interleaved [re, im] pairs
    (src/operator/contrib/fft-inl.h) — lax.fft under the hood."""
    jnp = _jnp()
    x = ins[0]
    c = jnp.fft.fft(x.astype("float32"), axis=-1)
    out = jnp.stack([c.real, c.imag], axis=-1)
    return [out.reshape(x.shape[:-1] + (x.shape[-1] * 2,)).astype(x.dtype)]


def _ifft_infer(attrs, in_shapes, aux):
    d = in_shapes[0]
    if d is None:
        return in_shapes, None, aux
    return in_shapes, [tuple(d[:-1]) + (d[-1] // 2,)], aux


@register("_contrib_ifft", attr_types={"compute_size": int},
          infer_shape=_ifft_infer, alias=("ifft",))
def _ifft(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    pairs = x.reshape(x.shape[:-1] + (x.shape[-1] // 2, 2))
    c = pairs[..., 0] + 1j * pairs[..., 1]
    # reference ifft does NOT normalize by N (cuFFT inverse is unscaled)
    out = jnp.fft.ifft(c, axis=-1) * (x.shape[-1] // 2)
    return [out.real.astype(x.dtype)]


@register("_contrib_count_sketch", arg_names=("data", "h", "s"),
          attr_types={"out_dim": int, "processing_batch_size": int})
def _count_sketch(attrs, ins, octx):
    """Count-sketch projection (src/operator/contrib/count_sketch-inl.h)."""
    jnp = _jnp()
    data, h, s = ins
    out_dim = int(attrs["out_dim"])
    hh = h.reshape(-1).astype("int32")
    ss = s.reshape(-1)
    vals = data * ss[None, :]
    out = jnp.zeros((data.shape[0], out_dim), data.dtype)
    return [out.at[:, hh].add(vals)]


@register("_contrib_MultiBoxPrior", arg_names=("data",),
          attr_types={"sizes": tuple, "ratios": tuple, "clip": bool,
                      "steps": tuple, "offsets": tuple})
def _multibox_prior(attrs, ins, octx):
    """Anchor-box generation (src/operator/contrib/multibox_prior-inl.h).
    Output (1, h*w*num_anchors, 4) in normalized corner coords."""
    jnp = _jnp()
    x = ins[0]
    h, w = x.shape[2], x.shape[3]
    sizes = attrs.get("sizes", (1.0,))
    ratios = attrs.get("ratios", (1.0,))
    if isinstance(sizes, float):
        sizes = (sizes,)
    if isinstance(ratios, float):
        ratios = (ratios,)
    steps = attrs.get("steps", (-1.0, -1.0))
    offsets = attrs.get("offsets", (0.5, 0.5))
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (onp.arange(h) + offsets[0]) * step_y
    cx = (onp.arange(w) + offsets[1]) * step_x
    boxes = []
    # reference enumerates (size_i, ratio_0) then (size_0, ratio_j>0)
    combos = [(s, ratios[0]) for s in sizes] + \
             [(sizes[0], r) for r in ratios[1:]]
    for yy in cy:
        for xx in cx:
            for s, r in combos:
                sr = onp.sqrt(r)
                bw = s * sr / 2
                bh = s / sr / 2
                boxes.append([xx - bw, yy - bh, xx + bw, yy + bh])
    out = onp.asarray(boxes, dtype=onp.float32)
    if attrs.get("clip", False):
        out = onp.clip(out, 0.0, 1.0)
    return [jnp.asarray(out[None])]


def _quantize_infer(attrs, in_shapes, aux):
    d = in_shapes[0]
    if in_shapes[1] is None:
        in_shapes[1] = (1,)
    if in_shapes[2] is None:
        in_shapes[2] = (1,)
    if d is None:
        return in_shapes, None, aux
    return in_shapes, [tuple(d), (1,), (1,)], aux


@register("_contrib_quantize", arg_names=("data", "min_range", "max_range"),
          out_names=("output", "min_output", "max_output"),
          attr_types={"out_type": str}, infer_shape=_quantize_infer,
          alias=("quantize",))
def _quantize(attrs, ins, octx):
    """Affine quantization (src/operator/contrib/quantize-inl.h:29
    ``quantize::Map``): out = (in - min) * (lim_max-lim_min)/(max-min) + .5,
    carrying the range through. ``out_type`` picks the integer dtype
    (reference enum admits uint8 only; int8 accepted as an extension)."""
    jnp = _jnp()
    data, mn, mx = ins
    out_type = attrs.get("out_type", "uint8")
    if out_type not in ("uint8", "int8"):
        raise ValueError("unsupported quantize out_type %s" % out_type)
    info = onp.iinfo(out_type)
    scale = (float(info.max) - float(info.min)) / (mx - mn)
    q = (data - mn.reshape((1,) * data.ndim)) * scale.reshape(
        (1,) * data.ndim) + float(info.min) + 0.5
    return [jnp.clip(q, info.min, info.max).astype(out_type), mn, mx]


def _dequantize_infer(attrs, in_shapes, aux):
    d = in_shapes[0]
    if in_shapes[1] is None:
        in_shapes[1] = (1,)
    if in_shapes[2] is None:
        in_shapes[2] = (1,)
    if d is None:
        return in_shapes, None, aux
    return in_shapes, [tuple(d)], aux


@register("_contrib_dequantize", arg_names=("data", "min_range", "max_range"),
          attr_types={"out_type": str}, infer_shape=_dequantize_infer,
          alias=("dequantize",))
def _dequantize(attrs, ins, octx):
    """Quantized int -> float32 (src/operator/contrib/dequantize-inl.h);
    input dtype determines the integer limits."""
    jnp = _jnp()
    data, mn, mx = ins
    info = onp.iinfo(onp.dtype(str(data.dtype)))
    scale = (mx - mn) / (float(info.max) - float(info.min))
    out = (data.astype(jnp.float32) - float(info.min)) \
        * scale.reshape((1,) * data.ndim) + mn.reshape((1,) * data.ndim)
    return [out]
