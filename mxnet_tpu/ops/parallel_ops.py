"""Mesh-aware parallel layer ops — the Module-reachable surface for
expert parallelism and sequence parallelism (VERDICT r3 #5; new design
per SURVEY §2.3, no reference counterpart: the reference scales MoE/
long-context by hand-written device placement, this framework by
sharding annotations).

Both ops read :func:`registry.current_mesh` at trace time (set by
MeshExecutorGroup around its evaluator closures):

* ``MoE`` — Switch-style top-1 router + capacity-bucketed expert FFN in
  the GSPMD formulation: dispatch/combine are einsums over an
  expert-major buffer whose expert dim carries a sharding constraint on
  the ``ep`` mesh axis, and the expert weights arrive ``ep``-sharded via
  ``Module(param_sharding=...)`` rules — XLA inserts the all-to-alls.
  Routing math is GLOBAL (same tokens, same cumsum order) regardless of
  the mesh, so the sharded program is numerically the 1-device program.
* ``RingAttention`` — blockwise ring attention over the ``sp`` axis
  (parallel/ring_attention.py): GSPMD cannot express the ppermute ring
  schedule, so the op drops into ``shard_map`` for the staged region;
  without an ``sp`` axis it runs the exact single-device attention the
  ring is equality-tested against.
"""
from __future__ import annotations

from ..registry import register, current_mesh
from ..parallel.expert_parallel import top1_routing, moe_ffn_block
from ..parallel.ring_attention import ring_attention, local_attention


def _jnp():
    import jax.numpy as jnp
    return jnp


def _moe_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    E = int(attrs["num_experts"])
    f = int(attrs["hidden_size"])
    d = data[-1]
    in_shapes[1] = (d, E)
    in_shapes[2] = (E, d, f)
    in_shapes[3] = (E, f)
    in_shapes[4] = (E, f, d)
    in_shapes[5] = (E, d)
    return in_shapes, [tuple(data), ()], aux


@register("MoE", arg_names=("data", "gate_weight", "expert1_weight",
                            "expert1_bias", "expert2_weight",
                            "expert2_bias"),
          attr_types={"num_experts": int, "hidden_size": int,
                      "capacity_factor": float},
          required_attrs=("num_experts", "hidden_size"),
          infer_shape=_moe_infer, num_outputs=2,
          out_names=("output", "aux_loss"))
def _moe(attrs, ins, octx):
    """Switch-style top-1 mixture-of-experts block, ep-shardable.

    Outputs: the routed expert output (same shape as data) and the
    scalar load-balance aux loss (add it into the objective via
    MakeLoss)."""
    import math

    jnp = _jnp()
    x, wg, w1, b1, w2, b2 = ins
    E = int(attrs["num_experts"])
    cf = float(attrs.get("capacity_factor", 1.25))

    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    T = xt.shape[0]
    cap = max(1, int(math.ceil(T * cf / E)))

    f32 = jnp.float32
    logits = xt.astype(f32) @ wg.astype(f32)
    dispatch, combine, aux = top1_routing(logits, cap)

    # expert-major buffer (E, C, d); constrain its expert dim onto the
    # 'ep' axis when one exists — GSPMD turns the einsums around it into
    # the dispatch/collect all-to-alls
    sendbuf = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), xt)
    sendbuf = _constrain_leading_ep(sendbuf)
    expert_out = moe_ffn_block(sendbuf, w1.astype(x.dtype),
                               b1.astype(x.dtype), w2.astype(x.dtype),
                               b2.astype(x.dtype))
    expert_out = _constrain_leading_ep(expert_out)
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return [y.reshape(lead + (d,)), aux.astype(f32)]


def _constrain_leading_ep(t):
    mesh = current_mesh()
    if mesh is None or "ep" not in mesh.axis_names:
        return t
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = P(*(("ep",) + (None,) * (t.ndim - 1)))
    return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, spec))


def _ring_infer(attrs, in_shapes, aux):
    q = in_shapes[0]
    if q is None:
        return in_shapes, None, aux
    in_shapes[1] = tuple(q)
    in_shapes[2] = tuple(q)
    return in_shapes, [tuple(q)], aux


@register("RingAttention", arg_names=("query", "key", "value"),
          attr_types={"causal": bool, "scale": float},
          infer_shape=_ring_infer)
def _ring_attention_op(attrs, ins, octx):
    """Sequence-parallel self-attention over (B, H, T, D) inputs.

    With an 'sp' mesh axis the sequence dim is ring-scheduled over it
    (shard_map + ppermute); otherwise exact single-device attention —
    the ring's tests pin the two equal up to the blockwise
    log-sum-exp accumulation."""
    q, k, v = ins
    causal = bool(attrs.get("causal", False))
    scale = attrs.get("scale")
    scale = float(scale) if scale is not None else None

    mesh = current_mesh()
    if mesh is None or "sp" not in mesh.axis_names:
        return [local_attention(q, k, v, causal=causal, scale=scale)]

    from functools import partial

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    sp = axes["sp"]
    if q.shape[2] % sp:
        raise ValueError(
            "RingAttention: sequence length %d not divisible by the "
            "sp axis (%d)" % (q.shape[2], sp))
    bdim = "dp" if "dp" in mesh.axis_names else None
    spec = P(bdim, None, "sp", None)
    fn = shard_map(
        partial(ring_attention, axis_name="sp", causal=causal,
                scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return [fn(q, k, v)]
