"""Fused optimizer update ops (src/operator/optimizer_op.cc).

The Python Optimizer calls these exactly like the reference does
(python/mxnet/optimizer.py:310-322): one op application per parameter, fully
fused by XLA. All mutate ``weight`` in place through the ``out=`` convention.
"""
from __future__ import annotations

import numpy as onp

from ..registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


_SGD_ATTRS = {"lr": float, "wd": float, "rescale_grad": float,
              "clip_gradient": float, "momentum": float}


def _sc(attrs, key, default):
    """Scalar attr that may be a python number OR a traced jax value (the
    sharded train step passes lr as a jit argument to avoid recompiles)."""
    v = attrs.get(key, default)
    return float(v) if isinstance(v, (int, float, str)) else v


def _prep(jnp, attrs, grad):
    rescale = _sc(attrs, "rescale_grad", 1.0)
    clip = attrs.get("clip_gradient", None)
    g = grad * rescale
    if clip is not None and float(clip) > 0:
        c = float(clip)
        g = jnp.clip(g, -c, c)
    return g


@register("sgd_update", arg_names=("weight", "grad"), attr_types=_SGD_ATTRS)
def _sgd_update(attrs, ins, octx):
    jnp = _jnp()
    w, grad = ins
    lr = _sc(attrs, "lr", 0.01)
    wd = _sc(attrs, "wd", 0.0)
    g = _prep(jnp, attrs, grad)
    return [w - lr * (g + wd * w)]


@register("sgd_mom_update", arg_names=("weight", "grad", "mom"),
          out_names=("weight", "mom"), attr_types=_SGD_ATTRS)
def _sgd_mom_update(attrs, ins, octx):
    jnp = _jnp()
    w, grad, mom = ins
    lr = _sc(attrs, "lr", 0.01)
    wd = _sc(attrs, "wd", 0.0)
    momentum = float(attrs.get("momentum", 0.0))
    g = _prep(jnp, attrs, grad)
    new_mom = momentum * mom - lr * (g + wd * w)
    return [w + new_mom, new_mom]


@register("adam_update", arg_names=("weight", "grad", "mean", "var"),
          out_names=("weight", "mean", "var"),
          attr_types={"lr": float, "beta1": float, "beta2": float,
                      "epsilon": float, "wd": float, "rescale_grad": float,
                      "clip_gradient": float})
def _adam_update(attrs, ins, octx):
    jnp = _jnp()
    w, grad, mean, var = ins
    lr = _sc(attrs, "lr", 0.01)
    beta1 = float(attrs.get("beta1", 0.9))
    beta2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-8))
    wd = _sc(attrs, "wd", 0.0)
    g = _prep(jnp, attrs, grad) + wd * w
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = w - lr * new_mean / (jnp.sqrt(new_var) + eps)
    return [new_w, new_mean, new_var]


@register("rmsprop_update", arg_names=("weight", "grad", "n"),
          out_names=("weight", "n"), attr_types={"lr": float, "gamma1": float, "epsilon": float,
                      "wd": float, "rescale_grad": float,
                      "clip_gradient": float, "clip_weights": float})
def _rmsprop_update(attrs, ins, octx):
    jnp = _jnp()
    w, grad, n = ins
    lr = _sc(attrs, "lr", 0.01)
    gamma1 = float(attrs.get("gamma1", 0.95))
    eps = float(attrs.get("epsilon", 1e-8))
    wd = _sc(attrs, "wd", 0.0)
    g = _prep(jnp, attrs, grad) + wd * w
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_w = w - lr * g / jnp.sqrt(new_n + eps)
    cw = attrs.get("clip_weights", None)
    if cw is not None and float(cw) > 0:
        new_w = jnp.clip(new_w, -float(cw), float(cw))
    return [new_w, new_n]


@register("rmspropalex_update",
          arg_names=("weight", "grad", "n", "g", "delta"),
          out_names=("weight", "n", "g", "delta"),
          attr_types={"lr": float, "gamma1": float, "gamma2": float,
                      "epsilon": float, "wd": float, "rescale_grad": float,
                      "clip_gradient": float, "clip_weights": float})
def _rmspropalex_update(attrs, ins, octx):
    """Graves-form RMSProp (optimizer_op.cc rmspropalex_update)."""
    jnp = _jnp()
    w, grad, n, gbar, delta = ins
    lr = _sc(attrs, "lr", 0.01)
    gamma1 = float(attrs.get("gamma1", 0.95))
    gamma2 = float(attrs.get("gamma2", 0.9))
    eps = float(attrs.get("epsilon", 1e-8))
    wd = _sc(attrs, "wd", 0.0)
    g = _prep(jnp, attrs, grad) + wd * w
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_gbar = (1 - gamma1) * g + gamma1 * gbar
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(
        new_n - jnp.square(new_gbar) + eps)
    new_w = w + new_delta
    cw = attrs.get("clip_weights", None)
    if cw is not None and float(cw) > 0:
        new_w = jnp.clip(new_w, -float(cw), float(cw))
    return [new_w, new_n, new_gbar, new_delta]
