"""Random sampling ops (src/operator/tensor/sample_op.h).

Each op consumes a JAX PRNG key from OpContext.rng (threaded by the executor
/ imperative invoke from the global seed state, replacing the per-context
kRandom resource, src/resource.cc:70-77).
"""
from __future__ import annotations

import numpy as onp

from ..registry import register

_COMMON = {"shape": tuple, "dtype": str}


def _shape_of(attrs):
    shape = attrs.get("shape", (1,))
    if isinstance(shape, int):
        shape = (shape,)
    return tuple(shape)


def _dtype_of(attrs, default="float32"):
    return onp.dtype(attrs.get("dtype") or default)


def _shape_infer(attrs, in_shapes, aux):
    return in_shapes, [_shape_of(attrs)], aux


def _sample(name, fn, extra_attrs, alias=()):
    attr_types = dict(_COMMON)
    attr_types.update(extra_attrs)

    @register(name, arg_names=(), attr_types=attr_types, needs_rng=True,
              infer_shape=_shape_infer, alias=alias)
    def _f(attrs, ins, octx, _fn=fn):
        import jax
        return [_fn(jax, octx.rng, _shape_of(attrs), _dtype_of(attrs), attrs)]
    return _f


_sample("_random_uniform",
        lambda jax, key, shape, dt, a: jax.random.uniform(
            key, shape, dtype=dt, minval=float(a.get("low", 0.0)),
            maxval=float(a.get("high", 1.0))),
        {"low": float, "high": float},
        alias=("uniform", "random_uniform", "_sample_uniform"))

_sample("_random_normal",
        lambda jax, key, shape, dt, a: float(a.get("scale", 1.0))
        * jax.random.normal(key, shape, dtype=dt) + float(a.get("loc", 0.0)),
        {"loc": float, "scale": float},
        alias=("normal", "random_normal", "_sample_normal"))

_sample("_random_gamma",
        lambda jax, key, shape, dt, a: float(a.get("beta", 1.0))
        * jax.random.gamma(key, float(a.get("alpha", 1.0)), shape, dtype=dt),
        {"alpha": float, "beta": float},
        alias=("random_gamma", "_sample_gamma"))

_sample("_random_exponential",
        lambda jax, key, shape, dt, a: jax.random.exponential(
            key, shape, dtype=dt) / float(a.get("lam", 1.0)),
        {"lam": float},
        alias=("random_exponential", "_sample_exponential"))

_sample("_random_poisson",
        lambda jax, key, shape, dt, a: jax.random.poisson(
            key, float(a.get("lam", 1.0)), shape).astype(dt),
        {"lam": float},
        alias=("random_poisson", "_sample_poisson"))

_sample("_random_negative_binomial",
        lambda jax, key, shape, dt, a: _neg_binomial(
            jax, key, shape, dt, int(a.get("k", 1)), float(a.get("p", 0.5))),
        {"k": int, "p": float},
        alias=("random_negative_binomial", "_sample_negbinomial"))

_sample("_random_generalized_negative_binomial",
        lambda jax, key, shape, dt, a: _gen_neg_binomial(
            jax, key, shape, dt, float(a.get("mu", 1.0)),
            float(a.get("alpha", 1.0))),
        {"mu": float, "alpha": float},
        alias=("random_generalized_negative_binomial",
               "_sample_gennegbinomial"))

_sample("random_randint",
        lambda jax, key, shape, dt, a: jax.random.randint(
            key, shape, int(a.get("low", 0)), int(a.get("high", 2))).astype(dt),
        {"low": int, "high": int})


def _neg_binomial(jax, key, shape, dt, k, p):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape).astype(dt)


def _gen_neg_binomial(jax, key, shape, dt, mu, alpha):
    """Generalized (Polya) negative binomial: gamma-Poisson mixture with
    mean mu and dispersion alpha (sample_op.h GeneralizedNegativeBinomial
    — real-valued k = 1/alpha, scale mu*alpha)."""
    if alpha <= 0:  # degenerate: plain Poisson(mu)
        return jax.random.poisson(key, mu, shape).astype(dt)
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, 1.0 / alpha, shape) * (mu * alpha)
    return jax.random.poisson(k2, lam, shape).astype(dt)
