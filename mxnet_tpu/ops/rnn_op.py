"""Fused multi-layer RNN op — the cuDNN RNN replacement.

The reference's RNN op is GPU-only cuDNN (src/operator/rnn.cc:14 "RNN is only
available for gpu"; cudnn_rnn-inl.h). Here it is a ``lax.scan`` over time with
per-layer weights sliced out of the single flat parameter vector in cuDNN
canonical layout (all W/R matrices layer-major first, then all biases), so
``FusedRNNCell.unfuse()``-style weight sharing keeps working. The scan is
jit-friendly (static T) and XLA pipelines the per-step matmuls onto the MXU.

Modes: rnn_relu / rnn_tanh / lstm / gru; bidirectional; multi-layer.
Gate order matches cuDNN: LSTM [i, f, g, o], GRU [r, z, n].
"""
from __future__ import annotations

import numpy as onp

from ..registry import register, f32_precision


def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def _rnn_args(attrs):
    if attrs.get("mode", "lstm") == "lstm":
        return ("data", "parameters", "state", "state_cell")
    return ("data", "parameters", "state")


def _rnn_num_outputs(attrs):
    if not attrs.get("state_outputs", False):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    """Total flat parameter count (matches cudnn_rnn-inl.h GetParamSize)."""
    g = _gates(mode)
    d = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        per_dir = g * state_size * (in_sz + state_size + 2)
        size += per_dir * d
    return size


def _rnn_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    if data is None:
        return in_shapes, None, aux
    t, n, i = data
    h = int(attrs["state_size"])
    layers = int(attrs["num_layers"])
    bi = attrs.get("bidirectional", False)
    d = 2 if bi else 1
    mode = attrs.get("mode", "lstm")
    in_shapes[1] = (rnn_param_size(layers, i, h, bi, mode),)
    in_shapes[2] = (layers * d, n, h)
    if mode == "lstm" and len(in_shapes) > 3:
        in_shapes[3] = (layers * d, n, h)
    outs = [(t, n, h * d)]
    if attrs.get("state_outputs", False):
        outs.append((layers * d, n, h))
        if mode == "lstm":
            outs.append((layers * d, n, h))
    return in_shapes, outs, aux


def _split_params(jnp, params, num_layers, input_size, state_size, d, g):
    """Slice the flat vector into per-(layer,dir) (W, R, bW, bR)."""
    mats, biases = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        for _ in range(d):
            w = params[off:off + g * state_size * in_sz].reshape(
                (g * state_size, in_sz))
            off += g * state_size * in_sz
            r = params[off:off + g * state_size * state_size].reshape(
                (g * state_size, state_size))
            off += g * state_size * state_size
            mats.append((w, r))
    for layer in range(num_layers):
        for _ in range(d):
            bw = params[off:off + g * state_size]
            off += g * state_size
            br = params[off:off + g * state_size]
            off += g * state_size
            biases.append((bw, br))
    return [(mats[i][0], mats[i][1], biases[i][0], biases[i][1])
            for i in range(len(mats))]


def _cell_step(jnp, mode, h_prev, c_prev, pre, state_size):
    """One timestep given preactivations pre = x·Wᵀ + h·Rᵀ + b."""
    if mode == "rnn_relu":
        h = jnp.maximum(pre, 0)
        return h, c_prev
    if mode == "rnn_tanh":
        h = jnp.tanh(pre)
        return h, c_prev
    if mode == "lstm":
        i, f, gt, o = [pre[:, k * state_size:(k + 1) * state_size]
                       for k in range(4)]
        i = 1 / (1 + jnp.exp(-i))
        f = 1 / (1 + jnp.exp(-f))
        gt = jnp.tanh(gt)
        o = 1 / (1 + jnp.exp(-o))
        c = f * c_prev + i * gt
        return o * jnp.tanh(c), c
    raise ValueError(mode)


def _scan_layer(jax, jnp, mode, x, h0, c0, w, r, bw, br, state_size, reverse):
    """Scan one direction of one layer. x: (T, N, in). Returns (T,N,H), hT, cT."""
    prec = f32_precision(x)
    xw = jnp.einsum("tni,gi->tng", x, w,
                    precision=prec) + bw[None, None, :]

    if mode == "gru":
        def step(carry, xt):
            h_prev, _ = carry
            hr = jnp.dot(h_prev, r.T,
                         precision=prec) + br[None, :]
            rg = 1 / (1 + jnp.exp(-(xt[:, :state_size] + hr[:, :state_size])))
            zg = 1 / (1 + jnp.exp(-(xt[:, state_size:2 * state_size]
                                    + hr[:, state_size:2 * state_size])))
            ng = jnp.tanh(xt[:, 2 * state_size:] + rg * hr[:, 2 * state_size:])
            h = (1 - zg) * ng + zg * h_prev
            return (h, h), h
    else:
        def step(carry, xt):
            h_prev, c_prev = carry
            pre = xt + jnp.dot(h_prev, r.T,
                               precision=prec) + br[None, :]
            h, c = _cell_step(jnp, mode, h_prev, c_prev, pre, state_size)
            return (h, c), h

    (hT, cT), ys = jax.lax.scan(step, (h0, c0), xw, reverse=reverse)
    return ys, hT, cT


@register("RNN", arg_names=_rnn_args, num_outputs=_rnn_num_outputs,
          attr_types={"state_size": int, "num_layers": int,
                      "bidirectional": bool, "mode": str, "p": float,
                      "state_outputs": bool, "lstm_state_clip_min": float,
                      "lstm_state_clip_max": float},
          required_attrs=("state_size", "num_layers", "mode"),
          infer_shape=_rnn_infer, needs_rng=True)
def _rnn(attrs, ins, octx):
    import jax
    import jax.numpy as jnp

    mode = attrs.get("mode", "lstm")
    state_size = int(attrs["state_size"])
    num_layers = int(attrs["num_layers"])
    bi = attrs.get("bidirectional", False)
    d = 2 if bi else 1
    g = _gates(mode)
    pdrop = float(attrs.get("p", 0.0))

    data, params, state0 = ins[0], ins[1], ins[2]
    cell0 = ins[3] if mode == "lstm" and len(ins) > 3 else jnp.zeros_like(state0)
    T, N, input_size = data.shape

    layers = _split_params(jnp, params, num_layers, input_size, state_size, d, g)

    x = data
    h_finals, c_finals = [], []
    rng = octx.rng
    for layer in range(num_layers):
        outs_dir = []
        for di in range(d):
            idx = layer * d + di
            w, r, bw, br = layers[idx]
            h0 = state0[idx]
            c0 = cell0[idx]
            ys, hT, cT = _scan_layer(jax, jnp, mode, x, h0, c0, w, r, bw, br,
                                     state_size, reverse=(di == 1))
            outs_dir.append(ys)
            h_finals.append(hT)
            c_finals.append(cT)
        x = outs_dir[0] if d == 1 else jnp.concatenate(outs_dir, axis=-1)
        if pdrop > 0 and octx.is_train and layer < num_layers - 1 and rng is not None:
            rng, sub = jax.random.split(rng)
            mask = jax.random.bernoulli(sub, 1 - pdrop, x.shape)
            x = jnp.where(mask, x / (1 - pdrop), 0.0)

    outs = [x]
    if attrs.get("state_outputs", False):
        outs.append(jnp.stack(h_finals, axis=0))
        if mode == "lstm":
            outs.append(jnp.stack(c_finals, axis=0))
    return outs
