"""Creation ops (src/operator/tensor/init_op.h: zeros/ones/arange/*_like)."""
from __future__ import annotations

import numpy as onp

from ..registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _shape_infer(attrs, in_shapes, aux):
    shape = attrs.get("shape", ())
    if isinstance(shape, int):
        shape = (shape,)
    return in_shapes, [tuple(shape)], aux


@register("_zeros", arg_names=(), attr_types={"shape": tuple, "dtype": str},
          infer_shape=_shape_infer, alias=("zeros",))
def _zeros_op(attrs, ins, octx):
    shape = attrs.get("shape", ())
    if isinstance(shape, int):
        shape = (shape,)
    return [_jnp().zeros(shape, dtype=onp.dtype(attrs.get("dtype", "float32")))]


@register("_ones", arg_names=(), attr_types={"shape": tuple, "dtype": str},
          infer_shape=_shape_infer, alias=("ones",))
def _ones_op(attrs, ins, octx):
    shape = attrs.get("shape", ())
    if isinstance(shape, int):
        shape = (shape,)
    return [_jnp().ones(shape, dtype=onp.dtype(attrs.get("dtype", "float32")))]


@register("_full", arg_names=(),
          attr_types={"shape": tuple, "dtype": str, "value": float},
          infer_shape=_shape_infer)
def _full_op(attrs, ins, octx):
    shape = attrs.get("shape", ())
    if isinstance(shape, int):
        shape = (shape,)
    return [_jnp().full(shape, float(attrs.get("value", 0.0)),
                        dtype=onp.dtype(attrs.get("dtype", "float32")))]


def _arange_infer(attrs, in_shapes, aux):
    start = float(attrs.get("start", 0.0))
    stop = attrs.get("stop", None)
    step = float(attrs.get("step", 1.0))
    repeat = int(attrs.get("repeat", 1))
    if stop is None:
        start, stop = 0.0, start
    n = int(onp.ceil((float(stop) - start) / step)) * repeat
    return in_shapes, [(n,)], aux


@register("_arange", arg_names=(),
          attr_types={"start": float, "stop": float, "step": float,
                      "repeat": int, "dtype": str},
          infer_shape=_arange_infer, alias=("arange_op",))
def _arange_op(attrs, ins, octx):
    jnp = _jnp()
    start = float(attrs.get("start", 0.0))
    stop = attrs.get("stop", None)
    step = float(attrs.get("step", 1.0))
    repeat = int(attrs.get("repeat", 1))
    if stop is None:
        start, stop = 0.0, start
    vals = onp.arange(start, float(stop), step,
                      dtype=onp.dtype(attrs.get("dtype", "float32")))
    if repeat != 1:
        vals = onp.repeat(vals, repeat)
    return [jnp.asarray(vals)]


@register("zeros_like")
def _zeros_like(attrs, ins, octx):
    return [_jnp().zeros_like(ins[0])]


@register("ones_like")
def _ones_like(attrs, ins, octx):
    return [_jnp().ones_like(ins[0])]
