"""CTCLoss + Correlation — the remaining specialty layer ops.

CTCLoss replaces the warpctc plugin (plugin/warpctc, src/operator/
contrib/ctc_loss): log-space forward algorithm as a ``lax.scan`` over time;
the gradient comes from differentiating the scan (XLA keeps it on-device),
instead of warpctc's hand-written alpha-beta kernels.

Correlation (src/operator/correlation-inl.h, FlowNet) is expressed as a
displacement-enumerated elementwise product + channel reduction — a static
shift loop XLA fuses, replacing the CUDA patch kernel.
"""
from __future__ import annotations

import numpy as onp

from ..registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _ctc_infer(attrs, in_shapes, aux):
    data, label = in_shapes[0], in_shapes[1]
    if data is None:
        return in_shapes, None, aux
    if label is None and in_shapes[1] is None:
        return in_shapes, None, aux
    return in_shapes, [(data[1],)], aux


NEG_INF = -1e30


def _ctc_loss_single(jnp, logprobs, labels, blank):
    """CTC negative log likelihood for one sample.

    logprobs: (T, C) log-softmax; labels: (L,) int32, 0 = padding
    (blank_label='first' convention: class 0 is blank, valid labels >= 1).
    """
    import jax
    T, C = logprobs.shape
    L = labels.shape[0]
    S = 2 * L + 1
    # extended sequence: blank, l1, blank, l2, ... blank
    ext = jnp.full((S,), blank, dtype="int32")
    ext = ext.at[1::2].set(labels)
    valid_lab = labels > 0
    num_valid = jnp.sum(valid_lab.astype("int32"))
    S_valid = 2 * num_valid + 1

    # can alpha skip from s-2 to s (different consecutive labels)?
    skip_ok = jnp.zeros((S,), bool)
    skip_ok = skip_ok.at[2::2].set(False)
    lab_prev = jnp.concatenate([jnp.full((1,), -1, "int32"), labels[:-1]])
    skip_ok = skip_ok.at[3::2].set(labels[1:] != labels[:-1]) \
        if L > 1 else skip_ok

    alpha0 = jnp.full((S,), NEG_INF)
    alpha0 = alpha0.at[0].set(logprobs[0, blank])
    alpha0 = alpha0.at[1].set(jnp.where(L > 0, logprobs[0, ext[1]],
                                        NEG_INF))

    def step(alpha, lp):
        prev1 = jnp.concatenate([jnp.full((1,), NEG_INF), alpha[:-1]])
        prev2 = jnp.concatenate([jnp.full((2,), NEG_INF), alpha[:-2]])
        prev2 = jnp.where(skip_ok, prev2, NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
        new_alpha = merged + lp[ext]
        return new_alpha, None

    alpha, _ = jax.lax.scan(step, alpha0, logprobs[1:])
    # final: last blank or last label of the VALID sequence
    end1 = alpha[jnp.maximum(S_valid - 1, 0)]
    end2 = jnp.where(S_valid >= 2, alpha[jnp.maximum(S_valid - 2, 0)],
                     NEG_INF)
    return -jnp.logaddexp(end1, end2)


@register("CTCLoss", arg_names=("data", "label"),
          attr_types={"use_data_lengths": bool, "use_label_lengths": bool,
                      "blank_label": str},
          infer_shape=_ctc_infer, num_outputs=1,
          alias=("ctc_loss", "_contrib_CTCLoss"))
def _ctc_loss(attrs, ins, octx):
    """data (T, N, C) activations (softmax applied internally),
    label (N, L) 1-indexed classes padded with 0; returns per-sample loss
    (N,). blank_label='first' (class 0)."""
    import jax
    jnp = _jnp()
    data, label = ins[0], ins[1]
    lp = jax.nn.log_softmax(data, axis=-1)  # (T,N,C)
    labels = label.astype("int32")          # (N,L)

    def per_sample(lp_n, lab_n):
        return _ctc_loss_single(jnp, lp_n, lab_n, 0)

    losses = jax.vmap(per_sample, in_axes=(1, 0))(lp, labels)
    return [losses]


def _corr_infer(attrs, in_shapes, aux):
    d1 = in_shapes[0]
    if d1 is None:
        return in_shapes, None, aux
    md = int(attrs.get("max_displacement", 1))
    s2 = int(attrs.get("stride2", 1))
    d = 2 * (md // s2) + 1
    return in_shapes, [(d1[0], d * d, d1[2], d1[3])], aux


@register("Correlation", arg_names=("data1", "data2"),
          attr_types={"kernel_size": int, "max_displacement": int,
                      "stride1": int, "stride2": int, "pad_size": int,
                      "is_multiply": bool})
def _correlation(attrs, ins, octx):
    """Displacement correlation (correlation-inl.h). kernel_size=1 path:
    out[:, k, y, x] = mean_c d1[:, c, y, x] * d2[:, c, y+dy, x+dx]."""
    jnp = _jnp()
    d1, d2 = ins
    N, C, H, W = d1.shape
    md = int(attrs.get("max_displacement", 1))
    s2 = int(attrs.get("stride2", 1))
    multiply = attrs.get("is_multiply", True)
    disp = range(-md, md + 1, s2)
    pad = md
    d2p = jnp.pad(d2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    outs = []
    for dy in disp:
        for dx in disp:
            shifted = d2p[:, :, pad + dy:pad + dy + H,
                          pad + dx:pad + dx + W]
            if multiply:
                outs.append(jnp.mean(d1 * shifted, axis=1))
            else:
                outs.append(jnp.mean(jnp.abs(d1 - shifted), axis=1))
    return [jnp.stack(outs, axis=1)]
