"""Detection operators (src/operator/contrib/: multibox_target,
multibox_detection, proposal; src/operator/roi_pooling handled in conv.py).

All computations are static-shape XLA programs: IoU matrices are dense
(anchors × gt), NMS is an O(N²) mask-suppression loop via lax.fori_loop —
the idiomatic TPU formulation (no dynamic shapes, no host sync), replacing
the reference's CUDA kernels.
"""
from __future__ import annotations

import numpy as onp

from ..registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _iou_matrix(jnp, a, b):
    """IoU between (N,4) and (M,4) corner-format boxes -> (N,M)."""
    ax1, ay1, ax2, ay2 = a[:, 0:1], a[:, 1:2], a[:, 2:3], a[:, 3:4]
    bx1, by1, bx2, by2 = b[None, :, 0], b[None, :, 1], b[None, :, 2], \
        b[None, :, 3]
    ix1 = jnp.maximum(ax1, bx1)
    iy1 = jnp.maximum(ay1, by1)
    ix2 = jnp.minimum(ax2, bx2)
    iy2 = jnp.minimum(ay2, by2)
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    area_a = jnp.maximum((ax2 - ax1) * (ay2 - ay1), 0.0)
    area_b = jnp.maximum((bx2 - bx1) * (by2 - by1), 0.0)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _mbt_infer(attrs, in_shapes, aux):
    anchor, label, cls_pred = in_shapes
    if anchor is None or label is None or cls_pred is None:
        return in_shapes, None, aux
    num_anchors = anchor[1]
    batch = label[0]
    return in_shapes, [(batch, num_anchors * 4), (batch, num_anchors * 4),
                       (batch, num_anchors)], aux


@register("_contrib_MultiBoxTarget",
          arg_names=("anchor", "label", "cls_pred"),
          attr_types={"overlap_threshold": float, "ignore_label": float,
                      "negative_mining_ratio": float,
                      "negative_mining_thresh": float, "variances": tuple,
                      "minimum_negative_samples": int},
          infer_shape=_mbt_infer, num_outputs=3,
          backward_ignores_head_grads=True)
def _multibox_target(attrs, ins, octx):
    """Assign ground-truth to anchors (multibox_target-inl.h).

    anchor (1, A, 4); label (B, M, 5) [cls, x1, y1, x2, y2], cls<0 = pad;
    cls_pred (B, C, A). Outputs loc_target (B, A*4), loc_mask (B, A*4),
    cls_target (B, A) with 0 = background, k+1 = class k.
    """
    import jax
    jnp = _jnp()
    anchor, label, cls_pred = ins
    A = anchor.shape[1]
    anchors = anchor.reshape(A, 4)
    thresh = float(attrs.get("overlap_threshold", 0.5))
    variances = attrs.get("variances", (0.1, 0.1, 0.2, 0.2))

    aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-8)
    ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-8)
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def one_sample(lab):
        valid = lab[:, 0] >= 0  # (M,)
        gt = lab[:, 1:5]
        iou = _iou_matrix(jnp, anchors, gt)  # (A, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)          # (A,)
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou >= thresh
        # force-match the best anchor for each valid gt
        best_anchor = jnp.argmax(iou, axis=0)      # (M,)
        forced = jnp.zeros((A,), bool).at[best_anchor].set(valid)
        forced_gt = jnp.zeros((A,), "int32").at[best_anchor].set(
            jnp.arange(lab.shape[0], dtype="int32"))
        use_forced = forced
        gt_idx = jnp.where(use_forced, forced_gt, best_gt.astype("int32"))
        pos = matched | forced

        g = gt[gt_idx]
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-8)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-8)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        tx = (gcx - acx) / aw / variances[0]
        ty = (gcy - acy) / ah / variances[1]
        tw = jnp.log(gw / aw) / variances[2]
        th = jnp.log(gh / ah) / variances[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=1)  # (A,4)
        loc_t = jnp.where(pos[:, None], loc_t, 0.0)
        loc_m = jnp.broadcast_to(pos[:, None], (A, 4)).astype(loc_t.dtype)
        cls_t = jnp.where(pos, lab[gt_idx, 0] + 1.0, 0.0)
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one_sample)(label)
    dt = cls_pred.dtype
    return [loc_t.astype(dt), loc_m.astype(dt), cls_t.astype(dt)]


def _nms_suppress(jnp, boxes, scores, iou_thresh, topk):
    """Mask-based NMS: returns keep mask (N,), static shapes (lax loop)."""
    import jax
    N = boxes.shape[0]
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    iou = _iou_matrix(jnp, boxes_s, boxes_s)

    def body(i, keep):
        sup = (iou[i] > iou_thresh) & keep[i] & \
            (jnp.arange(N) > i)
        return keep & ~sup

    keep_sorted = jax.lax.fori_loop(0, N, body, jnp.ones((N,), bool))
    keep = jnp.zeros((N,), bool).at[order].set(keep_sorted)
    return keep


def _mbd_infer(attrs, in_shapes, aux):
    cls_prob, loc_pred, anchor = in_shapes
    if cls_prob is None or anchor is None:
        return in_shapes, None, aux
    return in_shapes, [(cls_prob[0], anchor[1], 6)], aux


@register("_contrib_MultiBoxDetection",
          arg_names=("cls_prob", "loc_pred", "anchor"),
          attr_types={"clip": bool, "threshold": float,
                      "background_id": int, "nms_threshold": float,
                      "force_suppress": bool, "variances": tuple,
                      "nms_topk": int},
          infer_shape=_mbd_infer, backward_ignores_head_grads=True)
def _multibox_detection(attrs, ins, octx):
    """Decode + NMS (multibox_detection-inl.h). Output (B, A, 6):
    [cls_id, score, x1, y1, x2, y2], cls_id = -1 for suppressed slots."""
    import jax
    jnp = _jnp()
    cls_prob, loc_pred, anchor = ins
    B, C, A = cls_prob.shape
    anchors = anchor.reshape(A, 4)
    variances = attrs.get("variances", (0.1, 0.1, 0.2, 0.2))
    thresh = float(attrs.get("threshold", 0.01))
    nms_thresh = float(attrs.get("nms_threshold", 0.5))
    clip = attrs.get("clip", True)

    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def one_sample(cp, lp):
        loc = lp.reshape(A, 4)
        cx = loc[:, 0] * variances[0] * aw + acx
        cy = loc[:, 1] * variances[1] * ah + acy
        w = jnp.exp(loc[:, 2] * variances[2]) * aw
        h = jnp.exp(loc[:, 3] * variances[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                          axis=1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        scores = jnp.max(cp[1:], axis=0)             # best fg score (A,)
        cls_id = jnp.argmax(cp[1:], axis=0).astype(cp.dtype)
        valid = scores > thresh
        keep = _nms_suppress(jnp, boxes, jnp.where(valid, scores, -1.0),
                             nms_thresh, A)
        final = valid & keep
        out_id = jnp.where(final, cls_id, -1.0)
        return jnp.concatenate([out_id[:, None], scores[:, None], boxes],
                               axis=1)

    return [jax.vmap(one_sample)(cls_prob, loc_pred)]


def _proposal_infer(attrs, in_shapes, aux):
    cls_prob = in_shapes[0]
    if cls_prob is None:
        return in_shapes, None, aux
    n = int(attrs.get("rpn_post_nms_top_n", 300))
    return in_shapes, [(cls_prob[0] * n, 5)], aux


@register("_contrib_Proposal",
          arg_names=("cls_prob", "bbox_pred", "im_info"),
          attr_types={"rpn_pre_nms_top_n": int, "rpn_post_nms_top_n": int,
                      "threshold": float, "rpn_min_size": int,
                      "scales": tuple, "ratios": tuple,
                      "feature_stride": int, "output_score": bool,
                      "iou_loss": bool},
          infer_shape=_proposal_infer, backward_ignores_head_grads=True,
          alias=("Proposal",))
def _proposal(attrs, ins, octx):
    """RPN proposal generation (src/operator/contrib/proposal-inl.h):
    enumerate anchors on the feature grid, decode bbox deltas, clip, topk by
    fg score, NMS, emit (B*post_nms, 5) rois [batch_idx, x1, y1, x2, y2]."""
    import jax
    jnp = _jnp()
    cls_prob, bbox_pred, im_info = ins
    B, twoA, H, W = cls_prob.shape
    stride = int(attrs.get("feature_stride", 16))
    scales = attrs.get("scales", (4, 8, 16, 32))
    ratios = attrs.get("ratios", (0.5, 1, 2))
    pre_n = int(attrs.get("rpn_pre_nms_top_n", 6000))
    post_n = int(attrs.get("rpn_post_nms_top_n", 300))
    nms_thresh = float(attrs.get("threshold", 0.7))
    if isinstance(scales, (int, float)):
        scales = (scales,)
    if isinstance(ratios, (int, float)):
        ratios = (ratios,)

    # base anchors centered at stride/2 (numpy, compile-time constant)
    base = []
    base_size = stride
    ctr = (base_size - 1) / 2.0
    for r in ratios:
        size = base_size * base_size
        size_r = size / r
        ws = onp.round(onp.sqrt(size_r))
        hs = onp.round(ws * r)
        for s in scales:
            w, h = ws * s, hs * s
            base.append([ctr - (w - 1) / 2, ctr - (h - 1) / 2,
                         ctr + (w - 1) / 2, ctr + (h - 1) / 2])
    base = onp.asarray(base, onp.float32)  # (K,4)
    K = base.shape[0]
    sx = onp.arange(W) * stride
    sy = onp.arange(H) * stride
    gx, gy = onp.meshgrid(sx, sy)
    shifts = onp.stack([gx.ravel(), gy.ravel(), gx.ravel(), gy.ravel()],
                       axis=1)  # (HW, 4)
    all_anchors = (shifts[:, None, :] + base[None, :, :]).reshape(-1, 4)
    all_anchors = jnp.asarray(all_anchors)
    A = all_anchors.shape[0]

    pre_n = min(pre_n, A)
    post_n = min(post_n, pre_n)

    def one_sample(cp, bp, info):
        scores = cp[K:].reshape(K, H, W).transpose(1, 2, 0).reshape(-1)
        deltas = bp.reshape(K, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = all_anchors[:, 2] - all_anchors[:, 0] + 1.0
        ah = all_anchors[:, 3] - all_anchors[:, 1] + 1.0
        acx = all_anchors[:, 0] + 0.5 * (aw - 1)
        acy = all_anchors[:, 1] + 0.5 * (ah - 1)
        cx = deltas[:, 0] * aw + acx
        cy = deltas[:, 1] * ah + acy
        w = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * aw
        h = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - 0.5 * (w - 1), cy - 0.5 * (h - 1),
                           cx + 0.5 * (w - 1), cy + 0.5 * (h - 1)], axis=1)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=1)
        top_scores, top_idx = jax.lax.top_k(scores, pre_n)
        top_boxes = boxes[top_idx]
        keep = _nms_suppress(jnp, top_boxes, top_scores, nms_thresh, pre_n)
        ranked = jnp.argsort(-jnp.where(keep, top_scores, -jnp.inf))
        sel = ranked[:post_n]
        return top_boxes[sel]

    rois = jax.vmap(one_sample)(cls_prob, bbox_pred, im_info)  # (B,post,4)
    bidx = jnp.repeat(jnp.arange(B, dtype=cls_prob.dtype), post_n)
    out = jnp.concatenate([bidx[:, None], rois.reshape(-1, 4)], axis=1)
    return [out]
