"""Matrix/shape-manipulation, indexing and ordering operators.

Covers src/operator/tensor/matrix_op-inl.h (1,735 LoC: transpose/reshape/
slice/dot/batch_dot/clip/repeat/tile/reverse), indexing_op.h (Embedding/take/
one_hot — the reference's backward-via-Thrust-sort becomes XLA scatter-add),
ordering_op-inl.h (topk/sort/argsort) and control_flow_op.h (where).
dot/batch_dot map straight onto the MXU via lax.dot_general.
"""
from __future__ import annotations

import numpy as onp

from ..registry import register, f32_precision


def _jnp():
    import jax.numpy as jnp
    return jnp


@register("dot", arg_names=("lhs", "rhs"),
          attr_types={"transpose_a": bool, "transpose_b": bool})
def _dot(attrs, ins, octx):
    """Matrix product (MXU path). Mirrors tensor/matrix_op dot incl. the
    1-D/2-D mixed semantics."""
    jnp = _jnp()
    a, b = ins
    if attrs.get("transpose_a", False):
        a = a.T
    if attrs.get("transpose_b", False):
        b = b.T
    return [jnp.dot(a, b, precision=f32_precision(a))]


@register("batch_dot", arg_names=("lhs", "rhs"),
          attr_types={"transpose_a": bool, "transpose_b": bool})
def _batch_dot(attrs, ins, octx):
    jnp = _jnp()
    a, b = ins
    if attrs.get("transpose_a", False):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("transpose_b", False):
        b = jnp.swapaxes(b, -1, -2)
    return [jnp.matmul(a, b, precision=f32_precision(a))]


@register("linalg_gemm2", arg_names=("A", "B"),
          attr_types={"transpose_a": bool, "transpose_b": bool, "alpha": float})
def _linalg_gemm2(attrs, ins, octx):
    jnp = _jnp()
    a, b = ins
    if attrs.get("transpose_a", False):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("transpose_b", False):
        b = jnp.swapaxes(b, -1, -2)
    return [float(attrs.get("alpha", 1.0))
            * jnp.matmul(a, b, precision=f32_precision(a))]


@register("transpose", attr_types={"axes": tuple})
def _transpose(attrs, ins, octx):
    jnp = _jnp()
    axes = attrs.get("axes", ())
    if not axes:
        axes = None
    return [jnp.transpose(ins[0], axes)]


@register("SwapAxis", attr_types={"dim1": int, "dim2": int},
          alias=("swapaxes",))
def _swapaxes(attrs, ins, octx):
    jnp = _jnp()
    return [jnp.swapaxes(ins[0], int(attrs.get("dim1", 0)),
                         int(attrs.get("dim2", 0)))]


@register("expand_dims", attr_types={"axis": int})
def _expand_dims(attrs, ins, octx):
    return [_jnp().expand_dims(ins[0], int(attrs["axis"]))]


def _infer_reshape_shape(target, src_shape):
    """MXNet Reshape special codes: 0 copy dim, -1 infer, -2 copy rest,
    -3 merge two dims, -4 split (matrix_op-inl.h ReshapeParam)."""
    src = list(src_shape)
    out = []
    i = 0  # index into src
    j = 0
    target = list(target)
    while j < len(target):
        t = target[j]
        if t == 0:
            out.append(src[i]); i += 1
        elif t == -1:
            out.append(-1); i += 1
        elif t == -2:
            out.extend(src[i:]); i = len(src)
        elif t == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif t == -4:
            d1, d2 = target[j + 1], target[j + 2]
            cur = src[i]
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(t)
            if i < len(src):
                i += 1
        j += 1
    if -1 in out:
        total = 1
        for s in src_shape:
            total *= s
        known = 1
        for s in out:
            if s != -1:
                known *= s
        out[out.index(-1)] = total // known
    return tuple(out)


@register("Reshape", attr_types={"shape": tuple, "reverse": bool},
          alias=("reshape",))
def _reshape(attrs, ins, octx):
    tgt = _infer_reshape_shape(attrs["shape"], ins[0].shape)
    return [ins[0].reshape(tgt)]


@register("Flatten", alias=("flatten",))
def _flatten(attrs, ins, octx):
    x = ins[0]
    return [x.reshape((x.shape[0], -1))]


@register("slice", attr_types={"begin": tuple, "end": tuple},
          alias=("crop",))
def _slice(attrs, ins, octx):
    x = ins[0]
    begin = attrs["begin"]
    end = attrs["end"]
    if isinstance(begin, int):
        begin = (begin,)
    if isinstance(end, int):
        end = (end,)
    idx = []
    for i in range(x.ndim):
        if i < len(begin):
            b = begin[i] if begin[i] is not None else 0
            e = end[i] if end[i] is not None else x.shape[i]
            idx.append(slice(b, e))
        else:
            idx.append(slice(None))
    return [x[tuple(idx)]]


@register("slice_axis", attr_types={"axis": int, "begin": int, "end": int})
def _slice_axis(attrs, ins, octx):
    x = ins[0]
    ax = int(attrs["axis"]) % x.ndim
    b = attrs.get("begin", 0) or 0
    e = attrs.get("end", None)
    if e is None:
        e = x.shape[ax]
    idx = [slice(None)] * x.ndim
    idx[ax] = slice(b, e)
    return [x[tuple(idx)]]


@register("reverse", attr_types={"axis": tuple}, alias=("flip",))
def _reverse(attrs, ins, octx):
    jnp = _jnp()
    axis = attrs.get("axis", 0)
    if isinstance(axis, int):
        axis = (axis,)
    return [jnp.flip(ins[0], axis=axis)]


@register("repeat", attr_types={"repeats": int, "axis": int})
def _repeat(attrs, ins, octx):
    jnp = _jnp()
    axis = attrs.get("axis", None)
    if axis is None:
        return [jnp.repeat(ins[0].reshape(-1), int(attrs["repeats"]))]
    return [jnp.repeat(ins[0], int(attrs["repeats"]), axis=int(axis))]


@register("tile", attr_types={"reps": tuple})
def _tile(attrs, ins, octx):
    return [_jnp().tile(ins[0], attrs["reps"])]


def _concat_infer(attrs, in_shapes, aux):
    dim = int(attrs.get("dim", 1))
    known = [s for s in in_shapes if s is not None]
    if not known:
        return in_shapes, None, aux
    total = 0
    for s in in_shapes:
        if s is None:
            return in_shapes, None, aux
        total += s[dim]
    out = list(known[0])
    out[dim] = total
    return in_shapes, [tuple(out)], aux


@register("Concat", variable_args="num_args", attr_types={"dim": int},
          infer_shape=_concat_infer, alias=("concat",))
def _concat(attrs, ins, octx):
    return [_jnp().concatenate(ins, axis=int(attrs.get("dim", 1)))]


def _slice_channel_outputs(attrs):
    return int(attrs.get("num_outputs", 1))


@register("SliceChannel", num_outputs=_slice_channel_outputs,
          attr_types={"num_outputs": int, "axis": int, "squeeze_axis": bool},
          alias=("split",))
def _slice_channel(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    n = int(attrs["num_outputs"])
    axis = int(attrs.get("axis", 1))
    parts = jnp.split(x, n, axis=axis)
    if attrs.get("squeeze_axis", False):
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return parts


@register("where", arg_names=("condition", "x", "y"))
def _where(attrs, ins, octx):
    jnp = _jnp()
    cond, x, y = ins
    if cond.ndim == 1 and x.ndim > 1:
        cond = cond.reshape((-1,) + (1,) * (x.ndim - 1))
    return [jnp.where(cond != 0, x, y)]


# ---------------------------------------------------------------------------
# indexing (src/operator/tensor/indexing_op.h)
# ---------------------------------------------------------------------------
def _embedding_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    in_shapes[1] = (int(attrs["input_dim"]), int(attrs["output_dim"]))
    if data is None:
        return in_shapes, None, aux
    return in_shapes, [tuple(data) + (int(attrs["output_dim"]),)], aux


@register("Embedding", arg_names=("data", "weight"),
          attr_types={"input_dim": int, "output_dim": int},
          required_attrs=("input_dim", "output_dim"),
          infer_shape=_embedding_infer)
def _embedding(attrs, ins, octx):
    """Embedding lookup — gather from the weight table; backward is XLA
    scatter-add (the reference sorts indices with Thrust, indexing_op.h)."""
    data, weight = ins
    return [weight[data.astype("int32")]]


@register("take", arg_names=("a", "indices"), attr_types={"axis": int,
                                                          "mode": str})
def _take(attrs, ins, octx):
    jnp = _jnp()
    a, idx = ins
    axis = int(attrs.get("axis", 0))
    mode = attrs.get("mode", "clip")
    idx = idx.astype("int32")
    if mode == "clip":
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    elif mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    return [jnp.take(a, idx, axis=axis)]


@register("batch_take", arg_names=("a", "indices"))
def _batch_take(attrs, ins, octx):
    jnp = _jnp()
    a, idx = ins
    return [a[jnp.arange(a.shape[0]), idx.astype("int32")]]


@register("one_hot", attr_types={"depth": int, "on_value": float,
                                 "off_value": float, "dtype": str})
def _one_hot(attrs, ins, octx):
    jnp = _jnp()
    idx = ins[0].astype("int32")
    depth = int(attrs["depth"])
    on = float(attrs.get("on_value", 1.0))
    off = float(attrs.get("off_value", 0.0))
    dt = onp.dtype(attrs.get("dtype", "float32"))
    oh = (idx[..., None] == jnp.arange(depth)).astype(dt)
    return [oh * onp.asarray(on - off, dt) + onp.asarray(off, dt)]


@register("gather_nd", arg_names=("data", "indices"))
def _gather_nd(attrs, ins, octx):
    data, indices = ins
    idx = tuple(indices.astype("int32"))
    return [data[idx]]


# ---------------------------------------------------------------------------
# ordering (src/operator/tensor/ordering_op-inl.h)
# ---------------------------------------------------------------------------
@register("topk", attr_types={"axis": int, "k": int, "ret_typ": str,
                              "is_ascend": bool},
          num_outputs=lambda attrs: 2 if attrs.get("ret_typ") == "both" else 1)
def _topk(attrs, ins, octx):
    import jax
    jnp = _jnp()
    x = ins[0]
    axis = attrs.get("axis", -1)
    axis = x.ndim - 1 if axis is None else int(axis) % x.ndim
    k = int(attrs.get("k", 1))
    ret = attrs.get("ret_typ", "indices")
    asc = bool(attrs.get("is_ascend", False))
    xm = jnp.moveaxis(x, axis, -1)
    vals, idxs = jax.lax.top_k(-xm if asc else xm, k)
    if asc:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idxs = jnp.moveaxis(idxs, -1, axis).astype(x.dtype)
    if ret == "value":
        return [vals]
    if ret == "both":
        return [vals, idxs]
    if ret == "mask":
        mask = jnp.zeros(xm.shape, x.dtype)
        mask = mask.at[..., :].set(0)
        onehot = jnp.sum(
            (jnp.arange(xm.shape[-1])[None, :] ==
             idxs.astype("int32").reshape((-1, k))[..., None]).astype(x.dtype),
            axis=-2).reshape(xm.shape)
        return [jnp.moveaxis(onehot, -1, axis)]
    return [idxs]


@register("sort", attr_types={"axis": int, "is_ascend": bool})
def _sort(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    axis = attrs.get("axis", -1)
    axis = x.ndim - 1 if axis is None else int(axis)
    r = jnp.sort(x, axis=axis)
    if not attrs.get("is_ascend", True):
        r = jnp.flip(r, axis=axis)
    return [r]


@register("argsort", attr_types={"axis": int, "is_ascend": bool})
def _argsort(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    axis = attrs.get("axis", -1)
    axis = x.ndim - 1 if axis is None else int(axis)
    r = jnp.argsort(x, axis=axis)
    if not attrs.get("is_ascend", True):
        r = jnp.flip(r, axis=axis)
    return [r.astype(x.dtype)]


# ---------------------------------------------------------------------------
# sequence ops (src/operator/sequence_{last,mask,reverse}-inl.h); layout TNC
# ---------------------------------------------------------------------------
def _seq_args(attrs):
    # sequence_length is an argument only when use_sequence_length=True
    # (reference ListArguments, sequence_op_common.h)
    if attrs.get("use_sequence_length", False):
        return ("data", "sequence_length")
    return ("data",)


@register("SequenceLast", arg_names=_seq_args,
          attr_types={"use_sequence_length": bool})
def _sequence_last(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    if not attrs.get("use_sequence_length", False) or len(ins) < 2:
        return [x[-1]]
    seq_len = ins[1].astype("int32")
    idx = jnp.maximum(seq_len - 1, 0)
    return [x[idx, jnp.arange(x.shape[1])]]


@register("SequenceMask", arg_names=_seq_args,
          attr_types={"use_sequence_length": bool, "value": float})
def _sequence_mask(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    if not attrs.get("use_sequence_length", False) or len(ins) < 2:
        return [x]
    seq_len = ins[1].astype("int32")
    val = float(attrs.get("value", 0.0))
    t = jnp.arange(x.shape[0])[:, None]
    mask = t < seq_len[None, :]
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    return [jnp.where(mask, x, onp.asarray(val, x.dtype))]


@register("SequenceReverse", arg_names=_seq_args,
          attr_types={"use_sequence_length": bool})
def _sequence_reverse(attrs, ins, octx):
    jnp = _jnp()
    x = ins[0]
    if not attrs.get("use_sequence_length", False) or len(ins) < 2:
        return [jnp.flip(x, axis=0)]
    seq_len = ins[1].astype("int32")
    T = x.shape[0]
    t = jnp.arange(T)[:, None]
    src = jnp.where(t < seq_len[None, :], seq_len[None, :] - 1 - t, t)
    return [x[src, jnp.arange(x.shape[1])[None, :]]]


def _assign_region(x, attrs):
    """Normalize SliceParam begin/end into per-dim slices."""
    begin = attrs.get("begin", ())
    end = attrs.get("end", ())
    if isinstance(begin, int):
        begin = (begin,)
    if isinstance(end, int):
        end = (end,)
    idx = []
    for i in range(x.ndim):
        if i < len(begin):
            b = begin[i] if begin[i] is not None else 0
            e = end[i] if end[i] is not None else x.shape[i]
            idx.append(slice(b, e))
        else:
            idx.append(slice(None))
    return tuple(idx)


def _slice_assign_infer(attrs, in_shapes, aux):
    lhs = in_shapes[0]
    if lhs is None:
        return in_shapes, None, aux
    return in_shapes, [tuple(lhs)], aux


@register("_slice_assign", arg_names=("lhs", "rhs"),
          attr_types={"begin": tuple, "end": tuple},
          infer_shape=_slice_assign_infer, alias=("_crop_assign",))
def _slice_assign_op(attrs, ins, octx):
    """Functional out-of-place form of the reference's in-place
    _slice_assign (src/operator/tensor/matrix_op.cc:258): output = lhs with
    region [begin:end) replaced by rhs. The NDArray sliced-set path
    (x[a:b] = y) routes here; XLA lowers it to dynamic-update-slice."""
    lhs, rhs = ins
    return [lhs.at[_assign_region(lhs, attrs)].set(rhs)]


@register("_crop_assign_scalar",
          attr_types={"begin": tuple, "end": tuple, "scalar": float},
          infer_shape=_slice_assign_infer)
def _crop_assign_scalar_op(attrs, ins, octx):
    """Scalar variant (src/operator/tensor/matrix_op.cc:283)."""
    x = ins[0]
    return [x.at[_assign_region(x, attrs)].set(float(attrs.get("scalar", 0.0)))]
