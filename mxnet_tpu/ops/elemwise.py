"""Elementwise unary/binary/scalar operators.

Covers the reference's macro-registered elementwise families
(src/operator/tensor/elemwise_binary_op.cc, elemwise_binary_scalar_op.cc,
elemwise_unary_op.cc; scalar functors src/operator/mshadow_op.h). Each op is
one jnp expression — XLA fuses chains of these into single kernels, replacing
mshadow expression templates.
"""
from __future__ import annotations

import numpy as onp

from ..registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _unary(name, fn, alias=()):
    @register(name, alias=alias)
    def _f(attrs, ins, octx, _fn=fn):
        return [_fn(_jnp(), ins[0])]
    _f.__doc__ = "Elementwise %s." % name
    return _f


_UNARY_TABLE = {
    "abs": lambda jnp, x: jnp.abs(x),
    "sign": lambda jnp, x: jnp.sign(x),
    "round": lambda jnp, x: jnp.round(x),
    "rint": lambda jnp, x: jnp.rint(x),
    "ceil": lambda jnp, x: jnp.ceil(x),
    "floor": lambda jnp, x: jnp.floor(x),
    "fix": lambda jnp, x: jnp.trunc(x),
    "square": lambda jnp, x: jnp.square(x),
    "sqrt": lambda jnp, x: jnp.sqrt(x),
    "rsqrt": lambda jnp, x: 1.0 / jnp.sqrt(x),
    "exp": lambda jnp, x: jnp.exp(x),
    "log": lambda jnp, x: jnp.log(x),
    "log10": lambda jnp, x: jnp.log10(x),
    "log2": lambda jnp, x: jnp.log2(x),
    "log1p": lambda jnp, x: jnp.log1p(x),
    "expm1": lambda jnp, x: jnp.expm1(x),
    "sin": lambda jnp, x: jnp.sin(x),
    "cos": lambda jnp, x: jnp.cos(x),
    "tan": lambda jnp, x: jnp.tan(x),
    "arcsin": lambda jnp, x: jnp.arcsin(x),
    "arccos": lambda jnp, x: jnp.arccos(x),
    "arctan": lambda jnp, x: jnp.arctan(x),
    "sinh": lambda jnp, x: jnp.sinh(x),
    "cosh": lambda jnp, x: jnp.cosh(x),
    "tanh": lambda jnp, x: jnp.tanh(x),
    "arcsinh": lambda jnp, x: jnp.arcsinh(x),
    "arccosh": lambda jnp, x: jnp.arccosh(x),
    "arctanh": lambda jnp, x: jnp.arctanh(x),
    "sigmoid": lambda jnp, x: 1.0 / (1.0 + jnp.exp(-x)),
    "relu": lambda jnp, x: jnp.maximum(x, 0),
    "softsign": lambda jnp, x: x / (1.0 + jnp.abs(x)),
    "reciprocal": lambda jnp, x: 1.0 / x,
    "negative": lambda jnp, x: -x,
    "gamma": lambda jnp, x: _gamma(jnp, x),
    "gammaln": lambda jnp, x: _gammaln(jnp, x),
    "erf": lambda jnp, x: _erf(jnp, x),
    "degrees": lambda jnp, x: jnp.degrees(x),
    "radians": lambda jnp, x: jnp.radians(x),
}


def _gammaln(jnp, x):
    from jax.scipy.special import gammaln
    return gammaln(x)


def _gamma(jnp, x):
    from jax.scipy.special import gammaln
    return jnp.exp(gammaln(x))


def _erf(jnp, x):
    from jax.scipy.special import erf
    return erf(x)


for _name, _fn in _UNARY_TABLE.items():
    _unary(_name, _fn)

_unary("identity", lambda jnp, x: x, alias=("_copy",))


@register("BlockGrad", alias=("stop_gradient",))
def _block_grad(attrs, ins, octx):
    """Identity forward, zero gradient (src/operator/tensor/elemwise_unary_op.cc
    BlockGrad) — exactly lax.stop_gradient."""
    import jax
    return [jax.lax.stop_gradient(ins[0])]


@register("Cast", alias=("cast",), attr_types={"dtype": str})
def _cast(attrs, ins, octx):
    """Cast to a new dtype (src/operator/tensor/elemwise_unary_op.cc Cast)."""
    return [ins[0].astype(onp.dtype(attrs["dtype"]))]


@register("clip", attr_types={"a_min": float, "a_max": float})
def _clip(attrs, ins, octx):
    """Clip values to [a_min, a_max] (src/operator/tensor/matrix_op.cc clip)."""
    return [_jnp().clip(ins[0], attrs["a_min"], attrs["a_max"])]


@register("smooth_l1", attr_types={"scalar": float})
def _smooth_l1(attrs, ins, octx):
    jnp = _jnp()
    sigma2 = float(attrs.get("scalar", 1.0)) ** 2
    x = ins[0]
    return [jnp.where(jnp.abs(x) < 1.0 / sigma2,
                      0.5 * sigma2 * x * x, jnp.abs(x) - 0.5 / sigma2)]


# -- binary elementwise -----------------------------------------------------
def _binary(name, fn, alias=()):
    @register(name, arg_names=("lhs", "rhs"), alias=alias)
    def _f(attrs, ins, octx, _fn=fn):
        return [_fn(_jnp(), ins[0], ins[1])]
    return _f


_BINARY_TABLE = {
    "_plus": (lambda jnp, a, b: a + b,
              # _grad_add: the reference's gradient-accumulation add
              # (elemwise_binary_op.cc) — same math, kept for parity
              ("elemwise_add", "_add", "_grad_add")),
    "_minus": (lambda jnp, a, b: a - b, ("elemwise_sub", "_sub")),
    "_mul": (lambda jnp, a, b: a * b, ("elemwise_mul",)),
    "_div": (lambda jnp, a, b: a / b, ("elemwise_div",)),
    "_mod": (lambda jnp, a, b: jnp.mod(a, b), ()),
    "_power": (lambda jnp, a, b: jnp.power(a, b), ("pow",)),
    "_maximum": (lambda jnp, a, b: jnp.maximum(a, b), ()),
    "_minimum": (lambda jnp, a, b: jnp.minimum(a, b), ()),
    "_hypot": (lambda jnp, a, b: jnp.hypot(a, b), ()),
    "_equal": (lambda jnp, a, b: (a == b).astype(a.dtype), ()),
    "_not_equal": (lambda jnp, a, b: (a != b).astype(a.dtype), ()),
    "_greater": (lambda jnp, a, b: (a > b).astype(a.dtype), ()),
    "_greater_equal": (lambda jnp, a, b: (a >= b).astype(a.dtype), ()),
    "_lesser": (lambda jnp, a, b: (a < b).astype(a.dtype), ()),
    "_lesser_equal": (lambda jnp, a, b: (a <= b).astype(a.dtype), ()),
}

for _name, (_fn, _alias) in _BINARY_TABLE.items():
    _binary(_name, _fn, _alias)


# -- binary with scalar -----------------------------------------------------
def _scalar_op(name, fn, alias=()):
    @register(name, attr_types={"scalar": float}, alias=alias)
    def _f(attrs, ins, octx, _fn=fn):
        s = float(attrs.get("scalar", 0.0))
        return [_fn(_jnp(), ins[0], s)]
    return _f


_SCALAR_TABLE = {
    "_plus_scalar": lambda jnp, a, s: a + onp.asarray(s, a.dtype),
    "_minus_scalar": lambda jnp, a, s: a - onp.asarray(s, a.dtype),
    "_rminus_scalar": lambda jnp, a, s: onp.asarray(s, a.dtype) - a,
    "_mul_scalar": lambda jnp, a, s: a * onp.asarray(s, a.dtype),
    "_div_scalar": lambda jnp, a, s: a / onp.asarray(s, a.dtype),
    "_rdiv_scalar": lambda jnp, a, s: onp.asarray(s, a.dtype) / a,
    "_mod_scalar": lambda jnp, a, s: jnp.mod(a, onp.asarray(s, a.dtype)),
    "_rmod_scalar": lambda jnp, a, s: jnp.mod(onp.asarray(s, a.dtype), a),
    "_power_scalar": lambda jnp, a, s: jnp.power(a, onp.asarray(s, a.dtype)),
    "_rpower_scalar": lambda jnp, a, s: jnp.power(onp.asarray(s, a.dtype), a),
    "_maximum_scalar": lambda jnp, a, s: jnp.maximum(a, onp.asarray(s, a.dtype)),
    "_minimum_scalar": lambda jnp, a, s: jnp.minimum(a, onp.asarray(s, a.dtype)),
    "_hypot_scalar": lambda jnp, a, s: jnp.hypot(a, onp.asarray(s, a.dtype)),
    "_equal_scalar": lambda jnp, a, s: (a == s).astype(a.dtype),
    "_not_equal_scalar": lambda jnp, a, s: (a != s).astype(a.dtype),
    "_greater_scalar": lambda jnp, a, s: (a > s).astype(a.dtype),
    "_greater_equal_scalar": lambda jnp, a, s: (a >= s).astype(a.dtype),
    "_lesser_scalar": lambda jnp, a, s: (a < s).astype(a.dtype),
    "_lesser_equal_scalar": lambda jnp, a, s: (a <= s).astype(a.dtype),
}

for _name, _fn in _SCALAR_TABLE.items():
    _scalar_op(_name, _fn)


@register("add_n", variable_args="num_args", alias=("ElementWiseSum", "_sum"))
def _add_n(attrs, ins, octx):
    """Sum of N arrays in one fused op (src/ndarray/ndarray.cc:290
    ElementwiseSum; NNVM op add_n)."""
    out = ins[0]
    for x in ins[1:]:
        out = out + x
    return [out]


@register("_identity_with_attr_like_rhs", arg_names=("lhs", "rhs"))
def _identity_like_rhs(attrs, ins, octx):
    """Pass lhs through, shape/attrs taken from rhs — the grad-aggregation
    helper (src/operator/tensor/elemwise_unary_op.cc)."""
    return [ins[0]]


@register("_NoGradient", arg_names=())
def _no_gradient(attrs, ins, octx):
    """Placeholder node meaning "no gradient flows here" (nnvm graph
    construction). Materializes as a scalar zero; the executor's grad
    aggregation treats it as absent."""
    jnp = _jnp()
    return [jnp.zeros((1,), jnp.float32)]


@register("_CrossDeviceCopy")
def _cross_device_copy(attrs, ins, octx):
    """Device-boundary copy inserted by PlaceDevice in model-parallel graphs
    (src/operator/cross_device_copy.cc). Under XLA/GSPMD, device placement is
    expressed by shardings, so inside a jitted graph this is the identity;
    the imperative NDArray.copyto path does the real device_put."""
    return [ins[0]]


@register("choose_element_0index", arg_names=("lhs", "rhs"))
def _choose_element_0index(attrs, ins, octx):
    """out[i] = lhs[i, rhs[i]] (src/ndarray/ndarray.cc:765
    MatChooseRowElem)."""
    jnp = _jnp()
    lhs, rhs = ins
    idx = jnp.clip(rhs.astype("int32"), 0, lhs.shape[1] - 1)
    return [jnp.take_along_axis(lhs, idx[:, None], axis=1)[:, 0]]


@register("fill_element_0index", arg_names=("lhs", "mhs", "rhs"))
def _fill_element_0index(attrs, ins, octx):
    """lhs with lhs[i, rhs[i]] = mhs[i] (src/ndarray/ndarray.cc:771
    MatFillRowElem)."""
    jnp = _jnp()
    lhs, mhs, rhs = ins
    idx = jnp.clip(rhs.astype("int32"), 0, lhs.shape[1] - 1)
    rows = jnp.arange(lhs.shape[0])
    return [lhs.at[rows, idx].set(mhs)]


@register("_onehot_encode", arg_names=("indices", "out_like"))
def _onehot_encode_op(attrs, ins, octx):
    """One-hot rows sized like the second input (src/ndarray/ndarray.cc:765
    OneHotEncode BinaryOp)."""
    jnp = _jnp()
    idx, out_like = ins
    depth = out_like.shape[1]
    return [(idx.astype("int32")[:, None] == jnp.arange(depth)[None, :])
            .astype(out_like.dtype)]
