"""Base types and errors for mxnet_tpu.

TPU-native re-design of the reference's ctypes base layer
(``python/mxnet/base.py``). There is no C ABI boundary here: the "backend"
is JAX/XLA, so this module only carries the error type, version, and small
shared helpers.
"""
from __future__ import annotations

__all__ = ["MXNetError", "string_types", "numeric_types", "mx_uint", "mx_float",
           "__version__"]

# Reference is MXNet 0.9.5 (include/mxnet/base.h:87-93); we version the
# TPU-native rebuild as 0.9.5+tpu.
__version__ = "0.9.5+tpu.1"


class MXNetError(Exception):
    """Error raised by mxnet_tpu (mirrors mxnet.base.MXNetError)."""


string_types = (str,)
numeric_types = (float, int)

# ctypes-era aliases kept so user code doing ``from mxnet.base import mx_uint``
# keeps importing; they are plain python ints here.
mx_uint = int
mx_float = float


def check_call(ret):
    """No-op compatibility shim (there is no C call to check)."""
    return ret


def c_array(ctype, values):  # pragma: no cover - compat shim
    return list(values)
