"""Test oracles (python/mxnet/test_utils.py:905).

Same contracts as the reference: numpy is the ground truth
(check_numeric_gradient finite differences :360, check_symbolic_forward/
backward :473/:526), and check_consistency (:676) runs one symbol across a
context list cross-checking outputs/grads — the reference's primary
device-correctness oracle (cpu vs accelerator), reused here for cpu-vs-tpu.
"""
from __future__ import annotations

import time

import numpy as onp

from . import context as ctx_mod
from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError

__all__ = ["default_context", "assert_almost_equal", "same", "rand_ndarray",
           "random_arrays", "check_numeric_gradient",
           "check_symbolic_forward", "check_symbolic_backward",
           "check_consistency", "check_speed", "simple_forward",
           "numeric_grad", "reldiff"]

_default_ctx = None


def default_context():
    """The context tests run on (test_utils.py:27)."""
    global _default_ctx
    if _default_ctx is None:
        return ctx_mod.current_context()
    return _default_ctx


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def same(a, b):
    return onp.array_equal(a, b)


def reldiff(a, b):
    diff = onp.sum(onp.abs(a - b))
    norm = onp.sum(onp.abs(a)) + onp.sum(onp.abs(b))
    if diff == 0:
        return 0
    return diff / norm


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-8, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, nd.NDArray) else onp.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else onp.asarray(b)
    onp.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                err_msg="%s and %s differ" % names)


def random_arrays(*shapes):
    arrays = [onp.random.randn(*s).astype(onp.float32) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def rand_ndarray(shape, ctx=None, dtype=onp.float32):
    return nd.array(onp.random.uniform(-1.0, 1.0, shape), ctx=ctx,
                    dtype=dtype)


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Bind, forward, return numpy outputs (test_utils.simple_forward)."""
    ctx = ctx or default_context()
    inputs = {k: nd.array(v) if not isinstance(v, nd.NDArray) else v
              for k, v in inputs.items()}
    ex = sym.simple_bind(ctx, grad_req="null",
                         **{k: v.shape for k, v in inputs.items()})
    for k, v in inputs.items():
        v.copyto(ex.arg_dict[k])
    ex.forward(is_train=is_train)
    outputs = [x.asnumpy() for x in ex.outputs]
    if len(outputs) == 1:
        return outputs[0]
    return outputs


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Finite-difference gradients of executor outputs summed
    (test_utils.numeric_grad)."""
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    approx_grads = {k: onp.zeros(v.shape, dtype=onp.float32)
                    for k, v in location.items()}

    executor.forward(is_train=use_forward_train)
    f_x = sum(out.asnumpy().sum() for out in executor.outputs)

    for k in location:
        old_value = location[k].copy()
        flat = old_value.reshape(-1)
        grad_flat = approx_grads[k].reshape(-1)
        for i in range(flat.size):
            flat[i] += eps
            executor.arg_dict[k][:] = old_value.reshape(location[k].shape)
            executor.forward(is_train=use_forward_train)
            f_eps = sum(out.asnumpy().sum() for out in executor.outputs)
            grad_flat[i] = (f_eps - f_x) / eps
            flat[i] -= eps
        executor.arg_dict[k][:] = old_value
    return approx_grads


def check_numeric_gradient(sym, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, use_forward_train=True, ctx=None):
    """Compare executor backward with finite differences
    (test_utils.py:360)."""
    ctx = ctx or default_context()
    location = {k: onp.asarray(v, dtype=onp.float32)
                for k, v in location.items()}
    if grad_nodes is None:
        grad_nodes = list(location.keys())

    input_shapes = {k: v.shape for k, v in location.items()}
    arg_shapes, _, aux_shapes = sym.infer_shape(**input_shapes)
    arg_names = sym.list_arguments()

    args = {}
    args_grad = {}
    for name, shape in zip(arg_names, arg_shapes):
        args[name] = nd.array(
            location.get(name, onp.random.randn(*shape)), ctx=ctx)
        if name in grad_nodes:
            args_grad[name] = nd.zeros(shape, ctx=ctx)
    aux = [nd.zeros(s, ctx=ctx) for s in aux_shapes]
    if aux_states:
        for name, val in aux_states.items():
            idx = sym.list_auxiliary_states().index(name)
            aux[idx][:] = val

    executor = sym.bind(ctx, args, args_grad=args_grad, grad_req="write",
                        aux_states=aux)
    executor.forward(is_train=True)
    executor.backward()
    symbolic_grads = {k: executor.grad_dict[k].asnumpy()
                      for k in grad_nodes}

    check_loc = {k: args[k].asnumpy() for k in grad_nodes}
    numeric_gradients = numeric_grad(executor, check_loc, eps=numeric_eps,
                                     use_forward_train=use_forward_train)
    for name in grad_nodes:
        fd_grad = numeric_gradients[name]
        sym_grad = symbolic_grads[name]
        rel = reldiff(fd_grad, sym_grad)
        assert rel <= rtol, \
            "numeric check failed for %s: relative diff %g > %g\nfd=%s\n" \
            "sym=%s" % (name, rel, rtol, fd_grad, sym_grad)


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=1e-8,
                           aux_states=None, ctx=None):
    """Compare forward outputs against expected numpy (test_utils.py:473)."""
    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    args = {k: nd.array(v, ctx=ctx) for k, v in location.items()}
    arg_shapes, _, aux_shapes = sym.infer_shape(
        **{k: v.shape for k, v in location.items()})
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        if name not in args:
            args[name] = nd.zeros(shape, ctx=ctx)
    aux = [nd.zeros(s, ctx=ctx) for s in aux_shapes]
    if aux_states is not None:
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(sym.list_auxiliary_states(), aux_states))
        for name, val in aux_states.items():
            idx = sym.list_auxiliary_states().index(name)
            aux[idx][:] = val
    executor = sym.bind(ctx, args, aux_states=aux, grad_req="null")
    executor.forward(is_train=False)
    for out, exp in zip(executor.outputs, expected):
        assert_almost_equal(out.asnumpy(), exp, rtol=rtol, atol=atol)
    return [o.asnumpy() for o in executor.outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=1e-8, aux_states=None, grad_req="write",
                            ctx=None):
    """Compare backward grads against expected numpy (test_utils.py:526)."""
    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    args = {k: nd.array(v, ctx=ctx) for k, v in location.items()}
    args_grad = {k: nd.zeros(v.shape, ctx=ctx)
                 for k, v in location.items()}
    arg_shapes, _, aux_shapes = sym.infer_shape(
        **{k: v.shape for k, v in location.items()})
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        if name not in args:
            args[name] = nd.zeros(shape, ctx=ctx)
            args_grad[name] = nd.zeros(shape, ctx=ctx)
    aux = [nd.zeros(s, ctx=ctx) for s in aux_shapes]
    executor = sym.bind(ctx, args, args_grad=args_grad, grad_req=grad_req,
                        aux_states=aux)
    executor.forward(is_train=True)
    if out_grads is not None:
        out_grads = [nd.array(v, ctx=ctx) if not isinstance(v, nd.NDArray)
                     else v for v in out_grads]
    executor.backward(out_grads)
    for name, exp in expected.items():
        assert_almost_equal(executor.grad_dict[name].asnumpy(), exp,
                            rtol=rtol, atol=atol, names=("grad " + name,
                                                         "expected"))
    return {k: v.asnumpy() if v is not None else None
            for k, v in executor.grad_dict.items()}


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True):
    """Run one symbol across a context/dtype list and cross-check outputs
    and gradients — the reference's device-correctness oracle
    (test_utils.py:676)."""
    if tol is None:
        tol = {onp.dtype(onp.float16): 1e-1, onp.dtype(onp.float32): 1e-3,
               onp.dtype(onp.float64): 1e-5}
    assert len(ctx_list) > 1

    executors = []
    for spec in ctx_list:
        spec = dict(spec)
        ctx = spec.pop("ctx")
        type_dict = spec.pop("type_dict", {})
        exe = sym.simple_bind(ctx, grad_req=grad_req, type_dict=type_dict,
                              **spec)
        executors.append(exe)

    # shared random init across executors
    exe0 = executors[0]
    inits = {}
    for name, arr in exe0.arg_dict.items():
        if arg_params and name in arg_params:
            inits[name] = onp.asarray(arg_params[name])
        else:
            inits[name] = onp.random.normal(
                size=arr.shape, scale=scale).astype(onp.float32)
    aux_inits = {}
    for name, arr in exe0.aux_dict.items():
        if aux_params and name in aux_params:
            aux_inits[name] = onp.asarray(aux_params[name])
        else:
            aux_inits[name] = onp.zeros(arr.shape, dtype=onp.float32)

    for exe in executors:
        for name, val in inits.items():
            exe.arg_dict[name][:] = val.astype(exe.arg_dict[name].dtype)
        for name, val in aux_inits.items():
            exe.aux_dict[name][:] = val.astype(exe.aux_dict[name].dtype)
        exe.forward(is_train=(grad_req != "null"))
        if grad_req != "null":
            exe.backward()

    dtypes = [onp.dtype(exe.outputs[0].dtype) for exe in executors]
    max_idx = onp.argmax([onp.finfo(d).precision if d.kind == "f" else 0
                          for d in dtypes])
    gt_exe = executors[max_idx]
    for i, exe in enumerate(executors):
        if i == max_idx:
            continue
        rtol = tol[dtypes[i]]
        for o_gt, o in zip(gt_exe.outputs, exe.outputs):
            try:
                assert_almost_equal(o.asnumpy().astype(onp.float64),
                                    o_gt.asnumpy().astype(onp.float64),
                                    rtol=rtol, atol=rtol)
            except AssertionError:
                if raise_on_err:
                    raise
        if grad_req != "null":
            for name in exe.grad_dict:
                g = exe.grad_dict[name]
                g_gt = gt_exe.grad_dict[name]
                if g is None or g_gt is None:
                    continue
                try:
                    assert_almost_equal(g.asnumpy().astype(onp.float64),
                                        g_gt.asnumpy().astype(onp.float64),
                                        rtol=rtol, atol=rtol)
                except AssertionError:
                    if raise_on_err:
                        raise
    return [exe.outputs for exe in executors]


def check_speed(sym, location=None, ctx=None, N=20, grad_req="write",
                typ="whole", **kwargs):
    """Time forward(+backward) throughput (test_utils.py:602)."""
    ctx = ctx or default_context()
    if location is None:
        arg_shapes, _, _ = sym.infer_shape(**kwargs)
        location = {name: onp.random.normal(size=shape, scale=1.0)
                    for name, shape in zip(sym.list_arguments(), arg_shapes)}
    else:
        kwargs = {k: v.shape for k, v in location.items()}
    exe = sym.simple_bind(ctx, grad_req=grad_req, **kwargs)
    for name, value in location.items():
        exe.arg_dict[name][:] = value

    if typ == "whole":
        # warm up (compile)
        exe.forward(is_train=True)
        exe.backward()
        for o in exe.outputs:
            o.wait_to_read()
        tic = time.time()
        for _ in range(N):
            exe.forward(is_train=True)
            exe.backward()
        nd.waitall()
        for o in exe.outputs:
            o.wait_to_read()
        toc = time.time()
        return (toc - tic) / N
    elif typ == "forward":
        exe.forward(is_train=False)
        for o in exe.outputs:
            o.wait_to_read()
        tic = time.time()
        for _ in range(N):
            exe.forward(is_train=False)
            for o in exe.outputs:
                o.wait_to_read()
        toc = time.time()
        return (toc - tic) / N
    else:
        raise ValueError("typ can only be whole or forward")
