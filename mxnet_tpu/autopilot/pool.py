"""ReplicaPool — the serving-autoscale actuator.

Holds N live replicas (``Predictor`` or ``DecodeEngine`` — anything
the caller's ``factory()`` builds) and moves N toward whatever target
the autopilot decides, inside ``[min_replicas, max_replicas]``. Every
spin-up warms the fresh replica through the persistent executable
cache (``warmup(cache_dir=...)``), so a scale-out under an SLO breach
serves with ZERO XLA compiles and bitwise-identical rows — the PR 11
warm-start contract is what makes autoscaling safe to automate.
Scale-in releases the newest replica (drain first for engines that
queue).

The spin-up path carries the ``autopilot.scale`` fault seam
(kind=error): a chaos plan can make a spin-up fail exactly when the
controller needs it, and the pool must stay at its previous size with
the failure counted (``autopilot.scale_errors``) — never half-built.
"""
from __future__ import annotations

import logging
import threading
import time

from .. import faults as _faults

__all__ = ["ReplicaPool"]


class ReplicaPool(object):
    """A bounded pool of warm serving replicas.

    Parameters
    ----------
    factory : callable
        ``factory() -> replica`` building ONE fresh replica (its own
        Predictor/DecodeEngine — replicas never share stats scopes).
    min_replicas / max_replicas : int
        The pool's hard bounds; ``scale_to`` clamps into them.
    cache_dir : str, optional
        Persistent executable-cache root handed to each spin-up's
        ``warmup(cache_dir=...)``; None warms without the cache (every
        spin-up then compiles — the cold baseline the bench measures).
    warm : bool
        Warm each new replica before it joins (default). ``False``
        skips warmup for factories that warm internally.
    start : bool
        Spin up to ``min_replicas`` at construction (default).
    """

    def __init__(self, factory, min_replicas=1, max_replicas=2,
                 cache_dir=None, warm=True, start=True, logger=None):
        if min_replicas < 0 or max_replicas < min_replicas:
            raise ValueError(
                "need 0 <= min_replicas <= max_replicas (got %d..%d)"
                % (min_replicas, max_replicas))
        self._factory = factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self._cache_dir = cache_dir
        self._warm = bool(warm)
        self._replicas = []
        self._rr = 0
        self._lock = threading.RLock()
        self._logger = logger or logging.getLogger(
            "mxnet_tpu.autopilot")
        from .. import telemetry
        scope = telemetry.registry().scope("autopilot")
        self._g_replicas = scope.gauge("replicas")
        self._c_out = scope.counter("scale_outs")
        self._c_in = scope.counter("scale_ins")
        self._c_err = scope.counter("scale_errors")
        self.spinup_reports = []
        if start:
            self.scale_to(self.min_replicas)

    # ------------------------------------------------------------------
    @property
    def size(self):
        with self._lock:
            return len(self._replicas)

    @property
    def replicas(self):
        """The live replicas, oldest first (a copy)."""
        with self._lock:
            return list(self._replicas)

    # ------------------------------------------------------------------
    def scale_to(self, n):
        """Move the pool to ``n`` replicas (clamped into the bounds).
        Spin-ups warm through the executable cache; a spin-up failure
        (including a fired ``autopilot.scale`` fault) leaves the pool
        at its current size, counts into ``autopilot.scale_errors``,
        and re-raises — the controller's tick records the miss and the
        cooldown paces the retry. Returns the resulting size."""
        n = max(self.min_replicas, min(self.max_replicas, int(n)))
        with self._lock:
            while len(self._replicas) < n:
                try:
                    self._spin_up()
                except BaseException:
                    self._c_err.add()
                    raise
            while len(self._replicas) > n:
                self._spin_down()
            self._g_replicas.set(len(self._replicas))
            return len(self._replicas)

    def _spin_up(self):
        from .. import telemetry
        if _faults.armed():
            # spin-up seam (kind=error): the deterministic stand-in
            # for a replica that fails to come up (OOM, dead host) —
            # the pool must absorb it without going half-built
            _faults.check("autopilot.scale",
                          replicas=len(self._replicas))
        t0 = time.perf_counter()
        rep = self._factory()
        report = None
        if self._warm and hasattr(rep, "warmup"):
            try:
                rep.warmup(cache_dir=self._cache_dir)
            except BaseException:
                self._release(rep)
                raise
            if hasattr(rep, "warmup_report"):
                report = rep.warmup_report()
        ms = (time.perf_counter() - t0) * 1000.0
        self._replicas.append(rep)
        self._c_out.add()
        sources = sorted({r.get("source") for r in (report or {}).values()})
        self.spinup_reports.append(
            {"spinup_ms": round(ms, 3), "sources": sources,
             "replicas": len(self._replicas)})
        telemetry.flight_recorder().note(
            "autopilot_replica_up", replicas=len(self._replicas),
            spinup_ms=round(ms, 3), sources=sources)
        self._logger.info(
            "autopilot: replica %d up in %.1f ms (warm sources: %s)",
            len(self._replicas), ms, sources or "n/a")

    def _spin_down(self):
        from .. import telemetry
        rep = self._replicas.pop()
        self._release(rep)
        self._c_in.add()
        telemetry.flight_recorder().note(
            "autopilot_replica_down", replicas=len(self._replicas))
        self._logger.info("autopilot: replica released (%d remain)",
                          len(self._replicas))

    @staticmethod
    def _release(rep):
        if hasattr(rep, "shutdown"):
            try:
                rep.shutdown(drain=True)
            except TypeError:
                rep.shutdown()
        if hasattr(rep, "release"):
            rep.release()

    # ------------------------------------------------------------------
    def predict(self, data, **kwargs):
        """Round-robin one request over the live replicas (the pool's
        minimal load-balancer; production traffic normally fronts each
        replica with its own :class:`~mxnet_tpu.serving
        .DynamicBatcher`)."""
        with self._lock:
            if not self._replicas:
                raise RuntimeError("replica pool is empty")
            rep = self._replicas[self._rr % len(self._replicas)]
            self._rr += 1
        return rep.predict(data, **kwargs)

    def close(self):
        """Release every replica (idempotent)."""
        with self._lock:
            while self._replicas:
                self._spin_down()
            self._g_replicas.set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
