"""ReplicaPool — the serving-autoscale actuator.

Holds N live replicas (``Predictor`` or ``DecodeEngine`` — anything
the caller's ``factory()`` builds) and moves N toward whatever target
the autopilot decides, inside ``[min_replicas, max_replicas]``. Every
spin-up warms the fresh replica through the persistent executable
cache (``warmup(cache_dir=...)``), so a scale-out under an SLO breach
serves with ZERO XLA compiles and bitwise-identical rows — the PR 11
warm-start contract is what makes autoscaling safe to automate.
Scale-in releases the newest replica (drain first for engines that
queue).

Every dispatch runs under a :meth:`lease`: the replica is picked and
its in-flight count bumped atomically, and scale-in *waits for the
count to reach zero* before releasing the replica — a request can
never land on (or still be running inside) a closed replica, no
matter how ``scale_to`` oscillates underneath the traffic. Leases
also expose per-replica outstanding counts and stable serial numbers,
which is what the gateway router keys least-outstanding routing and
decode session affinity on.

The spin-up path carries the ``autopilot.scale`` fault seam
(kind=error): a chaos plan can make a spin-up fail exactly when the
controller needs it, and the pool must stay at its previous size with
the failure counted (``autopilot.scale_errors``) — never half-built.
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time

from .. import faults as _faults

__all__ = ["ReplicaPool"]


class ReplicaPool(object):
    """A bounded pool of warm serving replicas.

    Parameters
    ----------
    factory : callable
        ``factory() -> replica`` building ONE fresh replica (its own
        Predictor/DecodeEngine — replicas never share stats scopes).
    min_replicas / max_replicas : int
        The pool's hard bounds; ``scale_to`` clamps into them.
    cache_dir : str, optional
        Persistent executable-cache root handed to each spin-up's
        ``warmup(cache_dir=...)``; None warms without the cache (every
        spin-up then compiles — the cold baseline the bench measures).
    warm : bool
        Warm each new replica before it joins (default). ``False``
        skips warmup for factories that warm internally.
    start : bool
        Spin up to ``min_replicas`` at construction (default).
    drain_timeout_s : float
        Longest a scale-in will wait for a retiring replica's leased
        requests to finish before releasing it anyway (with a
        warning). Leases normally last one request, so the bound only
        bites on a wedged replica.
    """

    def __init__(self, factory, min_replicas=1, max_replicas=2,
                 cache_dir=None, warm=True, start=True, logger=None,
                 drain_timeout_s=30.0):
        if min_replicas < 0 or max_replicas < min_replicas:
            raise ValueError(
                "need 0 <= min_replicas <= max_replicas (got %d..%d)"
                % (min_replicas, max_replicas))
        self._factory = factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self._cache_dir = cache_dir
        self._warm = bool(warm)
        self._drain_timeout_s = float(drain_timeout_s)
        self._replicas = []
        self._rr = 0
        self._inflight = {}    # id(rep) -> outstanding leased requests
        self._serials = {}     # id(rep) -> stable spin-up serial
        self._next_serial = 0
        self._lock = threading.RLock()
        self._drain_cond = threading.Condition(self._lock)
        self._logger = logger or logging.getLogger(
            "mxnet_tpu.autopilot")
        from .. import telemetry
        scope = telemetry.registry().scope("autopilot")
        self._g_replicas = scope.gauge("replicas")
        self._c_out = scope.counter("scale_outs")
        self._c_in = scope.counter("scale_ins")
        self._c_err = scope.counter("scale_errors")
        self.spinup_reports = []
        if start:
            self.scale_to(self.min_replicas)

    # ------------------------------------------------------------------
    @property
    def size(self):
        with self._lock:
            return len(self._replicas)

    @property
    def replicas(self):
        """The live replicas, oldest first (a copy)."""
        with self._lock:
            return list(self._replicas)

    # ------------------------------------------------------------------
    def scale_to(self, n):
        """Move the pool to ``n`` replicas (clamped into the bounds).
        Spin-ups warm through the executable cache; a spin-up failure
        (including a fired ``autopilot.scale`` fault) leaves the pool
        at its current size, counts into ``autopilot.scale_errors``,
        and re-raises — the controller's tick records the miss and the
        cooldown paces the retry. Returns the resulting size."""
        n = max(self.min_replicas, min(self.max_replicas, int(n)))
        victims = []
        with self._lock:
            while len(self._replicas) < n:
                try:
                    self._spin_up()
                except BaseException:
                    self._c_err.add()
                    raise
            while len(self._replicas) > n:
                # pop under the lock so no NEW lease can pick the
                # victim; drain + release happen outside so in-flight
                # requests (and other leases) keep making progress
                victims.append(self._replicas.pop())
            self._g_replicas.set(len(self._replicas))
            size = len(self._replicas)
        for rep in victims:
            self._retire(rep)
        return size

    def _spin_up(self):
        from .. import telemetry
        if _faults.armed():
            # spin-up seam (kind=error): the deterministic stand-in
            # for a replica that fails to come up (OOM, dead host) —
            # the pool must absorb it without going half-built
            _faults.check("autopilot.scale",
                          replicas=len(self._replicas))
        t0 = time.perf_counter()
        rep = self._factory()
        report = None
        if self._warm and hasattr(rep, "warmup"):
            try:
                rep.warmup(cache_dir=self._cache_dir)
            except BaseException:
                self._release(rep)
                raise
            if hasattr(rep, "warmup_report"):
                report = rep.warmup_report()
        ms = (time.perf_counter() - t0) * 1000.0
        self._replicas.append(rep)
        self._serials[id(rep)] = self._next_serial
        self._next_serial += 1
        self._c_out.add()
        sources = sorted({r.get("source") for r in (report or {}).values()})
        self.spinup_reports.append(
            {"spinup_ms": round(ms, 3), "sources": sources,
             "replicas": len(self._replicas)})
        telemetry.flight_recorder().note(
            "autopilot_replica_up", replicas=len(self._replicas),
            spinup_ms=round(ms, 3), sources=sources)
        self._logger.info(
            "autopilot: replica %d up in %.1f ms (warm sources: %s)",
            len(self._replicas), ms, sources or "n/a")

    def _retire(self, rep):
        """Drain a popped replica's leased requests, then release it.

        Called with the replica already removed from ``_replicas`` (so
        no new lease can reach it) and WITHOUT the pool lock held —
        waiting happens on ``_drain_cond`` so lease holders finishing
        their requests wake us."""
        from .. import telemetry
        deadline = time.monotonic() + self._drain_timeout_s
        with self._lock:
            while self._inflight.get(id(rep), 0) > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    self._logger.warning(
                        "autopilot: replica drain timed out with %d "
                        "request(s) still leased; releasing anyway",
                        self._inflight.get(id(rep), 0))
                    break
                self._drain_cond.wait(min(left, 0.5))
            self._inflight.pop(id(rep), None)
            self._serials.pop(id(rep), None)
        self._release(rep)
        self._c_in.add()
        telemetry.flight_recorder().note(
            "autopilot_replica_down", replicas=len(self._replicas))
        self._logger.info("autopilot: replica released (%d remain)",
                          len(self._replicas))

    @staticmethod
    def _release(rep):
        if hasattr(rep, "shutdown"):
            try:
                rep.shutdown(drain=True)
            except TypeError:
                rep.shutdown()
        if hasattr(rep, "release"):
            rep.release()

    # ------------------------------------------------------------------
    @contextlib.contextmanager
    def lease(self, pick=None):
        """Check a replica out for one request.

        Picks a live replica (round-robin by default), bumps its
        in-flight count, yields it, and decrements on the way out —
        waking any scale-in waiting to drain it. ``pick`` overrides
        the choice: it receives a snapshot ``[(replica, outstanding,
        serial), ...]`` (oldest replica first) and returns the chosen
        replica — the hook the gateway router uses for
        least-outstanding predict routing and serial-keyed decode
        affinity."""
        with self._lock:
            if not self._replicas:
                raise RuntimeError("replica pool is empty")
            if pick is not None:
                snap = [(r, self._inflight.get(id(r), 0),
                         self._serials.get(id(r), -1))
                        for r in self._replicas]
                rep = pick(snap)
                if rep is None or id(rep) not in self._serials:
                    raise RuntimeError(
                        "lease pick returned a non-live replica")
            else:
                rep = self._replicas[self._rr % len(self._replicas)]
                self._rr += 1
            key = id(rep)
            self._inflight[key] = self._inflight.get(key, 0) + 1
        try:
            yield rep
        finally:
            with self._lock:
                n = self._inflight.get(key, 1) - 1
                if n > 0:
                    self._inflight[key] = n
                else:
                    self._inflight.pop(key, None)
                self._drain_cond.notify_all()

    def outstanding(self, rep=None):
        """Leased-request count for one replica (or the pool total)."""
        with self._lock:
            if rep is not None:
                return self._inflight.get(id(rep), 0)
            return sum(self._inflight.values())

    def serial(self, rep):
        """The replica's stable spin-up serial (-1 if not live)."""
        with self._lock:
            return self._serials.get(id(rep), -1)

    def predict(self, data, **kwargs):
        """Round-robin one request over the live replicas (the pool's
        minimal load-balancer; production traffic normally fronts each
        replica with its own :class:`~mxnet_tpu.serving
        .DynamicBatcher`). Runs under a :meth:`lease`, so a concurrent
        scale-in waits for this request instead of closing the replica
        underneath it."""
        with self.lease() as rep:
            return rep.predict(data, **kwargs)

    def close(self):
        """Release every replica (idempotent)."""
        victims = []
        with self._lock:
            while self._replicas:
                victims.append(self._replicas.pop())
            self._g_replicas.set(0)
        for rep in victims:
            self._retire(rep)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
