"""PeerCheckpointStore — peer-replicated in-memory checkpoints.

Disk is the durability story (the manager's atomic commits); this
store is the GOODPUT story: every elastic commit also leaves a host-
memory copy, row-sharded over the job's hosts by the same
``shard_rows`` rule the data plane uses, with each host additionally
retaining its RIGHT neighbor's block (replication factor 2, ring
layout: block ``b`` lives on hosts ``b`` and ``(b+1) % n``). When a
dp-shrink kills hosts, the survivors can reassemble the full global
arrays from memory — no disk re-read on the resume path — as long as
no block lost BOTH its holders (i.e. no two ring-adjacent hosts died
together). Arrays whose leading dim does not split evenly (biases,
scalars, optimizer bytes, RNG state, manifest extra) are replicated on
every host.

The assembled :class:`~mxnet_tpu.checkpoint.manager.Checkpoint` is
bitwise-equal to ``manager.restore()`` of the same step: both paths
snapshot the same device buffers to host (``serialize.snapshot`` →
``assemble``), and the npy round-trip the disk path adds is exact.
``ElasticTrainer(peer_store=...)`` captures behind its existing commit
callback and consults :meth:`resume_checkpoint` on recovery — peer
memory is used only when it holds exactly the step disk would restore
(:func:`~mxnet_tpu.autopilot.kernel.decide_resume`).

CI runs single-process, so "hosts" here are dicts and ``drop_hosts``
simulates the memory loss a real death causes; the sharding/placement
math is identical either way.
"""
from __future__ import annotations

import logging
import threading
import time

__all__ = ["PeerCheckpointStore"]


class PeerCheckpointStore(object):
    """In-memory ring-replicated checkpoint snapshots.

    Parameters
    ----------
    n_hosts : int
        The job's host count — the ring the blocks replicate over.
        Fixed at construction (captures shard over the ORIGINAL ring;
        a shrink only removes holders).
    keep : int
        Snapshots retained (default 2); older steps are evicted on
        capture.
    """

    def __init__(self, n_hosts, keep=2, logger=None):
        if int(n_hosts) < 1:
            raise ValueError("n_hosts must be >= 1")
        self.n_hosts = int(n_hosts)
        self.keep = max(1, int(keep))
        self._hosts = [dict() for _ in range(self.n_hosts)]
        self._steps = []
        self._dead = set()
        self._lock = threading.Lock()
        self._logger = logger or logging.getLogger(
            "mxnet_tpu.autopilot")
        self.transcript = []   # resume decisions, replayable
        from .. import telemetry
        scope = telemetry.registry().scope("autopilot")
        self._c_captures = scope.counter("peer_captures")
        self._c_restores = scope.counter("peer_restores")
        self._c_restore_ms = scope.counter("peer_restore_ms")

    # ----------------------------------------------------------- write
    def _split(self, arr):
        """True when ``arr`` row-shards evenly over the ring."""
        n = self.n_hosts
        return (n > 1 and getattr(arr, "ndim", 0) >= 1
                and arr.shape[0] >= n and arr.shape[0] % n == 0)

    def capture(self, step, arrays, optimizer_state=None, extra=None,
                rng_state="auto"):
        """Snapshot one committed step into host memory. ``arrays``
        maps name -> NDArray / jax.Array / numpy (the same values the
        manager's ``save`` snapshots — call right after the disk
        commit so both paths freeze identical buffers)."""
        from .. import random as _random
        from ..checkpoint import serialize
        from ..dist.sharded_iter import shard_rows
        step = int(step)
        if rng_state == "auto":
            rng_state = _random.get_state()
        n = self.n_hosts
        assembled = {}
        for name, value in arrays.items():
            shards = serialize.snapshot(value)
            full = next((a for idx, a in shards if idx is None), None)
            if full is not None:
                arr = full
            else:
                gshape = [max(idx[d][1] for idx, _ in shards)
                          for d in range(len(shards[0][0]))]
                arr = serialize.assemble(gshape,
                                         str(shards[0][1].dtype),
                                         shards)
            assembled[str(name)] = arr
        with self._lock:
            names = {}
            for name, arr in assembled.items():
                if self._split(arr):
                    names[name] = n
                    for b in range(n):
                        block = shard_rows(arr, b, n)
                        for holder in (b, (b + 1) % n):
                            self._hosts[holder][(step, name, b)] = block
                else:
                    names[name] = None
                    for holder in range(n):
                        self._hosts[holder][(step, name, None)] = arr
            meta = {"names": names,
                    "optimizer": bytes(optimizer_state)
                    if optimizer_state is not None else None,
                    "rng": rng_state,
                    "extra": dict(extra or {})}
            for holder in range(n):
                self._hosts[holder][(step, "__meta__", None)] = meta
            if step in self._steps:
                self._steps.remove(step)
            self._steps.append(step)
            while len(self._steps) > self.keep:
                self._evict(self._steps.pop(0))
        self._c_captures.add()
        return step

    def _evict(self, step):
        for host in self._hosts:
            for key in [k for k in host if k[0] == step]:
                del host[key]

    # ---------------------------------------------------------- deaths
    def drop_hosts(self, hosts):
        """A host death loses its memory: clear the named hosts'
        retained blocks (identity-known deaths — heartbeat-only counts
        cannot name a memory to drop and fall back to disk)."""
        with self._lock:
            for h in hosts:
                h = int(h)
                if 0 <= h < self.n_hosts:
                    self._hosts[h].clear()
                    self._dead.add(h)
        return sorted(self._dead)

    def _holder(self, step, name, block):
        """A surviving host holding the block, or None."""
        if block is None:
            candidates = range(self.n_hosts)
        else:
            candidates = (block, (block + 1) % self.n_hosts)
        for h in candidates:
            if h not in self._dead and \
                    (step, name, block) in self._hosts[h]:
                return h
        return None

    def restorable(self, step):
        """Whether every block of ``step`` still has a surviving
        holder."""
        meta_host = self._holder(step, "__meta__", None)
        if meta_host is None:
            return False
        meta = self._hosts[meta_host][(step, "__meta__", None)]
        for name, nblocks in meta["names"].items():
            blocks = [None] if nblocks is None else range(nblocks)
            for b in blocks:
                if self._holder(step, name, b) is None:
                    return False
        return True

    def latest(self):
        """Newest captured step still assemblable from the survivors,
        or None."""
        with self._lock:
            for step in reversed(self._steps):
                if self.restorable(step):
                    return step
        return None

    # --------------------------------------------------------- restore
    def restore(self, step=None):
        """Assemble a :class:`~mxnet_tpu.checkpoint.manager
        .Checkpoint` from the surviving hosts' memory (default: the
        newest restorable step). Raises ``KeyError`` when no step is
        restorable."""
        import numpy as onp

        from ..checkpoint.manager import Checkpoint
        t0 = time.perf_counter()
        with self._lock:
            if step is None:
                step = next((s for s in reversed(self._steps)
                             if self.restorable(s)), None)
            if step is None or not self.restorable(step):
                raise KeyError(
                    "no peer-restorable checkpoint (steps %r, dead "
                    "hosts %r)" % (self._steps, sorted(self._dead)))
            step = int(step)
            meta_host = self._holder(step, "__meta__", None)
            meta = self._hosts[meta_host][(step, "__meta__", None)]
            params = {}
            for name, nblocks in meta["names"].items():
                if nblocks is None:
                    h = self._holder(step, name, None)
                    params[name] = self._hosts[h][(step, name, None)]
                else:
                    blocks = []
                    for b in range(nblocks):
                        h = self._holder(step, name, b)
                        blocks.append(self._hosts[h][(step, name, b)])
                    params[name] = onp.concatenate(blocks, axis=0)
        self._c_restores.add()
        self._c_restore_ms.add((time.perf_counter() - t0) * 1000.0)
        return Checkpoint(step=step, params=params,
                          optimizer_state=meta["optimizer"],
                          extra=dict(meta["extra"]), rng=meta["rng"])

    def resume_checkpoint(self, disk_step):
        """The elastic-resume hook: the peer Checkpoint when memory
        holds exactly ``disk_step`` (the manager's newest committed
        step), else None (resume from disk). The decision is the pure
        :func:`~mxnet_tpu.autopilot.kernel.decide_resume` and is
        recorded — with its observation — into ``self.transcript``
        and the flight recorder."""
        from .. import telemetry
        from .kernel import AutopilotConfig, decide_resume
        peer_step = self.latest()
        obs = {"disk_step": disk_step, "peer_step": peer_step,
               "peer_restorable": peer_step is not None}
        decision = decide_resume(AutopilotConfig(), obs)
        self.transcript.append({"plane": "resume", "obs": obs,
                                "decision": decision})
        telemetry.flight_recorder().note(
            "autopilot_resume_decision", **dict(obs, **decision))
        if decision["action"] != "peer_restore":
            self._logger.info(
                "autopilot: elastic resume from DISK (%s)",
                decision["reason"])
            return None
        ckpt = self.restore(peer_step)
        self._logger.warning(
            "autopilot: elastic resume from PEER MEMORY at step %d "
            "(no disk re-read)", ckpt.step)
        return ckpt

    # ------------------------------------------------------------ misc
    def stats(self):
        """Occupancy snapshot: steps retained, dead hosts, resident
        bytes per host."""
        with self._lock:
            return {
                "steps": list(self._steps),
                "dead_hosts": sorted(self._dead),
                "bytes_per_host": [
                    sum(getattr(v, "nbytes", 0) for k, v in host.items()
                        if k[1] != "__meta__")
                    for host in self._hosts],
            }
