"""mxnet_tpu.autopilot — the fleet controller closing the
telemetry→action loop.

Every sensor this repo grew (``slo.*`` burn-rate gauges, checkpoint
manifests with ``params_digest``, restart transcripts) and every
actuator (multi-tenant serving admission, the persistent executable
cache, elastic restarts) existed — with a human between them. The
autopilot is the deterministic poll loop that removes the human, in
three planes:

* **serving autoscale** — :class:`ReplicaPool` spins replicas up/down
  against an :class:`~mxnet_tpu.telemetry.SLOTracker`'s burn state
  with hysteresis: a BOTH-window breach scales out, sustained idle
  scales in, bounded by min/max replicas and a cooldown; every
  spin-up warms through the persistent executable cache (zero XLA
  compiles, bitwise rows);
* **continuous delivery** — :class:`CanaryController` admits each new
  committed checkpoint generation as a low-priority canary tenant,
  promotes after a clean soak, rolls back on SLO burn or a failing
  accuracy probe; a poisoned generation never takes protected traffic;
* **training goodput** — :class:`PeerCheckpointStore` keeps ring-
  replicated in-memory copies of every elastic commit so a dp-shrink
  resume restores from host memory instead of disk
  (``ElasticTrainer(peer_store=...)``).

Every decision is a pure function of (config, polled snapshot, seed)
in :mod:`~mxnet_tpu.autopilot.kernel`; the controller only assembles
observations and actuates. Each tick appends ``{tick, plane, obs,
decision}`` to ``Autopilot.transcript`` and :meth:`Autopilot.replay`
re-derives every decision — a divergence is a bug (pinned by the
``dryrun_autopilot`` gate). Observability rides ``autopilot.*``
gauges/counters and FlightRecorder events; the controller's own
misbehavior is chaos-testable through the ``autopilot.poll`` and
``autopilot.scale`` fault seams (unarmed = bitwise no-op).

The subsystem is opt-in end to end: nothing constructs these classes
unless you do, and the background loop (:meth:`Autopilot.start`) only
runs under ``MXNET_AUTOPILOT=1`` — an autopilot-off process is bitwise
identical to one where the subsystem doesn't exist.

Quick start (docs/api/autopilot.md has the full sensor→decision→
actuator table)::

    from mxnet_tpu import autopilot

    pool = autopilot.ReplicaPool(make_predictor, min_replicas=1,
                                 max_replicas=3, cache_dir=cache)
    ap = autopilot.Autopilot(
        config=autopilot.AutopilotConfig(cooldown_ticks=2),
        slo=tracker, pool=pool)
    ap.step()          # one deterministic tick (tests drive this)
    ap.start()         # ... or the MXNET_AUTOPILOT=1 background loop
    assert ap.replay() == []   # transcript re-derives bitwise
"""
from __future__ import annotations

import logging
import os
import threading

from .. import faults as _faults
from .canary import CanaryController, finite_probe
from .kernel import (AutopilotConfig, decide_canary, decide_resume,
                     decide_scale, replay)
from .peer import PeerCheckpointStore
from .pool import ReplicaPool

__all__ = ["Autopilot", "AutopilotConfig", "ReplicaPool",
           "CanaryController", "PeerCheckpointStore", "finite_probe",
           "decide_scale", "decide_canary", "decide_resume", "replay",
           "enabled"]


def enabled():
    """Whether the background autopilot loop may run
    (``MXNET_AUTOPILOT``, default off). Explicit ``step()`` calls are
    always honored — the flag gates the autonomous thread, so an
    autopilot-off process never acts on its own."""
    return os.environ.get("MXNET_AUTOPILOT", "0") != "0"


class Autopilot(object):
    """The poll-driven controller: one ``step()`` polls every
    configured plane, runs the pure decision kernel, actuates, and
    appends to the replayable transcript.

    Parameters
    ----------
    config : AutopilotConfig, optional
        The policy (default :meth:`AutopilotConfig.from_env`).
    slo : SLOTracker, optional
        The serving objectives driving autoscale (with ``pool``).
    pool : ReplicaPool, optional
        The autoscale actuator.
    canary : CanaryController, optional
        The continuous-delivery plane.
    peer : PeerCheckpointStore, optional
        Held for introspection (``ElasticTrainer`` consults the store
        directly on its recovery path).
    """

    def __init__(self, config=None, slo=None, pool=None, canary=None,
                 peer=None, logger=None):
        self.config = config or AutopilotConfig.from_env()
        self.slo = slo
        self.pool = pool
        self.canary = canary
        self.peer = peer
        self.transcript = []
        self._tick = 0
        self._idle_ticks = 0
        self._cooldown_until = 0
        self._stop = threading.Event()
        self._thread = None
        self._logger = logger or logging.getLogger(
            "mxnet_tpu.autopilot")
        from .. import telemetry
        scope = telemetry.registry().scope("autopilot")
        self._g_ticks = scope.gauge("ticks")
        self._c_poll_err = scope.counter("poll_errors")
        self._c_canary_err = scope.counter("canary_errors")

    # ------------------------------------------------------------ tick
    def step(self, now=None):
        """One deterministic controller tick: poll, decide, actuate.
        Returns the tick's transcript entries. A fired
        ``autopilot.poll`` fault (delay sleeps; error skips) exercises
        a controller that itself misbehaves — a skipped poll is a
        counted, transcribed non-event, never a crash."""
        from .. import telemetry
        tick = self._tick
        self._tick += 1
        self._g_ticks.set(self._tick)
        if _faults.armed():
            try:
                _faults.check("autopilot.poll", tick=tick)
            except _faults.FaultError as exc:
                self._c_poll_err.add()
                entry = {"tick": tick, "plane": "poll",
                         "error": str(exc)}
                self.transcript.append(entry)
                telemetry.flight_recorder().note(
                    "autopilot_poll_error", tick=tick, error=str(exc))
                self._logger.warning(
                    "autopilot: poll failed at tick %d (%s) — tick "
                    "skipped", tick, exc)
                return [entry]
        out = []
        if self.pool is not None and self.slo is not None:
            out.append(self._step_scale(tick, now))
        if self.canary is not None:
            out.append(self._step_canary(tick, now))
        return out

    def _step_scale(self, tick, now):
        from .. import telemetry
        burn = self.slo.burn_state(now=now)
        idle = burn["n_fast"] == 0 and not burn["breach"]
        self._idle_ticks = self._idle_ticks + 1 if idle else 0
        obs = {"tick": tick, "replicas": self.pool.size,
               "breach": bool(burn["breach"]),
               "breach_epochs": int(burn["breach_epochs"]),
               "idle_ticks": self._idle_ticks,
               "cooldown_remaining":
                   max(0, self._cooldown_until - tick)}
        decision = decide_scale(self.config, obs)
        entry = {"tick": tick, "plane": "scale", "obs": obs,
                 "decision": decision}
        if decision["action"] in ("scale_out", "scale_in"):
            try:
                self.pool.scale_to(decision["target"])
            except Exception as exc:  # noqa: BLE001 — an actuator
                # failure (incl. the autopilot.scale seam) must not
                # kill the loop; the pool stays at its previous size
                # and the cooldown paces the retry
                entry["actuate_error"] = str(exc)
                telemetry.flight_recorder().note(
                    "autopilot_scale_error", tick=tick,
                    action=decision["action"], error=str(exc))
                self._logger.warning(
                    "autopilot: %s to %d failed (%s)",
                    decision["action"], decision["target"], exc)
            else:
                telemetry.flight_recorder().note(
                    "autopilot_scale", tick=tick,
                    action=decision["action"],
                    target=decision["target"],
                    reason=decision["reason"])
            self._cooldown_until = tick + 1 + self.config.cooldown_ticks
            self._idle_ticks = 0
        self.transcript.append(entry)
        return entry

    def _step_canary(self, tick, now):
        from .. import telemetry
        obs = self.canary.observe(tick=tick, now=now)
        decision = decide_canary(self.config, obs)
        entry = {"tick": tick, "plane": "canary", "obs": obs,
                 "decision": decision}
        if decision["action"] != "hold":
            try:
                self.canary.apply(decision, tick=tick)
            except Exception as exc:  # noqa: BLE001 — same discipline
                # as the scale actuator: record, count, keep looping
                self._c_canary_err.add()
                entry["actuate_error"] = str(exc)
                telemetry.flight_recorder().note(
                    "autopilot_canary_error", tick=tick,
                    action=decision["action"], error=str(exc))
                self._logger.warning(
                    "autopilot: canary %s failed (%s)",
                    decision["action"], exc)
        self.transcript.append(entry)
        return entry

    # ---------------------------------------------------------- replay
    def replay(self):
        """Re-derive every transcribed decision through the pure
        kernel; returns the divergences (empty == deterministic, the
        gate's witness)."""
        return replay(self.config, self.transcript)

    # ------------------------------------------------- background loop
    def start(self):
        """Start the background poll loop — ONLY under
        ``MXNET_AUTOPILOT=1`` (returns None and does nothing
        otherwise, so an autopilot-off process never self-actuates).
        Returns self when started."""
        if not enabled():
            self._logger.info(
                "autopilot: MXNET_AUTOPILOT is off — background loop "
                "not started (explicit step() still works)")
            return None
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="mxtpu-autopilot", daemon=True)
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.config.poll_interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the loop must survive
                self._logger.exception("autopilot tick failed")

    def stop(self):
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2 * self.config.poll_interval_s + 1)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
