"""The autopilot decision kernel — pure functions over polled snapshots.

Every fleet decision the autopilot takes is computed here, and ONLY
here, as a pure function ``decide_*(config, obs) -> decision`` over a
JSON-able observation dict the controller assembled from one poll of
the sensor plane (``slo.*`` burn state, pool size, checkpoint
generations, peer-replica inventory). No clocks, no randomness, no
I/O: the same (config, obs) always yields the same decision, which is
what makes a recorded transcript *replayable* — :func:`replay` re-runs
the kernel over every recorded observation and any divergence from the
recorded decision is a bug (the ``dryrun_autopilot`` gate and
tests/test_autopilot.py both pin this).

Decisions are plain dicts ``{"action", "reason", ...}`` so the
transcript serializes as-is into a chaos report.
"""
from __future__ import annotations

import collections
import math
import os

__all__ = ["AutopilotConfig", "decide_scale", "decide_canary",
           "decide_resume", "replay"]


class AutopilotConfig(collections.namedtuple("AutopilotConfig", (
        "min_replicas", "max_replicas", "cooldown_ticks", "idle_ticks",
        "canary_soak_ticks", "poll_interval_s", "seed"))):
    """The autopilot's whole policy, as one immutable record.

    ``min_replicas``/``max_replicas`` bound the serving pool;
    ``cooldown_ticks`` is the hysteresis gap after ANY scale action
    (no further scaling while it runs down); ``idle_ticks`` is how many
    consecutive zero-traffic polls scale-in waits for;
    ``canary_soak_ticks`` how many clean polls a canary must survive
    before promotion. ``poll_interval_s`` paces the background loop
    (and converts ``MXNET_AUTOPILOT_COOLDOWN_S`` into ticks); ``seed``
    rides into the transcript so a replay names the full decision
    input even though the current policies draw nothing from it.
    """
    __slots__ = ()

    @classmethod
    def from_env(cls, **overrides):
        """Build a config from the ``MXNET_AUTOPILOT_*`` knobs
        (docs/how_to/env_var.md), explicit ``overrides`` winning."""
        poll_s = float(overrides.pop("poll_interval_s", 1.0))
        cooldown_s = float(os.environ.get(
            "MXNET_AUTOPILOT_COOLDOWN_S", "30"))
        base = {
            "min_replicas": int(os.environ.get(
                "MXNET_AUTOPILOT_MIN_REPLICAS", "1")),
            "max_replicas": int(os.environ.get(
                "MXNET_AUTOPILOT_MAX_REPLICAS", "2")),
            "cooldown_ticks": max(
                1, int(math.ceil(cooldown_s / max(poll_s, 1e-9)))),
            "idle_ticks": 3,
            "canary_soak_ticks": 2,
            "poll_interval_s": poll_s,
            "seed": 0,
        }
        base.update(overrides)
        cfg = cls(**base)
        if cfg.min_replicas < 0 or cfg.max_replicas < cfg.min_replicas:
            raise ValueError(
                "need 0 <= min_replicas <= max_replicas (got %d..%d)"
                % (cfg.min_replicas, cfg.max_replicas))
        return cfg


AutopilotConfig.__new__.__defaults__ = (1, 2, 3, 3, 2, 1.0, 0)


def _hold(reason):
    return {"action": "hold", "reason": reason}


def decide_scale(cfg, obs):
    """One autoscale decision from one polled burn-rate snapshot.

    ``obs`` carries ``replicas`` (current pool size), ``breach`` (the
    tracker's BOTH-window burn verdict), ``breach_epochs`` (the
    monotonic counter, recorded for hysteresis audits), ``idle_ticks``
    (consecutive zero-traffic polls, maintained by the controller) and
    ``cooldown_remaining`` (ticks left of the post-action freeze).

    Policy: cooldown freezes everything (the hysteresis half of the
    contract — one breach epoch cannot flap the pool); a both-window
    breach scales OUT one replica up to ``max_replicas``; sustained
    idleness (``idle_ticks`` consecutive quiet polls, no breach)
    scales IN one replica down to ``min_replicas``; a pool below
    ``min_replicas`` is repaired first.
    """
    replicas = int(obs["replicas"])
    if int(obs.get("cooldown_remaining", 0)) > 0:
        return _hold("cooldown")
    if replicas < cfg.min_replicas:
        return {"action": "scale_out", "target": replicas + 1,
                "reason": "below_min"}
    if obs.get("breach"):
        if replicas >= cfg.max_replicas:
            return _hold("breach_at_max")
        return {"action": "scale_out", "target": replicas + 1,
                "reason": "slo_breach"}
    if int(obs.get("idle_ticks", 0)) >= cfg.idle_ticks \
            and replicas > cfg.min_replicas:
        return {"action": "scale_in", "target": replicas - 1,
                "reason": "sustained_idle"}
    return _hold("steady")


def decide_canary(cfg, obs):
    """One continuous-delivery decision from one generation snapshot.

    ``obs`` carries ``latest_step`` (newest committed checkpoint
    generation), ``stable_step`` (the generation protected traffic is
    served from), ``rejected`` (latest generation already rolled
    back once — never re-admitted), and — while a canary is live —
    ``canary_step``, ``probe_ok`` (the accuracy/parity probe's fresh
    verdict), ``canary_breach`` (the canary tenant's OWN SLO burn) and
    ``ticks_in_canary``.

    Policy: a new, never-rejected generation is ADMITTED as a canary;
    a live canary ROLLS BACK the moment its probe fails or its burn
    windows breach; only after ``canary_soak_ticks`` clean polls with
    a passing probe is it PROMOTED to the protected route. A poisoned
    generation therefore never reaches protected traffic: its only
    path there runs through ``probe_ok`` twice (admission and soak).
    """
    canary = obs.get("canary_step")
    if canary is None:
        latest = obs.get("latest_step")
        stable = obs.get("stable_step")
        if latest is not None and latest != stable \
                and (stable is None or latest > stable) \
                and not obs.get("rejected"):
            return {"action": "admit", "step": latest,
                    "reason": "new_generation"}
        return _hold("no_new_generation")
    if obs.get("probe_ok") is False:
        return {"action": "rollback", "step": canary,
                "reason": "probe_failed"}
    if obs.get("canary_breach"):
        return {"action": "rollback", "step": canary,
                "reason": "slo_breach"}
    if int(obs.get("ticks_in_canary", 0)) >= cfg.canary_soak_ticks \
            and obs.get("probe_ok"):
        return {"action": "promote", "step": canary,
                "reason": "soaked_clean"}
    return _hold("soaking")


def decide_resume(cfg, obs):
    """Where an elastic restart should restore from.

    ``obs`` carries ``disk_step`` (the checkpoint manager's newest
    committed step), ``peer_step`` (the newest step the peer-replicated
    in-memory store can still assemble from the SURVIVING hosts) and
    ``peer_restorable``. Peer memory wins only when it holds exactly
    the step disk would restore — a stale peer snapshot must never
    shadow a newer durable commit.
    """
    disk = obs.get("disk_step")
    peer = obs.get("peer_step")
    if obs.get("peer_restorable") and peer is not None \
            and peer == disk:
        return {"action": "peer_restore", "step": peer,
                "reason": "peer_current"}
    reason = "no_peer_snapshot" if peer is None else (
        "peer_stale" if obs.get("peer_restorable")
        else "peer_shards_lost")
    return {"action": "disk_restore", "step": disk, "reason": reason}


_DECIDERS = {"scale": decide_scale, "canary": decide_canary,
             "resume": decide_resume}


def replay(cfg, transcript):
    """Re-run the kernel over a recorded transcript; return the list
    of divergences (empty == fully replayable, the determinism
    witness). Entries without a decision plane (e.g. ``poll`` fault
    incidents) are skipped — they record sensor failures, not
    decisions."""
    mismatches = []
    for i, entry in enumerate(transcript):
        decider = _DECIDERS.get(entry.get("plane"))
        if decider is None or "decision" not in entry:
            continue
        again = decider(cfg, entry["obs"])
        if again != entry["decision"]:
            mismatches.append({"index": i, "plane": entry["plane"],
                               "recorded": entry["decision"],
                               "replayed": again})
    return mismatches
