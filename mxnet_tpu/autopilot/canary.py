"""CanaryController — continuous delivery over the tenancy plane.

Watches a :class:`~mxnet_tpu.checkpoint.CheckpointManager` for new
committed generations (``step_metadata()`` reads ``params_digest``
without loading arrays), admits each one as a LOW-priority, unprotected
canary tenant on the serving :class:`~mxnet_tpu.serving.DynamicBatcher`,
and promotes or rolls back from two sensors:

* the canary tenant's OWN ``slo.*`` burn windows (per-tenant SLO from
  the tenancy plane — protected/stable traffic never shares them);
* an accuracy/parity **probe** run out-of-band against the canary
  Predictor each poll (default: a fixed zero batch whose outputs must
  be finite — a NaN-poisoned generation fails it on the first tick).

The safety contract: a poisoned generation can only reach the
protected route through ``promote``, and ``promote`` requires a
passing probe after ``canary_soak_ticks`` clean polls — so a poisoned
canary is rolled back (and its step marked rejected, never re-admitted)
while the stable tenant keeps serving its own generation untouched.
The decision itself lives in :func:`mxnet_tpu.autopilot.kernel
.decide_canary`; this class is the sensor (``observe``) and the
actuator (``apply``).
"""
from __future__ import annotations

import logging

__all__ = ["CanaryController", "finite_probe"]


def finite_probe(inputs=None, batch=None):
    """Build the default accuracy probe: ``probe(predictor) -> bool``
    running one fixed batch (``inputs`` name->array, or zeros at the
    smallest bucket) and requiring every output element finite. The
    cheapest possible parity check — it catches the failure class the
    chaos plan injects (non-finite params) without a labeled set;
    pass your own callable for a real accuracy/parity gate."""
    import numpy as onp

    def probe(pred):
        feed = inputs
        if feed is None:
            b = batch or pred.buckets[0]
            feed = {name: onp.zeros((b,) + tuple(shape[1:]),
                                    onp.float32)
                    for name, shape in pred._data_descs}
        outs = pred.predict(feed)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        return all(bool(onp.isfinite(onp.asarray(o)).all())
                   for o in outs)
    return probe


class CanaryController(object):
    """Sensor + actuator for one stable/canary tenant pair.

    Parameters
    ----------
    manager : CheckpointManager or str
        The trainer's checkpoint directory — each newly committed step
        is a candidate generation.
    batcher : DynamicBatcher
        The serving plane; must host the ``stable_name`` tenant. The
        canary is admitted/removed via ``add_tenant``/``remove_tenant``
        and a promotion atomically swaps the stable route
        (``replace_tenant``).
    stable_step : int
        The generation the stable tenant currently serves (promotions
        advance it).
    data_shapes : list, optional
        ``Predictor.load`` shapes for admitted generations (required
        with the default ``predictor_factory``).
    predictor_factory : callable, optional
        ``factory(step) -> Predictor`` for a committed step; defaults
        to ``Predictor.load(manager, step, data_shapes=...)`` warmed
        through ``cache_dir``.
    probe : callable, optional
        ``probe(predictor) -> bool`` accuracy/parity gate (default
        :func:`finite_probe` at the smallest bucket).
    slo_factory : callable, optional
        ``slo_factory(name) -> SLOTracker`` building the canary
        tenant's own objectives; None admits the canary without a
        tracker (probe-only gating).
    cache_dir : str, optional
        Executable-cache root each admitted generation warms from.
    """

    def __init__(self, manager, batcher, stable_step,
                 data_shapes=None, stable_name="stable",
                 canary_name="canary", predictor_factory=None,
                 probe=None, slo_factory=None, cache_dir=None,
                 context=None, logger=None):
        from ..checkpoint import CheckpointManager
        if isinstance(manager, str):
            manager = CheckpointManager(manager)
        self.manager = manager
        self.batcher = batcher
        self.stable_step = stable_step
        self.stable_name = str(stable_name)
        self.canary_name = str(canary_name)
        self._data_shapes = data_shapes
        self._factory = predictor_factory
        self._probe = probe or finite_probe()
        self._slo_factory = slo_factory
        self._cache_dir = cache_dir
        self._context = context
        self._logger = logger or logging.getLogger(
            "mxnet_tpu.autopilot")
        self._canary = None      # {"step", "predictor", "since_tick"}
        self._rejected = set()   # steps rolled back — never re-admitted
        from .. import telemetry
        scope = telemetry.registry().scope("autopilot")
        self._c_admit = scope.counter("canary_admissions")
        self._c_promote = scope.counter("canary_promotions")
        self._c_rollback = scope.counter("canary_rollbacks")
        self._g_canary = scope.gauge("canary_step")

    # ------------------------------------------------------- sensors
    def observe(self, tick=0, now=None):
        """One poll of the delivery sensors, as the JSON-able obs dict
        :func:`~mxnet_tpu.autopilot.kernel.decide_canary` consumes.
        Re-runs the probe on a live canary every poll — the probe is a
        sensor, and a generation that degrades AFTER admission must
        still fail before its soak completes."""
        latest = self.manager.latest()
        obs = {"latest_step": latest, "stable_step": self.stable_step,
               "canary_step": None, "probe_ok": None,
               "canary_breach": False, "ticks_in_canary": 0,
               "rejected": bool(latest is not None
                                and latest in self._rejected)}
        if self._canary is not None:
            c = self._canary
            obs["canary_step"] = c["step"]
            obs["ticks_in_canary"] = int(tick) - c["since_tick"]
            obs["probe_ok"] = self._run_probe(c["predictor"])
            ten = self.batcher.tenant(self.canary_name)
            obs["canary_breach"] = bool(
                ten.slo is not None and ten.slo.breached(now=now))
        return obs

    def _run_probe(self, pred):
        try:
            return bool(self._probe(pred))
        except Exception as exc:  # noqa: BLE001 — a probe that cannot
            # run is a failing probe: the generation must not promote
            # on a broken sensor
            self._logger.warning("canary probe raised: %r", exc)
            return False

    def _load(self, step):
        if self._factory is not None:
            return self._factory(step)
        from ..serving import Predictor
        pred = Predictor.load(self.manager, step,
                              data_shapes=self._data_shapes,
                              context=self._context)
        pred.warmup(cache_dir=self._cache_dir)
        return pred

    # ------------------------------------------------------ actuators
    def apply(self, decision, tick=0):
        """Actuate one kernel decision (``admit``/``promote``/
        ``rollback``; ``hold`` is a no-op)."""
        action = decision.get("action")
        if action == "admit":
            self._admit(decision["step"], tick)
        elif action == "rollback":
            self._rollback(decision)
        elif action == "promote":
            self._promote(decision)

    def _admit(self, step, tick):
        from .. import telemetry
        from ..serving import Tenant
        pred = self._load(step)
        slo = self._slo_factory("%s_%d" % (self.canary_name, step)) \
            if self._slo_factory is not None else None
        # priority 0 + protected=False: the canary is the FIRST tenant
        # shed under pressure and never survives its own breach
        self.batcher.add_tenant(Tenant(self.canary_name, pred, slo=slo,
                                       priority=0, protected=False))
        self._canary = {"step": step, "predictor": pred,
                        "since_tick": int(tick)}
        self._c_admit.add()
        self._g_canary.set(step)
        telemetry.flight_recorder().note(
            "canary_admitted", step=step,
            digest=(pred.params_digest or "")[:12])
        self._logger.info("autopilot: admitted step %d as canary %r",
                          step, self.canary_name)

    def _rollback(self, decision):
        from .. import telemetry
        c, self._canary = self._canary, None
        self.batcher.remove_tenant(self.canary_name)
        self._rejected.add(c["step"])
        c["predictor"].release()
        self._c_rollback.add()
        self._g_canary.set(-1)
        telemetry.flight_recorder().note(
            "canary_rollback", step=c["step"],
            reason=decision.get("reason"))
        self._logger.warning(
            "autopilot: rolled back canary step %d (%s) — generation "
            "marked rejected", c["step"], decision.get("reason"))

    def _promote(self, decision):
        from .. import telemetry
        from ..serving import Tenant
        c, self._canary = self._canary, None
        # remove the canary route FIRST: the promoted Predictor must
        # not be hosted under two names (the batcher refuses shared
        # predictor instances across tenants)
        self.batcher.remove_tenant(self.canary_name)
        old = self.batcher.tenant(self.stable_name)
        self.batcher.replace_tenant(self.stable_name, Tenant(
            self.stable_name, c["predictor"], slo=old.slo,
            priority=max(1, old.priority), protected=True))
        old.predictor.release()
        self.stable_step = c["step"]
        self._c_promote.add()
        self._g_canary.set(-1)
        telemetry.flight_recorder().note(
            "canary_promoted", step=c["step"],
            reason=decision.get("reason"))
        self._logger.info(
            "autopilot: promoted canary step %d to %r", c["step"],
            self.stable_name)

    # ---------------------------------------------------------- misc
    @property
    def canary_step(self):
        """The live canary's generation, or None."""
        return self._canary["step"] if self._canary is not None else None

    @property
    def rejected_steps(self):
        """Generations rolled back (never re-admitted), sorted."""
        return sorted(self._rejected)
