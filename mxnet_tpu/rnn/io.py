"""Bucketed sequence iterators.

API counterpart of the reference's python/mxnet/rnn/io.py
(BucketSentenceIter / encode_sentences), redesigned around numpy batch
assembly: sentences are padded into one dense matrix PER BUCKET up
front, labels are the shifted sequence computed vectorized at reset, and
each next() slices a contiguous batch out of the bucket matrix — batches
stay host-side numpy until the train step stages them, so no device
chatter happens during iteration.

TPU note: every distinct bucket length is a distinct XLA program for the
BucketingModule (compile cache keyed by bucket_key). Fewer, coarser
buckets mean fewer compilations; the auto-bucketing below only keeps
lengths holding at least one full batch for exactly that reason.
"""
from __future__ import annotations

import bisect
import logging
import random

import numpy as onp

from .. import ndarray
from ..io import DataIter, DataBatch, DataDesc

__all__ = ["BucketSentenceIter", "encode_sentences"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Map token sequences to integer id sequences.

    With ``vocab=None`` a new vocabulary is built on the fly (ids
    assigned in first-seen order from ``start_label``, skipping
    ``invalid_label``); with a given vocab, unknown tokens raise.
    Returns ``(encoded_sentences, vocab)``.
    """
    building = vocab is None
    if building:
        vocab = {invalid_key: invalid_label}
    next_id = start_label
    encoded = []
    for sentence in sentences:
        ids = []
        for token in sentence:
            if token not in vocab:
                if not building:
                    raise ValueError("unknown token %r with a fixed vocab"
                                     % (token,))
                if next_id == invalid_label:
                    next_id += 1
                vocab[token] = next_id
                next_id += 1
            ids.append(vocab[token])
        encoded.append(ids)
    return encoded, vocab


class BucketSentenceIter(DataIter):
    """Iterator over padded variable-length sequences grouped into
    length buckets; emits DataBatch with ``bucket_key`` for the
    BucketingModule and next-token labels for language modelling."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label",
                 dtype="float32", layout="NTC"):
        super().__init__()
        self.batch_size = batch_size
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.major_axis = layout.find("N")
        if self.major_axis not in (0, 1):
            raise ValueError(
                "layout %r: need batch-major ('NT...') or time-major "
                "('TN...')" % layout)

        if not buckets:
            # keep only lengths that can fill at least one whole batch —
            # each bucket is a separate XLA compilation downstream
            counts = onp.bincount([len(s) for s in sentences])
            buckets = [length for length, c in enumerate(counts)
                       if c >= batch_size]
        self.buckets = sorted(buckets)
        self.default_bucket_key = max(self.buckets)

        # dense per-bucket matrices, padded with invalid_label
        rows = [[] for _ in self.buckets]
        dropped = 0
        for s in sentences:
            b = bisect.bisect_left(self.buckets, len(s))
            if b == len(self.buckets):
                dropped += 1
                continue
            rows[b].append(s)
        if dropped:
            logging.warning(
                "BucketSentenceIter: dropped %d sentences longer than the "
                "largest bucket (%d)", dropped, self.default_bucket_key)
        self.data = []
        for blen, sents in zip(self.buckets, rows):
            mat = onp.full((len(sents), blen), invalid_label, dtype=dtype)
            for r, s in enumerate(sents):
                mat[r, :len(s)] = s
            self.data.append(mat)

        bshape = ((batch_size, self.default_bucket_key)
                  if self.major_axis == 0
                  else (self.default_bucket_key, batch_size))
        self.provide_data = [DataDesc(data_name, bshape, layout=layout)]
        self.provide_label = [DataDesc(label_name, bshape, layout=layout)]

        # (bucket, row-offset) pairs addressing every full batch
        self.idx = [(b, r)
                    for b, mat in enumerate(self.data)
                    for r in range(0, len(mat) - batch_size + 1,
                                   batch_size)]
        self.curr_idx = 0
        self.nddata = []
        self.ndlabel = []
        self.reset()

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        self.nddata = []
        self.ndlabel = []
        for mat in self.data:
            onp.random.shuffle(mat)
            # next-token target: shift left, pad the tail position
            lab = onp.roll(mat, -1, axis=1)
            lab[:, -1] = self.invalid_label
            self.nddata.append(ndarray.array(mat, dtype=self.dtype))
            self.ndlabel.append(ndarray.array(lab, dtype=self.dtype))

    def next(self):
        if self.curr_idx >= len(self.idx):
            raise StopIteration
        b, r = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[b][r:r + self.batch_size]
        label = self.ndlabel[b][r:r + self.batch_size]
        if self.major_axis == 1:  # time-major
            data, label = data.T, label.T
        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[b],
            provide_data=[DataDesc(self.data_name, data.shape)],
            provide_label=[DataDesc(self.label_name, label.shape)])
