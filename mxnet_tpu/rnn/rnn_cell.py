"""RNN cells (python/mxnet/rnn/rnn_cell.py:962).

Cell-level API identical to the reference: ``cell(inputs, states)`` one step,
``cell.unroll(...)`` builds the unrolled symbolic graph. ``FusedRNNCell``
emits the single fused RNN op (ops/rnn_op.py — lax.scan inside one XLA
program, replacing cuDNN RNN) and ``unfuse()`` expands it into per-step
cells sharing the same cuDNN-layout parameter vector.

Gate order everywhere is cuDNN canonical: LSTM [i, f, g, o], GRU [r, z, n].
"""
from __future__ import annotations

from .. import symbol
from ..base import string_types

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell", "ModifierCell"]


class RNNParams(object):
    """Container for holding variables (rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell(object):
    """Abstract RNN cell."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, **kwargs):
        """Initial states. By default these are zero-initialized, non-learned
        Variables (lr_mult=0) so the unrolled graph stays shape-inferable and
        bindable; pass ``func=symbol.zeros`` etc. to override (the reference
        signature, rnn_cell.py begin_state)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called directly. " \
            "Call the modifier cell instead."
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is None:
                state = symbol.Variable(name, lr_mult=0.0)
            else:
                # state_info supplies defaults (shape (0, H) = unknown
                # batch); caller kwargs override them, so
                # begin_state(func=zeros, shape=(N, H)) yields concrete
                # shapes (reference rnn_cell.py begin_state)
                merged = {}
                if info is not None:
                    merged.update({k: v for k, v in info.items()
                                   if not k.startswith("__")})
                merged.update(kwargs)
                state = func(name=name, **merged)
            states.append(state)
        return states

    def unpack_weights(self, args):
        """Split fused parameter arrays to per-gate arrays (rnn_cell.py
        unpack_weights)."""
        args = args.copy()
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ["i2h", "h2h"]:
            weight = args.pop("%s%s_weight" % (self._prefix, group_name))
            bias = args.pop("%s%s_bias" % (self._prefix, group_name))
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        from .. import ndarray as nd
        args = args.copy()
        if not self._gate_names:
            return args
        for group_name in ["i2h", "h2h"]:
            weight = []
            bias = []
            for gate in self._gate_names:
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                weight.append(args.pop(wname))
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                bias.append(args.pop(bname))
            args["%s%s_weight" % (self._prefix, group_name)] = \
                nd.concatenate(weight)
            args["%s%s_bias" % (self._prefix, group_name)] = \
                nd.concatenate(bias)
        return args

    def unroll(self, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC", merge_outputs=None):
        """Unroll the cell for ``length`` steps (rnn_cell.py unroll)."""
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            assert len(inputs.list_outputs()) == 1, \
                "unroll doesn't allow grouped symbol as input."
            inputs = symbol.SliceChannel(inputs, axis=axis,
                                         num_outputs=length,
                                         squeeze_axis=1)
            inputs = list(inputs)
        else:
            assert len(inputs) == length
        if begin_state is None:
            begin_state = self.begin_state()

        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [symbol.expand_dims(i, axis=axis) for i in outputs]
            outputs = symbol.Concat(*outputs, dim=axis)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell: h' = act(W x + R h + b)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = symbol.Activation(i2h + h2h, act_type=self._activation,
                                   name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell, gate order [i, f, g, o] (cuDNN canonical)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from ..initializer import LSTMBias
        self._iB = self.params.get(
            "i2h_bias", init=LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4,
                                          name="%sslice" % name)
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid",
                                    name="%si" % name)
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid",
                                        name="%sf" % name)
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh",
                                         name="%sc" % name)
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid",
                                     name="%so" % name)
        next_c = symbol._plus(forget_gate * states[1],
                              in_gate * in_transform,
                              name="%sstate" % name)
        next_h = symbol._mul(out_gate,
                             symbol.Activation(next_c, act_type="tanh"),
                             name="%sout" % name)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell, gate order [r, z, n] (cuDNN canonical)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=prev_state_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h = symbol.SliceChannel(i2h, num_outputs=3,
                                                name="%si2h_slice" % name)
        h2h_r, h2h_z, h2h = symbol.SliceChannel(h2h, num_outputs=3,
                                                name="%sh2h_slice" % name)
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid",
                                       name="%sr_act" % name)
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid",
                                        name="%sz_act" % name)
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h,
                                       act_type="tanh",
                                       name="%sh_act" % name)
        next_h = symbol._plus((1.0 - update_gate) * next_h_tmp,
                              update_gate * prev_state_h,
                              name="%sout" % name)
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN via the single RNN op (rnn_cell.py:497).

    One ``unroll`` emits ONE graph node → one lax.scan XLA program, the
    TPU-native replacement for the cuDNN RNN fast path.
    """

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._directions = 2 if bidirectional else 1

        from ..initializer import FusedRNN, Xavier
        initializer = FusedRNN(Xavier(factor_type="in", magnitude=2.34),
                               num_hidden, num_layers, mode, bidirectional,
                               forget_bias)
        self._parameter = self.params.get("parameters", init=initializer)

    @property
    def state_info(self):
        b = self._num_layers * self._directions
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden),
                 "__layout__": "LNC"}] * n

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def __call__(self, inputs, states):
        raise NotImplementedError("FusedRNNCell cannot be stepped. Please "
                                  "use unroll")

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        if isinstance(inputs, symbol.Symbol):
            assert len(inputs.list_outputs()) == 1
            if axis == 1:  # NTC -> TNC
                inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        else:
            assert len(inputs) == length
            inputs = [symbol.expand_dims(i, axis=0) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=0)
        if begin_state is None:
            begin_state = self.begin_state()

        states = begin_state
        if self._mode == "lstm":
            rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                             state=states[0], state_cell=states[1],
                             state_size=self._num_hidden,
                             num_layers=self._num_layers,
                             bidirectional=self._bidirectional,
                             p=self._dropout,
                             state_outputs=self._get_next_state,
                             mode=self._mode, name="%srnn" % self._prefix)
        else:
            rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                             state=states[0], state_size=self._num_hidden,
                             num_layers=self._num_layers,
                             bidirectional=self._bidirectional,
                             p=self._dropout,
                             state_outputs=self._get_next_state,
                             mode=self._mode, name="%srnn" % self._prefix)

        if not self._get_next_state:
            outputs, states = rnn, []
        elif self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]

        if axis == 1:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs = list(symbol.SliceChannel(
                outputs, axis=axis, num_outputs=length, squeeze_axis=1))
        return outputs, states

    def unfuse(self):
        """Expand to a SequentialRNNCell of unfused cells sharing params."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda pre: RNNCell(self._num_hidden,
                                            activation="relu", prefix=pre),
            "rnn_tanh": lambda pre: RNNCell(self._num_hidden,
                                            activation="tanh", prefix=pre),
            "lstm": lambda pre: LSTMCell(self._num_hidden, prefix=pre),
            "gru": lambda pre: GRUCell(self._num_hidden, prefix=pre),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_" % (self._prefix,
                                                                i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack multiple cells (rnn_cell.py SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "Either specify params for SequentialRNNCell or child cells, not both."
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        num_cells = len(self._cells)
        if begin_state is None:
            begin_state = self.begin_state()
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states,
                input_prefix=input_prefix, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class BidirectionalCell(BaseRNNCell):
    """Run two cells over the sequence in both directions (rnn_cell.py
    BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        raise NotImplementedError("Bidirectional cannot be stepped. "
                                  "Please use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [symbol.Variable("%st%d_data" % (input_prefix, i))
                      for i in range(length)]
        elif isinstance(inputs, symbol.Symbol):
            inputs = list(symbol.SliceChannel(inputs, axis=axis,
                                              num_outputs=length,
                                              squeeze_axis=1))
        else:
            assert len(inputs) == length
        if begin_state is None:
            begin_state = self.begin_state()

        states = begin_state
        l_cell, r_cell = self._cells
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info)],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info):],
            layout=layout, merge_outputs=False)

        outputs = [symbol.Concat(l_o, r_o, dim=1,
                                 name="%st%d" % (self._output_prefix, i))
                   for i, (l_o, r_o) in
                   enumerate(zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs:
            outputs = [symbol.expand_dims(i, axis=axis) for i in outputs]
            outputs = symbol.Concat(*outputs, dim=axis)
        states = [l_states, r_states]
        return outputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (rnn_cell.py ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError()


class DropoutCell(BaseRNNCell):
    """Apply dropout on input (rnn_cell.py DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell doesn't support zoneout. " \
            "Please unfuse first."
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout since it doesn't " \
            "support step. Please add ZoneoutCell to the cells underneath " \
            "instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = self.base_cell, self.zoneout_outputs, \
            self.zoneout_states
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: symbol.Dropout(
            symbol.ones_like(like) if hasattr(symbol, "ones_like")
            else like * 0 + 1, p=p)
        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros(shape=(0, 0))
        output = symbol.where(mask(p_outputs, next_output), next_output,
                              prev_output) if p_outputs != 0.0 \
            else next_output
        states = [symbol.where(mask(p_states, new_s), new_s, old_s)
                  for new_s, old_s in zip(next_states, states)] \
            if p_states != 0.0 else next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Output = base(output) + input (residual connection)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol._plus(output, inputs)
        return output, states


def _cells_unpack_weights(cells, args):
    for cell in cells:
        args = cell.unpack_weights(args)
    return args


def _cells_pack_weights(cells, args):
    for cell in cells:
        args = cell.pack_weights(args)
    return args
