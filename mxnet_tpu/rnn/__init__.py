"""RNN toolkit (python/mxnet/rnn)."""
from .rnn_cell import (BaseRNNCell, RNNCell, LSTMCell, GRUCell, FusedRNNCell,
                       SequentialRNNCell, BidirectionalCell, DropoutCell,
                       ZoneoutCell, ResidualCell, RNNParams)
from .io import BucketSentenceIter, encode_sentences
