"""mxnet_tpu.data — the async device-feed pipeline.

BENCH_r05 measured the device step at ~2750 img/s while the end-to-end
fed rate collapsed to a few percent of that: the HOST input path —
decode, batch assembly, and above all the host->device transfer — sat
on the step's critical path.  The reference hides decode behind
``dmlc::ThreadedIter`` double buffering (``PrefetcherIter``,
iter_prefetcher.h:129; our ``io.PrefetchingIter`` reproduces it as a
host thread), but a TPU-native stack has a third stage to hide: the
transfer itself.  This package overlaps all three:

* :class:`TransformIter` — N ordered decode/augment workers over any
  ``DataIter`` with deterministic per-batch seeding and in-order
  reassembly.  Worker count is a pure throughput knob: the delivered
  batch stream is bitwise identical at 1/2/4 workers.
* :class:`DeviceLoader` — a bounded ring (depth 2-3) of batches
  ALREADY resident on device: a background stager dispatches
  mesh-aware ``jax.device_put`` (per-device shards placed directly,
  no host concat; ``(K, B, ...)`` blocks through the executor group's
  ``stage_stacked`` for ``fit(batch_group=K)``) for batch i+1/i+2
  while the step for batch i runs.
* :class:`PipelineStats` — host-wait ms per step, ring occupancy,
  staged bytes/dtype, and stager throughput, so "input-bound" is a
  measured number in the training log, not a guess.
* :class:`DeviceAugment` / :class:`DeviceAugmentIter` — the u8 wire
  path: uint8 NHWC batches (4x fewer transported bytes than f32
  NCHW) with random crop/flip/normalize compiled as a DEVICE program
  at staging, draws keyed ``(seed, epoch, batch)`` — bitwise
  host-reference parity, replayable across resume.
* :class:`CachedDataset` — the HBM-resident dataset cache: epoch 1
  streams + captures the decoded u8 epoch, epochs >= 2 are served by
  device-side gather (a ``(B,)`` index array is the whole per-batch
  transfer), bit-identical to streaming and budget-gated with a
  graceful host fallback.
* :class:`ShardedCachedDataset` — the pod-sharded spelling: each host
  captures only its ``shard_rows`` block, the cache is one global
  ``P('dp')``-sharded pytree (N x the dataset budget per pod, zero
  duplicated bytes), spill tiers (HBM -> pinned host -> recordio
  re-decode) resolve per shard under one budget knob, and the
  per-epoch global shuffle is a pure function of ``(seed, epoch)``
  (:func:`global_shuffle_order`) — dp-width-stable across elastic
  resume.

Batches delivered through the pipeline are BITWISE identical to plain
iteration, so ``Module.fit(prefetch_to_device=2)`` trains to
bit-equal parameters (pinned by tests/test_data_pipeline.py and the
ci.sh gate).

Quick start::

    from mxnet_tpu.data import DeviceLoader, TransformIter

    it = TransformIter(host_iter, transform=augment, num_workers=4)
    mod.fit(it, num_epoch=..., prefetch_to_device=2)   # or, manually:
    with DeviceLoader(it, module=mod, depth=2) as loader:
        for batch in loader:
            ...
    print(loader.pipeline_stats.snapshot())

See docs/api/data.md for semantics and the stats field reference.
"""
from __future__ import annotations

from .augment import DeviceAugment, DeviceAugmentIter, fold_seed
from .cached import CachedDataset, global_shuffle_order
from .loader import DeviceLoader
from .sharded_cache import ShardedCachedDataset, cache_row_of_pos
from .stats import PipelineStats
from .transform import TransformIter

__all__ = ["DeviceLoader", "TransformIter", "PipelineStats",
           "DeviceAugment", "DeviceAugmentIter", "CachedDataset",
           "ShardedCachedDataset", "global_shuffle_order",
           "cache_row_of_pos", "fold_seed"]
