"""PipelineStats — one shared counter block for the async device-feed
pipeline, drained as an immutable snapshot.

Every number answers the question BENCH_r05 raised ("is the input path
or XLA the bottleneck?") without adding a readback anywhere: the stats
are pure host-side clocks and counters, updated by the stager/transform
threads and read by ``Speedometer``/``fit``/``bench.py``.

Since the telemetry subsystem landed, PipelineStats is a **view over
the shared** :class:`mxnet_tpu.telemetry.MetricsRegistry`: each
instance claims a ``data.<i>.*`` scope, so the Prometheus endpoint and
JSONL flush export pipeline health for free, while ``snapshot()``
keeps its exact historical shape. ``Module.fit`` additionally
publishes the loader it trains through as
``telemetry.set_active_pipeline(...)`` — that is where ``Speedometer``
and the epoch log read host-wait from (the old path reached into the
fit loop's local variables).
"""
from __future__ import annotations

from .. import telemetry

__all__ = ["PipelineStats"]


class PipelineStats:
    """Thread-safe counters for a :class:`~mxnet_tpu.data.DeviceLoader`
    (and the :class:`~mxnet_tpu.data.TransformIter` feeding it).

    Snapshot fields (``snapshot()``):

    * ``batches_delivered`` / ``images_delivered`` — batches/rows handed
      to the consumer so far.
    * ``host_wait_ms`` — cumulative wall time the CONSUMER spent blocked
      in ``next()`` waiting for the ring to produce a batch.  Zero means
      the device step fully hides the input path; a large fraction of
      the epoch means the pipeline is input-bound.
    * ``host_wait_ms_per_step`` — ``host_wait_ms / batches_delivered``.
    * ``stage_ms`` — cumulative time the stager spent assembling +
      dispatching ``jax.device_put`` (overlapped with compute, so this
      is throughput accounting, not a stall).
    * ``stager_img_per_sec`` — staging throughput over the stager's
      active time.
    * ``ring_depth`` / ``ring_occupancy`` / ``ring_high_water`` — the
      configured bound, the current fill, and the maximum fill ever
      observed (the bound holding is the backpressure contract).
    * ``ring_full_waits`` — times the stager blocked on a full ring
      (a healthy overlapped pipeline blocks here, not in ``next()``).
    """

    def __init__(self, ring_depth=0, scope=None):
        self.scope = scope or telemetry.registry().unique_scope("data")
        c = self.scope.counter
        self._c_batches_delivered = c("batches_delivered")
        self._c_images_delivered = c("images_delivered")
        self._c_host_wait_ms = c("host_wait_ms")
        self._c_stage_ms = c("stage_ms")
        self._c_images_staged = c("images_staged")
        self._c_batches_staged = c("batches_staged")
        self._c_bytes_staged = c("bytes_staged")
        self._c_ring_full_waits = c("ring_full_waits")
        # wire-format attribution (the io_device_augment bench fields):
        # what dtype actually crossed the transport and where the
        # augment stage ran — plain attrs, not registry instruments
        # (strings; exported through snapshot())
        self.staged_dtype = None
        self.augment_placement = None
        # dataset-cache attribution (CachedDataset /
        # ShardedCachedDataset feeding this pipeline): the resolved
        # serving tier plus the per-shard byte/row accounting, so the
        # watchdog and bench read the same wire the cache resolved
        self.cache_tier = None
        self._g_cache_shard_bytes = self.scope.gauge("cache_shard_bytes")
        self._g_cache_global_rows = self.scope.gauge("cache_global_rows")
        self._g_ring_depth = self.scope.gauge("ring_depth")
        self._g_ring_occupancy = self.scope.gauge("ring_occupancy")
        self._g_ring_high_water = self.scope.gauge("ring_high_water")
        self.ring_depth = int(ring_depth)
        self.reset()

    # registry-backed field reads (keeps the historical attribute
    # surface: tests and the fit loop read these directly)
    batches_delivered = telemetry.instrument_value("_c_batches_delivered")
    images_delivered = telemetry.instrument_value("_c_images_delivered")
    host_wait_ms = telemetry.instrument_value("_c_host_wait_ms")
    stage_ms = telemetry.instrument_value("_c_stage_ms")
    images_staged = telemetry.instrument_value("_c_images_staged")
    batches_staged = telemetry.instrument_value("_c_batches_staged")
    bytes_staged = telemetry.instrument_value("_c_bytes_staged")
    ring_full_waits = telemetry.instrument_value("_c_ring_full_waits")
    ring_occupancy = telemetry.instrument_value("_g_ring_occupancy")
    ring_high_water = telemetry.instrument_value("_g_ring_high_water")
    cache_shard_bytes = telemetry.instrument_value("_g_cache_shard_bytes")
    cache_global_rows = telemetry.instrument_value("_g_cache_global_rows")

    @property
    def ring_depth(self):
        return int(self._g_ring_depth.value)

    @ring_depth.setter
    def ring_depth(self, depth):
        self._g_ring_depth.set(int(depth))

    def release(self):
        """Drop this instance's ``data.<i>`` scope from the shared
        registry (the counters keep working locally). A DeviceLoader
        that created its own stats releases them on ``close()`` — a
        fit-per-call workload would otherwise grow the registry and
        every ``/metrics`` scrape without bound."""
        self.scope.release()

    def reset(self):
        depth = self.ring_depth
        for inst in (self._c_batches_delivered, self._c_images_delivered,
                     self._c_host_wait_ms, self._c_stage_ms,
                     self._c_images_staged, self._c_batches_staged,
                     self._c_bytes_staged, self._c_ring_full_waits,
                     self._g_ring_occupancy, self._g_ring_high_water,
                     self._g_cache_shard_bytes,
                     self._g_cache_global_rows):
            inst.reset()
        self._g_ring_depth.set(depth)

    # -- producer side -------------------------------------------------
    def note_staged(self, rows, seconds, nbytes=0, dtype=None):
        self._c_batches_staged.add()
        self._c_images_staged.add(int(rows))
        self._c_stage_ms.add(seconds * 1000.0)
        if nbytes:
            self._c_bytes_staged.add(int(nbytes))
        if dtype is not None:
            self.staged_dtype = str(dtype)

    def note_ring(self, occupancy):
        occupancy = int(occupancy)
        self._g_ring_occupancy.set(occupancy)
        if occupancy > self.ring_high_water:
            self._g_ring_high_water.set(occupancy)

    def note_ring_full(self):
        self._c_ring_full_waits.add()

    def note_cache(self, tier, shard_bytes, global_rows):
        """Record the dataset cache feeding this pipeline: resolved
        serving tier plus per-shard bytes / global rows (DeviceLoader
        forwards ``cache_info()`` here once the cache finalizes)."""
        self.cache_tier = str(tier) if tier else None
        self._g_cache_shard_bytes.set(int(shard_bytes or 0))
        self._g_cache_global_rows.set(int(global_rows or 0))

    # -- consumer side -------------------------------------------------
    def note_delivered(self, rows, wait_seconds):
        self._c_batches_delivered.add()
        self._c_images_delivered.add(int(rows))
        self._c_host_wait_ms.add(wait_seconds * 1000.0)

    # -- reading -------------------------------------------------------
    def snapshot(self):
        """Immutable dict of the counters (field table:
        docs/api/data.md)."""
        batches = self.batches_delivered
        host_wait = self.host_wait_ms
        stage_ms = self.stage_ms
        per_step = host_wait / batches if batches else 0.0
        stager_rate = (self.images_staged / (stage_ms / 1000.0)
                       if stage_ms > 0 else 0.0)
        staged_batches = self.batches_staged
        staged_bytes = self.bytes_staged
        return {
            "batches_delivered": batches,
            "images_delivered": self.images_delivered,
            "host_wait_ms": round(host_wait, 3),
            "host_wait_ms_per_step": round(per_step, 3),
            "stage_ms": round(stage_ms, 3),
            "stager_img_per_sec": round(stager_rate, 2),
            "ring_depth": self.ring_depth,
            "ring_occupancy": self.ring_occupancy,
            "ring_high_water": self.ring_high_water,
            "ring_full_waits": self.ring_full_waits,
            "staged_bytes": staged_bytes,
            "staged_bytes_per_batch": round(
                staged_bytes / staged_batches, 1) if staged_batches
            else 0.0,
            "staged_dtype": self.staged_dtype,
            "augment_placement": self.augment_placement,
            "cache_tier": self.cache_tier,
            "cache_shard_bytes": self.cache_shard_bytes,
            "cache_global_rows": self.cache_global_rows,
        }

    def __repr__(self):
        return "PipelineStats(%r)" % (self.snapshot(),)
