"""PipelineStats — one shared counter block for the async device-feed
pipeline, drained as an immutable snapshot.

Every number answers the question BENCH_r05 raised ("is the input path
or XLA the bottleneck?") without adding a readback anywhere: the stats
are pure host-side clocks and counters, updated by the stager/transform
threads and read by ``Speedometer``/``fit``/``bench.py``.
"""
from __future__ import annotations

import threading

__all__ = ["PipelineStats"]


class PipelineStats:
    """Thread-safe counters for a :class:`~mxnet_tpu.data.DeviceLoader`
    (and the :class:`~mxnet_tpu.data.TransformIter` feeding it).

    Snapshot fields (``snapshot()``):

    * ``batches_delivered`` / ``images_delivered`` — batches/rows handed
      to the consumer so far.
    * ``host_wait_ms`` — cumulative wall time the CONSUMER spent blocked
      in ``next()`` waiting for the ring to produce a batch.  Zero means
      the device step fully hides the input path; a large fraction of
      the epoch means the pipeline is input-bound.
    * ``host_wait_ms_per_step`` — ``host_wait_ms / batches_delivered``.
    * ``stage_ms`` — cumulative time the stager spent assembling +
      dispatching ``jax.device_put`` (overlapped with compute, so this
      is throughput accounting, not a stall).
    * ``stager_img_per_sec`` — staging throughput over the stager's
      active time.
    * ``ring_depth`` / ``ring_occupancy`` / ``ring_high_water`` — the
      configured bound, the current fill, and the maximum fill ever
      observed (the bound holding is the backpressure contract).
    * ``ring_full_waits`` — times the stager blocked on a full ring
      (a healthy overlapped pipeline blocks here, not in ``next()``).
    """

    def __init__(self, ring_depth=0):
        self._lock = threading.Lock()
        self.ring_depth = int(ring_depth)
        self.reset()

    def reset(self):
        with self._lock:
            self.batches_delivered = 0
            self.images_delivered = 0
            self.host_wait_ms = 0.0
            self.stage_ms = 0.0
            self.images_staged = 0
            self.batches_staged = 0
            self.ring_occupancy = 0
            self.ring_high_water = 0
            self.ring_full_waits = 0

    # -- producer side -------------------------------------------------
    def note_staged(self, rows, seconds):
        with self._lock:
            self.batches_staged += 1
            self.images_staged += int(rows)
            self.stage_ms += seconds * 1000.0

    def note_ring(self, occupancy):
        with self._lock:
            self.ring_occupancy = int(occupancy)
            if occupancy > self.ring_high_water:
                self.ring_high_water = int(occupancy)

    def note_ring_full(self):
        with self._lock:
            self.ring_full_waits += 1

    # -- consumer side -------------------------------------------------
    def note_delivered(self, rows, wait_seconds):
        with self._lock:
            self.batches_delivered += 1
            self.images_delivered += int(rows)
            self.host_wait_ms += wait_seconds * 1000.0

    # -- reading -------------------------------------------------------
    def snapshot(self):
        """Immutable dict of the counters (field table:
        docs/api/data.md)."""
        with self._lock:
            per_step = (self.host_wait_ms / self.batches_delivered
                        if self.batches_delivered else 0.0)
            stager_rate = (self.images_staged / (self.stage_ms / 1000.0)
                           if self.stage_ms > 0 else 0.0)
            return {
                "batches_delivered": self.batches_delivered,
                "images_delivered": self.images_delivered,
                "host_wait_ms": round(self.host_wait_ms, 3),
                "host_wait_ms_per_step": round(per_step, 3),
                "stage_ms": round(self.stage_ms, 3),
                "stager_img_per_sec": round(stager_rate, 2),
                "ring_depth": self.ring_depth,
                "ring_occupancy": self.ring_occupancy,
                "ring_high_water": self.ring_high_water,
                "ring_full_waits": self.ring_full_waits,
            }

    def __repr__(self):
        return "PipelineStats(%r)" % (self.snapshot(),)
