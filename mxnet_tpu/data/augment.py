"""DeviceAugment — crop/flip/normalize compiled INTO the train program.

BENCH_r02–r05 pinned every fed pipeline as host-bound
(``pipeline_bound_by: "host_cpu_decode"``): the reference's input path
(mshadow-backed ``io/`` iterators, ``iter_normalize.h``) augments and
float-converts every batch on the host and ships f32 NCHW — 4x the bytes of
the decoded uint8 image, plus a host normalize/transpose pass per
batch.  This module moves the whole augment stage onto the device:

* the iterator delivers **uint8 NHWC** wire batches (4x smaller over
  PCIe/ICI/tunnel than f32 NCHW) plus tiny per-batch *augment
  parameter* arrays (crop offsets, mirror flags);
* the bound :class:`~mxnet_tpu.module.MeshExecutorGroup` compiles
  pad -> per-row crop -> mirror -> u8->f32 cast -> normalize ->
  NHWC->NCHW transpose as ONE device program run at staging time
  (``_augment_jit``) — deliberately a SEPARATE program from the train
  step, because a different step-program preamble shifts XLA's
  layout/fusion choices and breaks bitwise parity (see
  :meth:`DeviceAugment.apply`); the cost is one small extra launch
  per staged batch, amortized K-fold by grouped staging;
* randomness is drawn HOST-side from ``(seed, epoch, batch_index)``
  with exactly :class:`~mxnet_tpu.data.TransformIter`'s SplitMix fold,
  so the delivered stream is bitwise identical at any worker count,
  replayable across ``reset()``/checkpoint resume (``set_epoch`` pins
  the epoch coordinate), and INDEPENDENT of the program's own rng
  stream (dropout keys never perturb augmentation);
* :meth:`DeviceAugment.apply_host` is the numpy reference
  implementation, pinned elementwise-equal to the in-program path by
  tests/test_device_augment.py — the host-reference fallback
  (``placement="host"``) trains to BIT-IDENTICAL params.

Eval (``is_train=False``) always takes the deterministic center-crop
variant with no mirror, so ``predict``/``score`` parity holds whatever
the training augmentation was.
"""
from __future__ import annotations

import os

import numpy as onp

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["DeviceAugment", "DeviceAugmentIter", "fold_seed",
           "crop_input_name", "mirror_input_name"]


def fold_seed(seed, epoch, index):
    """SplitMix-style fold of ``(seed, epoch, index)`` — the SAME
    constants as ``TransformIter._batch_seed``: adjacent batches land
    on unrelated streams and the value is a pure function of the
    stream POSITION, never of worker identity or wall time."""
    x = (int(seed) * 0x9e3779b97f4a7c15
         + int(epoch) * 0xbf58476d1ce4e5b9
         + int(index) * 0x94d049bb133111eb) & 0xffffffffffffffff
    x ^= x >> 31
    return x & 0x7fffffff


def crop_input_name(name):
    """Program-input name for a data input's per-row crop offsets."""
    return name + ".aug_crop"


def mirror_input_name(name):
    """Program-input name for a data input's per-row mirror flags."""
    return name + ".aug_mirror"


def _placement_default():
    return "host" if os.environ.get(
        "MXNET_DATA_DEVICE_AUGMENT", "1") == "0" else "device"


class DeviceAugment(object):
    """Declarative augment spec compiled into the step program.

    Parameters
    ----------
    shape : tuple
        Model-view ``(C, H, W)`` — what the symbol's ``data`` input
        consumes after augmentation.
    rand_crop : bool
        Random-crop an ``(H, W)`` window from the (padded) wire image
        during training.  Eval always center-crops.
    rand_mirror : bool
        Random horizontal flip (p=0.5) during training.
    pad : int
        Zero-pad ``pad`` pixels on every spatial edge IN-PROGRAM
        before cropping (the CIFAR pad-and-crop recipe: wire 32x32,
        pad 4, crop 32).
    mean, std : float or sequence
        Per-channel normalize.  The spec computes
        ``out = (x - mean) * (scale / std)`` with the factor
        precomputed in f32 ONCE on the host: a division by a
        non-power-of-two constant is not bitwise-stable between XLA's
        compiled program and the numpy reference (XLA may strength-
        reduce it to a reciprocal multiply), so the multiply IS the
        contract — both paths consume the identical f32 factor.
    scale : float
        Multiplied into the normalize as ``std / scale`` (reference
        ``ImageRecordIter(scale=)`` semantics; ``scale=1/255`` with
        mean 0/std 1 reproduces a plain ``x / 255`` feed).
    in_shape : tuple, optional
        Wire spatial size ``(H_in, W_in)`` the iterator actually
        delivers (default ``(H, W)``).  With ``H_in > H`` the crop
        window is ``H_in + 2*pad - H`` pixels (ImageNet-style
        decode-large-crop-small).
    seed : int
        Root of the per-batch parameter draws.
    """

    def __init__(self, shape, rand_crop=False, rand_mirror=False, pad=0,
                 mean=0.0, std=1.0, scale=1.0, in_shape=None, seed=0):
        c, h, w = (int(s) for s in shape)
        self.shape = (c, h, w)
        self.pad = int(pad)
        if self.pad < 0:
            raise MXNetError("pad must be >= 0 (got %d)" % self.pad)
        hin, win = (int(s) for s in (in_shape or (h, w)))
        self.in_shape = (hin, win)
        self._window = (hin + 2 * self.pad - h, win + 2 * self.pad - w)
        if self._window[0] < 0 or self._window[1] < 0:
            raise MXNetError(
                "crop target %r larger than padded wire image %r"
                % ((h, w), (hin + 2 * self.pad, win + 2 * self.pad)))
        self.rand_crop = bool(rand_crop)
        self.rand_mirror = bool(rand_mirror)
        self.mean = onp.broadcast_to(
            onp.asarray(mean, onp.float32), (c,)).copy()
        # ONE effective normalize factor, precomputed in f32 on the
        # host: both the compiled path and the numpy reference multiply
        # by this identical operand (see the class docstring for why a
        # division would break bitwise parity)
        self.std = onp.broadcast_to(
            onp.asarray(std, onp.float32), (c,)).copy()
        self.scale = float(scale)
        self._norm = (onp.float32(self.scale) / self.std) \
            .astype(onp.float32)
        self.seed = int(seed)

    # -- shapes ---------------------------------------------------------
    @property
    def wire_shape(self):
        """Per-image wire layout: ``(H_in, W_in, C)`` uint8 HWC."""
        return self.in_shape + (self.shape[0],)

    def model_shape(self, batch_size):
        """What the symbol sees: ``(B, C, H, W)`` f32 NCHW."""
        return (int(batch_size),) + self.shape

    @property
    def has_rand_crop(self):
        """Random crop only matters when there is crop freedom."""
        return self.rand_crop and (self._window[0] > 0
                                   or self._window[1] > 0)

    def data_descs(self, name, batch_size):
        """provide_data entries for a wire batch of this spec: the u8
        image block FIRST, then the augment-parameter inputs."""
        b = int(batch_size)
        descs = [DataDesc(name, (b,) + self.wire_shape,
                          dtype=onp.uint8, layout="NHWC")]
        descs.extend(self.param_descs(name, b))
        return descs

    def param_descs(self, name, batch_size):
        b = int(batch_size)
        descs = []
        if self.has_rand_crop:
            descs.append(DataDesc(crop_input_name(name), (b, 2),
                                  dtype=onp.int32, layout=None))
        if self.rand_mirror:
            descs.append(DataDesc(mirror_input_name(name), (b,),
                                  dtype=onp.uint8, layout=None))
        return descs

    # -- deterministic parameter draws ---------------------------------
    def draw(self, name, epoch, index, batch_size):
        """Per-batch augment parameters as ``{input name: host array}``
        — a pure function of ``(seed, epoch, index)``.  Draw order is
        part of the determinism contract: crop rows, crop cols, then
        mirror flags, always from one ``RandomState``."""
        rng = onp.random.RandomState(fold_seed(self.seed, epoch, index))
        b = int(batch_size)
        out = {}
        if self.has_rand_crop:
            wy, wx = self._window
            oy = rng.randint(0, wy + 1, size=b)
            ox = rng.randint(0, wx + 1, size=b)
            out[crop_input_name(name)] = onp.stack(
                [oy, ox], axis=1).astype(onp.int32)
        if self.rand_mirror:
            out[mirror_input_name(name)] = (
                rng.random_sample(b) < 0.5).astype(onp.uint8)
        return out

    # -- the compiled path ---------------------------------------------
    def _is_model_view(self, x):
        """True when ``x`` is already the augmented f32 NCHW tensor
        (a classic float iterator fed into an augment-bound program,
        or the group's zero-fill) — the program then passes it
        through untouched, so predict/score with pre-normalized
        batches keeps working."""
        return (x.dtype != onp.uint8
                and tuple(x.shape[1:]) == self.shape)

    def apply(self, x, crop=None, mirror=None, train=True):
        """uint8 NHWC wire batch -> normalized f32 NCHW, traced into
        the caller's XLA program.  ``crop``/``mirror`` are the staged
        per-row parameter arrays (ignored at eval: center crop, no
        mirror)."""
        import jax
        import jax.numpy as jnp
        if self._is_model_view(x):
            return x.astype(jnp.float32)
        c, h, w = self.shape
        b = x.shape[0]
        if self.pad:
            p = self.pad
            x = jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)))
        wy, wx = self._window
        if wy or wx:
            if train and self.has_rand_crop and crop is not None:
                def one(img, oy, ox):
                    return jax.lax.dynamic_slice(img, (oy, ox, 0),
                                                 (h, w, c))
                x = jax.vmap(one)(x, crop[:, 0], crop[:, 1])
            else:
                cy, cx = wy // 2, wx // 2
                x = x[:, cy:cy + h, cx:cx + w, :]
        if train and self.rand_mirror and mirror is not None:
            # mirror on the u8 bytes, before any arithmetic: bitwise
            # exactness against the host reference is then trivial
            x = jnp.where(mirror[:, None, None, None] != 0,
                          x[:, :, ::-1, :], x)
        # u8 -> f32 via i32: XLA:TPU fuses a direct u8->f32 cast into
        # the downstream transpose as a byte-gather loop ~145x slower
        # than the i32-routed equivalent (PERF.md "transport
        # pathologies")
        xf = x.astype(jnp.int32).astype(jnp.float32)
        xf = (xf - self.mean) * self._norm
        # NOTE: the executor group runs this as its OWN jitted program
        # (MeshExecutorGroup._augment_jit), never fused into the train
        # step — a different step-program preamble shifts XLA's
        # layout/fusion choices and with them the model's reduction
        # rounding, which would break the bitwise host-reference
        # parity contract.  Standalone, every op here is elementwise/
        # gather (no reductions), so the output bytes equal
        # ``apply_host`` exactly for any batch shape.
        return xf.transpose(0, 3, 1, 2)

    # -- the host reference --------------------------------------------
    def apply_host(self, x, crop=None, mirror=None, train=True):
        """Numpy reference of :meth:`apply`, pinned ELEMENTWISE-EQUAL
        by tests — same pad/crop/mirror geometry, same f32 operand
        order.  The ``placement="host"`` fallback trains through this
        path to bit-identical params."""
        x = onp.asarray(x)
        if self._is_model_view(x):
            return x.astype(onp.float32, copy=False)
        c, h, w = self.shape
        if self.pad:
            p = self.pad
            x = onp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)))
        wy, wx = self._window
        if wy or wx:
            if train and self.has_rand_crop and crop is not None:
                rows = [img[oy:oy + h, ox:ox + w, :]
                        for img, (oy, ox) in zip(x, onp.asarray(crop))]
                x = onp.stack(rows)
            else:
                cy, cx = wy // 2, wx // 2
                x = x[:, cy:cy + h, cx:cx + w, :]
        if train and self.rand_mirror and mirror is not None:
            flip = onp.asarray(mirror).astype(bool)
            x = onp.where(flip[:, None, None, None],
                          x[:, :, ::-1, :], x)
        xf = x.astype(onp.int32).astype(onp.float32)
        xf = (xf - self.mean) * self._norm
        return onp.ascontiguousarray(xf.transpose(0, 3, 1, 2))

    def __repr__(self):
        return ("DeviceAugment(shape=%r, in_shape=%r, pad=%d, "
                "rand_crop=%r, rand_mirror=%r, seed=%d)"
                % (self.shape, self.in_shape, self.pad, self.rand_crop,
                   self.rand_mirror, self.seed))


class DeviceAugmentIter(DataIter):
    """Attach a :class:`DeviceAugment` to a u8-HWC-emitting source.

    ``placement="device"`` (default): batches pass through as uint8
    wire blocks plus the spec's per-batch parameter arrays, and the
    iterator exposes ``device_augment_spec`` so ``Module.fit`` binds
    the augment INTO the step program (u8 staged bytes, zero host
    float work).

    ``placement="host"`` (or ``MXNET_DATA_DEVICE_AUGMENT=0``): the
    SAME draws are applied host-side through :meth:`DeviceAugment
    .apply_host` and f32 NCHW model batches are delivered — the
    reference path the CI digest gate trains against.

    Epoch coordinate: ``reset()`` advances it, ``set_epoch`` (called
    by ``fit`` with the true epoch index) pins it — a resumed run
    replays exactly the stream the uninterrupted run saw.

    ``train=False`` builds the EVAL variant: no random draws — the
    device placement ships plain wire batches (the bound program
    center-crops at ``is_train=False`` anyway) and the host placement
    applies the deterministic ``apply_host(train=False)``, so both
    placements score the identical centered stream.
    """

    def __init__(self, data_iter, augment, data_name=None,
                 placement=None, train=True):
        super().__init__(getattr(data_iter, "batch_size", 0))
        self._iter = data_iter
        self._augment = augment
        src = data_iter.provide_data
        self._name = data_name or src[0][0]
        if tuple(src[0][1][1:]) != augment.wire_shape:
            raise MXNetError(
                "source delivers %r per image but the augment spec "
                "expects wire shape %r (uint8 HWC)"
                % (tuple(src[0][1][1:]), augment.wire_shape))
        self.placement = placement or _placement_default()
        if self.placement not in ("device", "host"):
            raise MXNetError("placement must be 'device' or 'host' "
                             "(got %r)" % (self.placement,))
        self.augment_placement = self.placement
        self._train = bool(train)
        b = self.batch_size
        if self.placement == "device":
            self.provide_data = augment.data_descs(self._name, b) \
                if self._train else \
                [DataDesc(self._name, (b,) + augment.wire_shape,
                          dtype=onp.uint8, layout="NHWC")]
            self.device_augment_spec = {self._name: augment}
        else:
            self.provide_data = [DataDesc(self._name,
                                          augment.model_shape(b))]
            self.device_augment_spec = {}
        self.provide_label = data_iter.provide_label
        self._epoch = 0
        self._seq = 0

    # -- epoch coordinate ----------------------------------------------
    @property
    def epoch_coord(self):
        return self._epoch

    def set_epoch(self, epoch):
        self._epoch = int(epoch)
        self._seq = 0

    def reset(self):
        self._iter.reset()
        self._epoch += 1
        self._seq = 0

    # -- iteration ------------------------------------------------------
    def next(self):
        batch = self._iter.next()
        aug = self._augment
        img = batch.data[0]
        img = img._read() if hasattr(img, "_read") else img
        params = aug.draw(self._name, self._epoch, self._seq,
                          img.shape[0]) if self._train else {}
        self._seq += 1
        if self.placement == "device":
            data = [img] + [params[d.name] for d in
                            aug.param_descs(self._name, img.shape[0])
                            if d.name in params]
        else:
            data = [aug.apply_host(
                onp.asarray(img),
                params.get(crop_input_name(self._name)),
                params.get(mirror_input_name(self._name)),
                train=self._train)]
        return DataBatch(data=data, label=batch.label, pad=batch.pad,
                         index=batch.index)

    def iter_next(self):
        try:
            self._current = self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._current.data

    def getlabel(self):
        return self._current.label

    def getpad(self):
        return self._current.pad

    def getindex(self):
        return self._current.index

    def close(self):
        inner = getattr(self._iter, "close", None)
        if callable(inner):
            inner()
