"""CachedDataset — serve epochs >= 2 from an HBM-resident u8 cache.

The streaming path decodes (or at best host-gathers) every image every
epoch and pays a host->device transfer per batch.  But a decoded u8
epoch is small — CIFAR-10 is ~150 MB, ImageNet-224 ~19 GB/shard-able —
and after the first epoch its bytes never change.  CachedDataset
captures the first full epoch it streams (pad rows stripped), places
the decoded ``(N, H, W, C)`` uint8 block on DEVICE, and serves every
later epoch as a device-side gather: one tiny ``(B,)`` index transfer
per batch, ZERO image bytes over the transport, zero host decode.
Augmentation still varies per epoch — the :class:`DeviceAugment`
parameter draws are a pure function of ``(seed, epoch, batch_index)``
and ride the same in-program augment stage as the streaming path, so
cached-mode parameters are BIT-IDENTICAL to streaming-mode parameters
(the ci.sh device-augment gate).

Memory is a declared budget, not a hope: the cache sizes itself
against ``budget_mb`` (default ``MXNET_DATA_CACHE_BUDGET_MB``, 1024)
and falls back gracefully — host-RAM cache (decoded once, gathered on
host, staged as u8) when the block exceeds the device budget, pure
pass-through streaming when caching is disabled.  All three placements
deliver bitwise-identical batch streams.

Composes with the rest of the pipeline: the delivered device-resident
batches pass through ``DeviceLoader``'s ring and
``stage_stacked``'s grouped blocks without a readback, and the gather
program is compiled at cache-finalize time (the end of the capture
epoch — inside fit's warmup window), so steady-state training sees
zero post-warmup retraces.
"""
from __future__ import annotations

import logging
import os

import numpy as onp

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter
from .augment import (crop_input_name, fold_seed, mirror_input_name,
                      _placement_default)

__all__ = ["CachedDataset", "global_shuffle_order"]

_PLACEMENTS = ("auto", "device", "host", "off")


def _budget_bytes(budget_mb):
    if budget_mb is None:
        budget_mb = float(os.environ.get("MXNET_DATA_CACHE_BUDGET_MB",
                                         "1024"))
    return int(float(budget_mb) * (1 << 20))


def global_shuffle_order(seed, epoch, rows):
    """THE per-epoch global shuffle rule: a permutation of ``rows``
    drawn from the ``(seed, epoch)`` coordinate via the TransformIter
    SplitMix fold — a pure function of the coordinate, shared by
    :class:`CachedDataset` and
    :class:`~mxnet_tpu.data.ShardedCachedDataset` so the single-host
    and pod-sharded caches can NEVER drift on what "epoch e shuffled"
    means. The dp width (host count, device count) never enters, which
    is what makes the shuffled GLOBAL order replayable across an
    elastic resume at a CHANGED dp width: every surviving host re-draws
    the identical permutation and gathers its new row block of it."""
    rng = onp.random.RandomState(
        fold_seed(int(seed) ^ 0x5ca1ab1e, int(epoch), 0))
    return rng.permutation(int(rows))


class CachedDataset(DataIter):
    """Wrap a fixed-order u8 source; epoch 1 streams + captures, later
    epochs serve from the cache.

    Parameters
    ----------
    data_iter : DataIter
        Source delivering ONE data entry per batch (the uint8 HWC
        image block) plus labels, in the same order every epoch (a
        non-reshuffling ``NDArrayIter``, ``ImageRecordIter(
        shuffle=False)``, or ``ImageRecordIter(device_augment="defer",
        cache_decoded=True)``).  Per-epoch order variation belongs to
        THIS class (``shuffle=True``), which re-draws a row
        permutation from ``(seed, epoch)`` — the source is never
        touched again once the cache is built.
    augment : DeviceAugment, optional
        Augment spec attached to every delivered batch — parameter
        draws keyed on ``(epoch, batch_index)`` exactly like
        :class:`DeviceAugmentIter`, so streaming and cached epochs
        draw identically.
    module : Module, optional
        When given (even pre-bind), the cache is placed with the
        bound mesh group's shardings at finalize time: the u8 block
        replicated, the gather output sharded like a staged batch —
        ``Module.fit``'s own staging then no-ops on arrival.
    placement : str, optional
        ``"auto"`` (device if the block fits ``budget_mb``, else
        host), ``"device"``, ``"host"``, or ``"off"`` (pure
        pass-through streaming).  Default: the
        ``MXNET_DATA_CACHE_PLACEMENT`` env var, else ``"auto"``.
    budget_mb : float, optional
        Device-cache budget; default ``MXNET_DATA_CACHE_BUDGET_MB``
        (1024).
    shuffle : bool
        Re-permute rows every CACHED epoch (capture epoch delivers
        source order).
    shuffle_from : int
        First epoch coordinate the shuffle applies to (default 1).
        Epochs below it deliver CAPTURE order even when served from
        the cache — so re-entering the capture epoch via
        ``set_epoch`` (guardian rollback-and-skip, resume) replays
        exactly the stream the original pass delivered, instead of a
        permutation the original pass never saw.
    seed : int
        Shuffle-permutation seed.
    """

    def __init__(self, data_iter, augment=None, module=None,
                 data_name=None, placement=None, budget_mb=None,
                 shuffle=False, shuffle_from=1, seed=0,
                 augment_placement=None, logger=None):
        super().__init__(getattr(data_iter, "batch_size", 0))
        self._iter = data_iter
        self._name = data_name or data_iter.provide_data[0][0]
        if augment is None:
            # adopt the source's deferred spec (ImageRecordIter
            # (device_augment="defer"), DeviceAugmentIter): the cache
            # re-draws the SAME (seed, epoch, batch) stream per epoch
            src_spec = getattr(data_iter, "device_augment_spec", None)
            if src_spec:
                augment = src_spec.get(self._name)
        self._augment = augment
        self._module = module
        n_src = len(data_iter.provide_data)
        n_ok = {1}
        if augment is not None:
            # a defer-mode source also carries the spec's param
            # entries; only data[0] (the image block) is captured — the
            # cache recomputes identical draws at delivery
            n_ok.add(1 + len(augment.param_descs(self._name,
                                                 self.batch_size)))
        if n_src not in n_ok:
            raise MXNetError(
                "CachedDataset caches ONE image data entry; the source "
                "provides %r — attach augment params via "
                "CachedDataset(augment=...), not on the source"
                % ([d[0] for d in data_iter.provide_data],))
        self.placement = (placement
                          or os.environ.get("MXNET_DATA_CACHE_PLACEMENT")
                          or "auto")
        if self.placement not in _PLACEMENTS:
            raise MXNetError("placement must be one of %r (got %r)"
                             % (_PLACEMENTS, self.placement))
        self._budget = _budget_bytes(budget_mb)
        self.shuffle = bool(shuffle)
        self.shuffle_from = int(shuffle_from)
        self.seed = int(seed)
        self.logger = logger or logging.getLogger(__name__)
        self.augment_placement = (augment_placement
                                  or _placement_default()) \
            if augment is not None else None

        b = self.batch_size
        if augment is not None and self.augment_placement == "device":
            self.provide_data = augment.data_descs(self._name, b)
            self.device_augment_spec = {self._name: augment}
        elif augment is not None:
            self.provide_data = [DataDesc(self._name,
                                          augment.model_shape(b))]
            self.device_augment_spec = {}
        else:
            self.provide_data = list(data_iter.provide_data)
            self.device_augment_spec = {}
        self.provide_label = data_iter.provide_label
        self._label_names = [d[0] for d in (self.provide_label or [])]

        self._epoch = 0
        self._seq = 0
        # capture/cache state
        self._pending = [] if self.placement != "off" else None
        self._epoch_complete = False
        self._cache_ready = False
        self._rows = 0
        self._images = None       # host u8 block (host placement only:
        #                           freed after device placement — it
        #                           would pin an epoch of host RAM for
        #                           nothing)
        self._labels = None       # list of host (N, ...) label blocks
        self._dev_images = None   # device-resident block (device mode)
        self._gather = None
        self._order = None
        self._order_epoch = None
        self.cache_placement = None     # resolved at finalize
        self.cache_built_epoch = None

    # -- epoch coordinate ----------------------------------------------
    @property
    def epoch_coord(self):
        return self._epoch

    def set_epoch(self, epoch):
        self._epoch = int(epoch)
        self._seq = 0
        self._order = None

    def reset(self):
        if not self._cache_ready:
            if self._epoch_complete and self._pending is not None:
                self._finalize()
            else:
                # partial epoch (or placement "off"): nothing usable
                # was captured — stream the next epoch from the source
                if self._pending is not None:
                    self._pending = []
                self._iter.reset()
        self._epoch += 1
        self._seq = 0
        self._order = None
        self._epoch_complete = False

    # -- capture -> cache ----------------------------------------------
    def _finalize(self):
        """One full epoch captured: build the resident cache and
        compile the gather program — this runs at the END of the
        capture epoch, i.e. inside fit's warmup window, so cached
        epochs add zero post-warmup retraces."""
        imgs = onp.concatenate([e[0] for e in self._pending])
        labels = None
        if self._pending[0][1] is not None:
            labels = [onp.concatenate([e[1][i] for e in self._pending])
                      for i in range(len(self._pending[0][1]))]
        self._pending = []
        nbytes = imgs.nbytes + sum(l.nbytes for l in (labels or []))
        placement = self.placement
        if placement == "auto":
            placement = "device" if nbytes <= self._budget else "host"
            if placement == "host":
                self.logger.warning(
                    "CachedDataset: decoded epoch is %.1f MB > device "
                    "budget %.1f MB (MXNET_DATA_CACHE_BUDGET_MB) — "
                    "serving from the host-RAM cache instead",
                    nbytes / (1 << 20), self._budget / (1 << 20))
        self._images, self._labels = imgs, labels
        self._rows = int(imgs.shape[0])
        self.cache_bytes = nbytes
        self.cache_built_epoch = self._epoch
        if placement == "device":
            try:
                self._place_on_device(imgs)
                # the host copy has no further reader — the device
                # block is the authority; labels stay host (gathered
                # host-side per batch)
                self._images = None
            except Exception as exc:  # noqa: BLE001 — graceful fallback
                self.logger.warning(
                    "CachedDataset: device placement of the %.1f MB "
                    "cache failed (%s) — serving from the host-RAM "
                    "cache instead", nbytes / (1 << 20), exc)
                self._dev_images, self._gather = None, None
                placement = "host"
        self.cache_placement = placement
        self._cache_ready = True

    def _group(self):
        grp = getattr(self._module, "_exec_group", None)
        return grp if grp is not None and getattr(grp, "fused", False) \
            else None

    def _place_on_device(self, imgs):
        import jax
        import jax.numpy as jnp
        grp = self._group()
        if grp is not None:
            self._dev_images = jax.device_put(imgs, grp._repl)
            self._gather = jax.jit(
                lambda c, i: jnp.take(c, i, axis=0),
                out_shardings=grp._batch_sharding)
        else:
            self._dev_images = jax.device_put(imgs)
            self._gather = jax.jit(lambda c, i: jnp.take(c, i, axis=0))
        # compile NOW (still inside the warmup window) with the steady
        # (B,) index aval, and block so a compile failure surfaces here
        warm = self._gather(self._dev_images,
                            jnp.zeros((self.batch_size,), jnp.int32))
        warm.block_until_ready()

    # -- delivery -------------------------------------------------------
    def _epoch_order(self):
        n = self._rows
        if not self.shuffle or self._epoch < self.shuffle_from:
            # pre-shuffle epochs (the capture epoch, by default) serve
            # CAPTURE order: a set_epoch replay of the capture epoch
            # then yields the stream it originally delivered
            return onp.arange(n)
        return global_shuffle_order(self.seed, self._epoch, n)

    def _attach(self, img, labels, pad):
        """One delivered batch: augment params attached (device
        placement) or the host-reference augment applied (host
        placement) — draws keyed on (epoch, seq) either way."""
        aug = self._augment
        if aug is None:
            self._seq += 1
            return DataBatch(data=[img], label=labels, pad=pad)
        # draws sized to the DELIVERED rows (a short capture-epoch tail
        # has fewer than batch_size) — exactly DeviceAugmentIter's
        # draw, so streaming and cached modes stay bit-identical
        rows = int(img.shape[0])
        params = aug.draw(self._name, self._epoch, self._seq, rows)
        self._seq += 1
        if self.augment_placement == "device":
            data = [img] + [params[d.name] for d in
                            aug.param_descs(self._name, rows)]
        else:
            img = img._read() if hasattr(img, "_read") else img
            data = [aug.apply_host(
                onp.asarray(img),
                params.get(crop_input_name(self._name)),
                params.get(mirror_input_name(self._name)), train=True)]
        return DataBatch(data=data, label=labels, pad=pad)

    @staticmethod
    def _host_batch(batch):
        """THE host-unwrap rule for a streamed source batch:
        ``(img, labels, pad)`` as numpy — shared by the capture path,
        the sharded cache's eager prefill, and the recordio re-stream
        so the three can never diverge on what bytes a batch holds."""
        img = batch.data[0]
        img = img._read() if hasattr(img, "_read") else img
        img = onp.asarray(img)
        labels = None
        if batch.label:
            labels = [onp.asarray(lb._read() if hasattr(lb, "_read")
                                  else lb) for lb in batch.label]
        return img, labels, int(batch.pad or 0)

    def next(self):
        if self._cache_ready:
            return self._next_cached()
        try:
            batch = self._iter.next()
        except StopIteration:
            self._epoch_complete = True
            raise
        img, labels, pad = self._host_batch(batch)
        if self._pending is not None:
            self._capture_batch(img, labels, pad)
        return self._attach(img, labels, pad)

    def _strip_pad(self, img, labels, pad):
        """THE real-rows rule for a captured batch: pad rows are
        physically present only when the source wrapped the batch to
        full size (round-batch semantics); a SHORT tail
        (round_batch=False) sets pad but delivers real rows only —
        stripping there would lose data.  Shared by this class and the
        sharded capture so the two can never strip different rows."""
        keep = img.shape[0] - pad \
            if pad and img.shape[0] == self.batch_size \
            else img.shape[0]
        return img[:keep], \
            None if labels is None else [lb[:keep] for lb in labels]

    def _capture_batch(self, img, labels, pad):
        """Append one streamed batch's REAL rows to the capture list."""
        img, labels = self._strip_pad(img, labels, pad)
        self._pending.append(
            (img.copy(),
             None if labels is None else [lb.copy() for lb in labels]))

    def _next_cached(self):
        b = self.batch_size
        if self._order is None or self._order_epoch != self._epoch:
            self._order = self._epoch_order()
            self._order_epoch = self._epoch
        lo = self._seq * b
        if lo >= len(self._order):
            raise StopIteration
        idxs = self._order[lo:lo + b]
        pad = b - len(idxs)
        if pad > 0:
            # round-batch semantics: wrap the epoch head, report pad
            idxs = onp.concatenate([idxs, self._order[:pad]])
        idxs = onp.ascontiguousarray(idxs.astype(onp.int32))
        if self._dev_images is not None:
            import jax.numpy as jnp
            img = self._gather(self._dev_images, jnp.asarray(idxs))
        else:
            img = self._images[idxs]
        labels = None
        if self._labels is not None:
            labels = [lb[idxs] for lb in self._labels]
        return self._attach(img, labels, pad)

    def iter_next(self):
        try:
            self._current = self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._current.data

    def getlabel(self):
        return self._current.label

    def getpad(self):
        return self._current.pad

    def getindex(self):
        return self._current.index

    # -- introspection --------------------------------------------------
    def cache_info(self):
        """Resolved cache state: ``placement`` (None until built),
        ``rows``, ``bytes``, ``built_epoch``, plus the spill-tier
        spelling shared with :class:`ShardedCachedDataset` (``tier``:
        ``hbm`` for the device placement, ``host`` for the host-RAM
        fallback; single shard)."""
        tier = {"device": "hbm", "host": "host"}.get(
            self.cache_placement)
        return {
            "placement": self.cache_placement,
            "rows": self._rows,
            "bytes": getattr(self, "cache_bytes", 0),
            "built_epoch": self.cache_built_epoch,
            "tier": tier,
            "tiers": [tier] if tier else [],
            "shard_bytes": getattr(self, "cache_bytes", 0),
            "shard_rows": self._rows,
        }

    def close(self):
        self._dev_images = None
        self._gather = None
        inner = getattr(self._iter, "close", None)
        if callable(inner):
            inner()
