"""ShardedCachedDataset — the pod-sharded HBM dataset cache.

PR 9's :class:`CachedDataset` is single-host: at dp=N every host
captures the WHOLE decoded epoch — N x duplicated bytes, and the
per-pod dataset budget is capped at one host's HBM.  This class shards
the capture across the pod with the :func:`~mxnet_tpu.dist.shard_rows`
rule (each host keeps only its row block of every streamed batch), and
the resident cache becomes ONE global ``(N, ...)`` u8 pytree with a
``P('dp')`` row spec — the SNIPPETS.md GSPMD pattern: the cache is
just another sharded array, the epoch->=2 gather is a jitted program
over it, and the per-batch transfer stays a ``(B,)`` int32 index.
N x the dataset budget per pod, zero duplicated bytes.

**Spill tiers** — each shard resolves its residency at finalize under
one budget ladder (``MXNET_DATA_CACHE_BUDGET_MB`` ->
``MXNET_DATA_CACHE_HOST_BUDGET_MB`` -> nothing):

* ``hbm`` — the shard lives in the dp-sharded device cache; gather is
  in-program (cross-shard rows move over ICI inside the compiled
  program, never through the host).
* ``host`` — the shard spills to host RAM.  Spill is COORDINATED: one
  spilled shard moves the whole cache onto the host-assembled path
  (per-batch rows gathered host-side and staged through the normal
  batch staging rule), because a half-resident cache cannot be one
  gather program without holding the spilled rows in HBM — the very
  thing the spill avoided.  Where the runtime supports memory kinds
  (TPU), the host block is placed in ``pinned_host`` memory and the
  SAME jitted gather reads it directly; elsewhere it degrades to a
  numpy gather + stage.  Per-shard resolved tiers are still recorded
  individually (telemetry + ``cache_info()``).
* ``recordio`` — nothing is retained; every epoch re-streams
  (re-decodes) the source.  Global shuffle is unavailable on this
  tier (a streaming source has no random access) — requesting it
  warns once and delivery degrades to capture order.

**dp-stable global shuffle** — the per-epoch order is
:func:`~mxnet_tpu.data.global_shuffle_order`, a pure function of the
``(seed, epoch)`` coordinate (SplitMix fold, the ``TransformIter``
discipline).  The dp width never enters the draw, so an elastic resume
at a CHANGED width (dp=8 -> dp=4) re-draws the IDENTICAL global sample
order and each survivor simply gathers its new row block of it —
pinned bitwise by tests/test_dist_elastic.py and the ci.sh
sharded-cache gate.  Shuffled epochs must be fully resident before
batch 0, so a cache built at epoch >= ``shuffle_from`` ingests the
source EAGERLY (one untimed prefill pass) instead of streaming the
capture epoch; epochs below ``shuffle_from`` deliver capture order and
keep PR 9's stream-while-capturing overlap.
"""
from __future__ import annotations

import os

import numpy as onp

from ..base import MXNetError
from .cached import CachedDataset, _budget_bytes, global_shuffle_order

__all__ = ["ShardedCachedDataset", "cache_row_of_pos"]

_TIERS = ("auto", "hbm", "host", "recordio")
_TIER_RANK = {"hbm": 0, "host": 1, "recordio": 2}


def cache_row_of_pos(counts, num_shards, rows_per_shard_padded=None):
    """Map global STREAM position -> cache row for the sharded layout.

    The cache's global row order is host-major: shard h's block is the
    concatenation, over capture batches k, of batch k's h-th contiguous
    row sub-block (the ``shard_rows`` rule).  A sample at stream
    position ``p`` (batch k, within-batch offset o) therefore sits at
    cache row ``h * rows_per_shard_padded + cum_m[k] + (o % m_k)`` with
    ``h = o // m_k`` and ``m_k = counts[k] / num_shards``.  Pure
    arithmetic over the per-batch row counts — every host computes the
    identical mapping, which is what lets a replicated ``(B,)`` index
    drive the sharded gather.
    """
    counts = [int(c) for c in counts]
    R = int(num_shards)
    total = sum(counts)
    for k, c in enumerate(counts):
        if c % R:
            raise MXNetError(
                "captured batch %d has %d rows, not divisible over %d "
                "shards (the shard_rows rule)" % (k, c, R))
    rps = total // R
    rps_pad = int(rows_per_shard_padded) if rows_per_shard_padded \
        else rps
    row_of_pos = onp.empty(total, onp.int64)
    base = cum = 0
    for c in counts:
        m = c // R
        o = onp.arange(c)
        row_of_pos[base:base + c] = \
            (o // m) * rps_pad + cum + (o % m)
        base += c
        cum += m
    return row_of_pos


class ShardedCachedDataset(CachedDataset):
    """Pod-sharded epoch cache over a fixed-order global-batch source.

    Parameters (beyond :class:`CachedDataset`'s)
    --------------------------------------------
    cluster : VirtualCluster, optional
        Virtual-host mode (the CPU-CI harness): one process simulates
        ``cluster.n_hosts`` hosts — each host's shard is captured and
        accounted separately, and the hbm cache is assembled with
        :func:`~mxnet_tpu.dist.staging.assemble_host_slices` (the
        per-process placement of the real pod, driven from one
        process).  Without a cluster: single-shard when the process is
        alone, or one-shard-per-process under a real multi-process
        runtime (the cache block rides
        ``jax.make_array_from_process_local_data`` like every other
        staged input).
    budget_mb : float or sequence, optional
        Per-shard HBM budget (``MXNET_DATA_CACHE_BUDGET_MB``); a
        sequence gives each shard its own budget (the spill-tier
        tests force one virtual host onto the host tier this way).
    host_budget_mb : float or sequence, optional
        Per-shard host-RAM budget for the spill tier
        (``MXNET_DATA_CACHE_HOST_BUDGET_MB``, default 16384); a shard
        over it resolves ``recordio``.
    tier : str, optional
        Force ``hbm`` / ``host`` / ``recordio`` for every shard
        (``MXNET_DATA_CACHE_TIER``, default ``auto``).
    """

    def __init__(self, data_iter, cluster=None, augment=None,
                 module=None, data_name=None, budget_mb=None,
                 host_budget_mb=None, tier=None, shuffle=False,
                 shuffle_from=1, seed=0, augment_placement=None,
                 logger=None):
        super().__init__(
            data_iter, augment=augment, module=module,
            data_name=data_name, placement="auto", budget_mb=budget_mb
            if not isinstance(budget_mb, (list, tuple)) else None,
            shuffle=shuffle, shuffle_from=shuffle_from, seed=seed,
            augment_placement=augment_placement, logger=logger)
        self._cluster = cluster
        self.rank = 0
        if cluster is not None:
            self.num_shards = int(cluster.n_hosts)
            self._virtual = True
        else:
            import jax
            self._virtual = False
            if jax.process_count() > 1:
                from ..dist.runtime import get_runtime
                rt = get_runtime()
                self.rank, self.num_shards = rt.rank, rt.size
            else:
                self.num_shards = 1
        self._dev_budgets = self._per_shard(
            budget_mb, _budget_bytes, "budget_mb")
        self._host_budgets = self._per_shard(
            host_budget_mb,
            lambda v: int(float(
                v if v is not None else os.environ.get(
                    "MXNET_DATA_CACHE_HOST_BUDGET_MB", "16384"))
                * (1 << 20)),
            "host_budget_mb")
        self.tier = (tier or os.environ.get("MXNET_DATA_CACHE_TIER")
                     or "auto")
        if self.tier not in _TIERS:
            raise MXNetError("tier must be one of %r (got %r)"
                             % (_TIERS, self.tier))
        # resolved at finalize
        self._serving_tier = None
        self._shard_tiers = None
        self._dev_cache = None      # tuple of dp-sharded device leaves
        self._host_cache = None     # list of host (N_pad, ...) leaves
        self._counts = None
        self._cap_counts = []       # global per-batch row counts
        self._cap_row_nbytes = None
        self._row_of_pos = None
        self._rows_per_shard = 0
        self._rows_per_shard_pad = 0
        self.cache_shard_bytes = 0
        self.cache_pinned = False

    def _per_shard(self, value, to_bytes, name):
        if isinstance(value, (list, tuple)):
            if len(value) != self.num_shards:
                raise MXNetError(
                    "%s has %d entries for %d shards"
                    % (name, len(value), self.num_shards))
            return [to_bytes(v) for v in value]
        return [to_bytes(value)] * self.num_shards

    # -- mesh / sharding resolution ------------------------------------
    def _mesh_sharding(self):
        """(batch_sharding, host_of_device) — the module's own batch
        sharding when bound+fused (fit's staging then no-ops on the
        gather output), else the cluster's; (None, None) without
        either (plain single-device placement)."""
        grp = self._group()
        if grp is not None:
            sharding = grp._batch_sharding
        elif self._cluster is not None:
            sharding = self._cluster.batch_sharding()
        else:
            return None, None
        host_of = self._cluster.host_of_device() if self._virtual \
            else None
        return sharding, host_of

    # -- capture --------------------------------------------------------
    def _capture_batch(self, img, labels, pad):
        img, labels = self._strip_pad(img, labels, pad)
        rows = int(img.shape[0])
        if rows % self.num_shards:
            raise MXNetError(
                "streamed batch of %d rows does not divide over %d "
                "shards — the sharded cache needs every captured batch "
                "to split evenly (the shard_rows rule)"
                % (rows, self.num_shards))
        self._cap_counts.append(rows)
        if self._cap_row_nbytes is None and rows:
            self._cap_row_nbytes = int(img.nbytes) // rows + sum(
                int(lb.nbytes) // rows for lb in (labels or []))
        if self.tier == "recordio":
            # a forced re-decode tier retains NOTHING: accounting only
            # (the tier exists for epochs too big to hold — capturing
            # them first would be the very cost it avoids)
            return
        if not self._virtual and self.num_shards > 1:
            # real multi-process mode: this process retains ONLY its
            # row block — the whole point of sharding the capture
            from ..dist.sharded_iter import shard_rows
            img = shard_rows(img, self.rank, self.num_shards)
            labels = None if labels is None else \
                [shard_rows(lb, self.rank, self.num_shards)
                 for lb in labels]
        self._pending.append(
            (onp.ascontiguousarray(img),
             None if labels is None else
             [onp.ascontiguousarray(lb) for lb in labels]))

    def _prefill(self):
        """Eager ingest: a shuffled epoch's order touches the whole
        epoch before batch 0 can leave, so the capture cannot overlap
        delivery — drain the source, build the cache, then serve."""
        while True:
            try:
                batch = self._iter.next()
            except StopIteration:
                break
            img, labels, pad = self._host_batch(batch)
            self._capture_batch(img, labels, pad)
        self._epoch_complete = True
        self._finalize()
        if self._serving_tier == "recordio":
            # nothing was retained and the prefill drained the source:
            # rewind it so THIS epoch can re-stream
            self._iter.reset()

    # -- finalize -------------------------------------------------------
    def _finalize(self):
        # counts were recorded at capture time, BEFORE any per-process
        # slicing, so they are GLOBAL per-batch row counts
        counts = list(self._cap_counts)
        if not counts or not sum(counts):
            raise MXNetError(
                "sharded cache captured no rows — the source must "
                "deliver at least one batch")
        self._counts = counts
        total = sum(counts)
        self._rows = int(total)
        rps = total // self.num_shards
        self._rows_per_shard = rps

        sharding, host_of = self._mesh_sharding()
        if not self._virtual and self.num_shards > 1 and sharding is None:
            # without a mesh the local block cannot join a global
            # cache — and jnp.take would silently CLAMP the global row
            # indices into it (wrong data, no error)
            raise MXNetError(
                "multi-process ShardedCachedDataset needs a mesh to "
                "place the dp-sharded cache — pass module= (a bound "
                "fused module) or bind before the capture epoch ends")
        n_dev = len(sharding.mesh.devices.ravel()) if sharding is not None \
            else 1
        per_host_dev = n_dev // self.num_shards if self.num_shards else 1
        per_host_dev = max(1, per_host_dev)
        rps_pad = -(-rps // per_host_dev) * per_host_dev
        self._rows_per_shard_pad = rps_pad
        n_pad = rps_pad * self.num_shards

        self._row_of_pos = cache_row_of_pos(counts, self.num_shards,
                                            rps_pad)

        row_bytes = int(self._cap_row_nbytes or 0)
        self.cache_bytes = total * row_bytes
        self.cache_shard_bytes = rps * row_bytes
        self.cache_built_epoch = self._epoch

        self._shard_tiers = [self._resolve_tier(h) for h in
                             range(self.num_shards)]
        # coordinated degradation: the serving strategy is the WORST
        # resolved tier (a half-resident cache cannot be one program)
        self._serving_tier = max(self._shard_tiers,
                                 key=lambda t: _TIER_RANK[t])
        if self._serving_tier == "host" and not self._virtual \
                and self.num_shards > 1:
            # real multi-process mode captured only this process's
            # block, but host-tier serving gathers GLOBAL cache rows —
            # unavailable here. Re-streaming the (replicated) source
            # is the tier that stays correct on every process.
            self.logger.warning(
                "ShardedCachedDataset: the host spill tier needs the "
                "whole epoch host-side, which a multi-process capture "
                "does not retain — degrading to the recordio "
                "(re-stream) tier")
            self._serving_tier = "recordio"
        if self._serving_tier != "hbm":
            spilled = [h for h, t in enumerate(self._shard_tiers)
                       if t != "hbm"]
            self.logger.warning(
                "ShardedCachedDataset: shard(s) %s spilled off HBM "
                "(%.1f MB/shard vs per-shard budgets) — serving tier "
                "is %r for the whole cache", spilled,
                self.cache_shard_bytes / (1 << 20), self._serving_tier)

        # per-shard blocks in host-major cache row order (leaf 0 the
        # image block, leaves 1.. the labels) — concatenated only for
        # tiers that RETAIN rows; the recordio tier skips the copy
        # entirely (its datasets are the ones too big to hold twice)
        leaves = None
        if self._serving_tier != "recordio" and self._pending:
            leaves = self._collect_leaves(counts, rps, rps_pad)
        self._pending = []
        if self._serving_tier == "hbm":
            try:
                self._place_hbm(leaves, sharding, host_of, n_pad)
            except Exception as exc:  # noqa: BLE001 — graceful spill
                # same rule as the budget-resolved spill: the host tier
                # needs the WHOLE epoch host-side, which a
                # multi-process capture does not retain — there the
                # fallback is the re-stream tier
                fallback = "host" if self._virtual or \
                    self.num_shards == 1 else "recordio"
                self.logger.warning(
                    "ShardedCachedDataset: HBM placement failed (%s) — "
                    "spilling the whole cache to the %s tier", exc,
                    fallback)
                self._dev_cache = self._gather = None
                self._serving_tier = fallback
                self._shard_tiers = [fallback] * self.num_shards
        if self._serving_tier == "host":
            self._place_host(leaves, sharding, n_pad)
        if self._serving_tier == "recordio":
            if self.shuffle:
                self.logger.warning(
                    "ShardedCachedDataset: the recordio tier re-streams "
                    "the source every epoch and has no random access — "
                    "global shuffle is unavailable; delivering capture "
                    "order")
            self._host_cache = None
        self.cache_placement = {"hbm": "device", "host": "host",
                                "recordio": "off"}[self._serving_tier]
        self._cache_ready = True
        self._publish_telemetry()
        self.logger.info(
            "ShardedCachedDataset: %d rows cached across %d shard(s) "
            "(%.1f MB/shard, tier=%s%s)", total, self.num_shards,
            self.cache_shard_bytes / (1 << 20), self._serving_tier,
            ", pinned" if self.cache_pinned else "")

    def _collect_leaves(self, counts, rps, rps_pad):
        """Per-shard blocks concatenated host-major, one padded
        ``(num_shards * rps_pad, ...)`` numpy array per leaf.  Real
        multi-process mode keeps only this process's block (shape
        ``(rps_pad, ...)``)."""
        n_labels = 0 if self._pending[0][1] is None \
            else len(self._pending[0][1])
        own_only = not self._virtual and self.num_shards > 1
        shards = [self.rank] if own_only else range(self.num_shards)
        leaves = []
        for li in range(1 + n_labels):
            def leaf_of(entry):
                return entry[0] if li == 0 else entry[1][li - 1]

            blocks = []
            for h in shards:
                if own_only:
                    parts = [leaf_of(e) for e in self._pending]
                else:
                    parts = []
                    for k, e in enumerate(self._pending):
                        m = counts[k] // self.num_shards
                        parts.append(leaf_of(e)[h * m:(h + 1) * m])
                block = onp.concatenate(parts)
                if rps_pad > rps:
                    pad_rows = onp.zeros((rps_pad - rps,)
                                         + block.shape[1:], block.dtype)
                    block = onp.concatenate([block, pad_rows])
                blocks.append(block)
            leaves.append(blocks if self._virtual
                          else onp.concatenate(blocks))
        return leaves

    def _resolve_tier(self, shard):
        if self.tier != "auto":
            return self.tier
        if self.cache_shard_bytes <= self._dev_budgets[shard]:
            return "hbm"
        if self.cache_shard_bytes <= self._host_budgets[shard]:
            return "host"
        return "recordio"

    # -- placement ------------------------------------------------------
    def _cache_sharding(self, batch_sharding):
        """The cache rows ride the SAME ``P('dp')`` row spec as every
        staged batch — the cache is just another pytree on the mesh."""
        return batch_sharding

    def _place_hbm(self, leaves, sharding, host_of, n_pad):
        import jax
        placed = []
        for leaf in leaves:
            if sharding is None:
                placed.append(jax.device_put(
                    leaf if not isinstance(leaf, list) else leaf[0]))
            elif self._virtual and self.num_shards > 1:
                from ..dist.staging import assemble_host_slices
                gshape = (n_pad,) + tuple(leaf[0].shape[1:])
                placed.append(assemble_host_slices(
                    self._cache_sharding(sharding), gshape, leaf,
                    host_of))
            elif not self._virtual and self.num_shards > 1:
                # real pod: the local block rides THE staging rule —
                # make_array_from_process_local_data, like every input
                from ..dist.staging import stage_sharded
                gshape = (n_pad,) + tuple(leaf.shape[1:])
                placed.append(stage_sharded(
                    leaf, self._cache_sharding(sharding), gshape))
            else:
                block = leaf[0] if isinstance(leaf, list) else leaf
                placed.append(jax.device_put(
                    block, self._cache_sharding(sharding)))
        self._dev_cache = tuple(placed)
        self._build_gather(sharding)
        self._warm_gather()

    def _place_host(self, leaves, sharding, n_pad):
        """Spill path: the whole cache host-side (numpy), with an
        opportunistic ``pinned_host`` placement where the runtime has
        memory kinds — the jitted gather then reads the pinned block
        directly and the numpy copy is dropped."""
        host = []
        for leaf in leaves:
            host.append(onp.concatenate(leaf) if isinstance(leaf, list)
                        else leaf)
        self._host_cache = host
        if sharding is None or \
                os.environ.get("MXNET_DATA_CACHE_PINNED", "1") == "0":
            return
        try:
            import jax
            from jax.sharding import NamedSharding
            pinned = NamedSharding(sharding.mesh, sharding.spec,
                                   memory_kind="pinned_host")
            placed = tuple(jax.device_put(h, pinned) for h in host)
            self._dev_cache = placed
            self._build_gather(sharding)
            self._warm_gather()
            self.cache_pinned = True
            self._host_cache = None
        except Exception:  # noqa: BLE001 — memory kinds are optional
            self._dev_cache = self._gather = None
            self.cache_pinned = False

    def _build_gather(self, sharding):
        import jax
        import jax.numpy as jnp
        n_leaves = len(self._dev_cache)

        def gather(cache, idx):
            return tuple(jnp.take(c, idx, axis=0) for c in cache)

        if sharding is not None:
            self._gather = jax.jit(
                gather, out_shardings=(sharding,) * n_leaves)
        else:
            self._gather = jax.jit(gather)

    def _warm_gather(self):
        # compile NOW — finalize runs at the capture epoch's end, i.e.
        # inside fit's warmup window, so cached epochs retrace nothing
        import jax
        import jax.numpy as jnp
        warm = self._gather(self._dev_cache,
                            jnp.zeros((self.batch_size,), jnp.int32))
        jax.block_until_ready(warm)

    def _publish_telemetry(self):
        from .. import telemetry
        reg = telemetry.registry()
        for t in ("hbm", "host", "recordio"):
            reg.gauge("data.cache_tier_%s" % t).set(
                sum(1 for s in self._shard_tiers if s == t))
        reg.gauge("data.cache_shard_bytes").set(self.cache_shard_bytes)
        reg.gauge("data.cache_global_rows").set(self._rows)

    # -- delivery -------------------------------------------------------
    @property
    def background_pull_safe(self):
        """False when serving launches a COLLECTIVE gather program (any
        mesh-sharded cache): collectives must be enqueued in the same
        program order on every device, so a background stager thread
        launching the gather concurrently with the training step's
        collectives can interleave the per-device rendezvous — a
        deadlock on XLA:CPU and a cross-host ordering hazard on a real
        pod.  DeviceLoader consults this and pulls such a source on
        the CONSUMER thread instead (the gather output is already
        device-resident, so there is no transfer to hide anyway)."""
        try:
            sharding, _ = self._mesh_sharding()
        except Exception:  # noqa: BLE001 — conservative default
            return False
        return sharding is None

    def epoch_positions(self, epoch):
        """The delivered GLOBAL sample order of ``epoch`` as capture
        positions — a pure function of ``(seed, epoch)`` (plus the
        capture geometry), identical at every dp width.  The elastic
        tests pin dp=8 and dp=4 instances to the same transcript."""
        if not self._cache_ready:
            raise MXNetError("cache not built yet")
        if not self.shuffle or epoch < self.shuffle_from \
                or self._serving_tier == "recordio":
            return onp.arange(self._rows)
        return global_shuffle_order(self.seed, epoch, self._rows)

    def next(self):
        if not self._cache_ready and self.shuffle \
                and self._epoch >= self.shuffle_from:
            self._prefill()
        return super().next()

    def _next_cached(self):
        if self._serving_tier == "recordio":
            return self._next_restream()
        b = self.batch_size
        if self._order is None or self._order_epoch != self._epoch:
            self._order = self.epoch_positions(self._epoch)
            self._order_epoch = self._epoch
        lo = self._seq * b
        if lo >= len(self._order):
            raise StopIteration
        pos = self._order[lo:lo + b]
        pad = b - len(pos)
        if pad > 0:
            # round-batch semantics: wrap the epoch head, report pad
            pos = onp.concatenate([pos, self._order[:pad]])
        idx = onp.ascontiguousarray(
            self._row_of_pos[pos].astype(onp.int32))
        if self._dev_cache is not None:
            import jax.numpy as jnp
            gathered = self._gather(self._dev_cache, jnp.asarray(idx))
        else:
            gathered = tuple(leaf[idx] for leaf in self._host_cache)
        img = gathered[0]
        labels = list(gathered[1:]) if len(gathered) > 1 else None
        return self._attach(img, labels, pad)

    def _next_restream(self):
        batch = self._iter.next()   # StopIteration ends the epoch
        img, labels, pad = self._host_batch(batch)
        return self._attach(img, labels, pad)

    def _epoch_batches(self):
        return -(-self._rows // self.batch_size)

    def skip_batches(self, n):
        """Advance the stream position by ``n`` batches without paying
        gather/augment for discarded resume batches (fit's mid-epoch
        fast-forward)."""
        n = int(n)
        if not self._cache_ready and self.shuffle \
                and self._epoch >= self.shuffle_from:
            self._prefill()
        if self._cache_ready and self._serving_tier != "recordio":
            done = min(n, max(0, self._epoch_batches() - self._seq))
            self._seq += done
            return done
        done = 0
        for _ in range(n):
            try:
                self.next()     # capture-aware pull-and-discard
            except StopIteration:
                break
            done += 1
        return done

    def reset(self):
        super().reset()
        if not self._cache_ready:
            # a partial capture was discarded: the accounting recorded
            # alongside it must go too, or the re-streamed epoch would
            # double-count its head batches
            self._cap_counts = []
            self._cap_row_nbytes = None
        elif self._serving_tier == "recordio":
            # nothing was retained: the next epoch re-streams
            self._iter.reset()

    # -- introspection --------------------------------------------------
    def cache_info(self):
        """Resolved cache state: serving ``tier``, per-shard resolved
        ``tiers``, per-shard ``shard_rows``/``shard_bytes``, global
        ``rows``/``bytes``, ``num_shards``, ``pinned``,
        ``built_epoch`` (plus ``placement`` in the CachedDataset
        spelling)."""
        return {
            "tier": self._serving_tier,
            "tiers": list(self._shard_tiers or []),
            "placement": self.cache_placement,
            "rows": self._rows,
            "bytes": getattr(self, "cache_bytes", 0),
            "shard_rows": self._rows_per_shard,
            "shard_bytes": self.cache_shard_bytes,
            "num_shards": self.num_shards,
            "pinned": self.cache_pinned,
            "built_epoch": self.cache_built_epoch,
        }

    def close(self):
        self._dev_cache = None
        self._host_cache = None
        super().close()
