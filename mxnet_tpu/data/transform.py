"""TransformIter — N ordered transform workers over any DataIter.

The reference parallelizes decode inside the C++ iterator chain
(``iter_image_recordio_2.cc``'s decode farm) and double-buffers the
assembled batch behind ``dmlc::ThreadedIter`` (SURVEY §2.4).
``io.PrefetchingIter`` reproduces only the second half — ONE background
thread, so a python-side transform (augment, normalize, reshape, mixup)
still runs serially on the consumer's critical path.  TransformIter
generalizes it: the source iterator is pulled by one sequencer thread
(iterator protocol is stateful and must stay serial), each pulled batch
is handed to a pool of N workers together with a deterministic
per-batch RNG, and finished batches are reassembled IN ORDER.

Determinism is the contract that makes N a pure throughput knob: the
worker RNG is seeded from ``(seed, epoch, batch_index)`` — never from
which worker happened to pick the batch up or when — so the delivered
batch stream is bitwise identical at 1, 2, or 4 workers (pinned by
tests/test_data_pipeline.py), and a ``reset()`` replays the next epoch
identically for the same epoch index.
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as onp

from ..base import MXNetError
from .. import faults as _faults
from ..io import DataIter

__all__ = ["TransformIter"]

# free-running sentinel objects (identity-compared)
_END = object()


class TransformIter(DataIter):
    """Apply ``transform(batch, rng)`` with ``num_workers`` threads,
    delivering batches in source order.

    Parameters
    ----------
    data_iter : DataIter
        Source iterator.  It is pulled from exactly one thread.
    transform : callable, optional
        ``transform(batch, rng) -> batch`` where ``rng`` is a
        ``numpy.random.RandomState`` deterministically seeded per
        (epoch, batch index).  ``None`` means identity — the iterator
        is then a pure ordered multi-buffer prefetcher (the
        ``PrefetchingIter`` pattern with a bounded depth).
    num_workers : int
        Transform worker threads.  Changing it never changes the
        delivered bytes, only the throughput.
    depth : int, optional
        Maximum batches in flight (pulled but not yet consumed).
        Default ``2 * num_workers``.  The sequencer blocks when the
        bound is hit — a slow consumer backpressures the source
        instead of buffering an epoch in RAM.
    seed : int
        Root of the per-batch seeding.
    """

    def __init__(self, data_iter, transform=None, num_workers=2,
                 depth=None, seed=0, restart_on_error=None):
        super().__init__(getattr(data_iter, "batch_size", 0))
        if num_workers < 1:
            raise MXNetError("num_workers must be >= 1 (got %d)"
                             % num_workers)
        if restart_on_error is None:
            import os
            restart_on_error = os.environ.get(
                "MXNET_FAULT_STAGER_RESTART", "0") == "1"
        # with restart_on_error a TRANSFORM error is delivered in order
        # and the stream continues past the failed batch (the pool and
        # sequencer are still alive); source errors stay terminal — the
        # source iterator's state after its own exception is undefined
        self._restart_on_error = bool(restart_on_error)
        self._source_dead = False
        self._iter = data_iter
        self._transform = transform
        self._num_workers = int(num_workers)
        self._depth = int(depth) if depth else 2 * self._num_workers
        if self._depth < 1:
            raise MXNetError("depth must be >= 1 (got %d)" % self._depth)
        self._seed = int(seed)
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._pool = ThreadPoolExecutor(
            max_workers=self._num_workers,
            thread_name_prefix="mxtpu-transform")
        self._epoch = -1
        self._sequencer = None
        self._start_epoch(reset_source=False)

    # -- epoch machinery -----------------------------------------------
    def _start_epoch(self, reset_source):
        """Tear down any in-flight epoch, optionally reset the source,
        and launch a fresh sequencer.  Serial by construction: the old
        sequencer is joined before the source is touched, so a
        ``reset()`` mid-epoch can never race an in-flight pull."""
        self._stop_sequencer()
        if reset_source:
            self._iter.reset()
        with self._cond:
            self._results = {}
            self._next_put = 0      # next sequence number to pull
            self._next_get = 0      # next sequence number to deliver
            self._stop = False
            self._exhausted = False
            self._source_dead = False
        self._epoch += 1
        with self._cond:
            # epoch tag: a straggler transform submitted before a
            # reset() must never deposit its (stale) batch into the new
            # epoch's reassembly window
            self._live_epoch = self._epoch
        self._sequencer = threading.Thread(
            target=self._sequence, args=(self._epoch,),
            name="mxtpu-transform-seq", daemon=True)
        self._sequencer.start()

    def _stop_sequencer(self):
        seq = self._sequencer
        if seq is None:
            return
        with self._cond:
            self._stop = True
            # unblock a sequencer waiting on a full window and any
            # worker-completion waits
            self._cond.notify_all()
        seq.join()
        self._sequencer = None
        # drop any transformed-but-undelivered batches
        with self._cond:
            self._results = {}

    def _sequence(self, epoch):
        """Pull batches serially, fan transforms out to the pool."""
        while True:
            with self._cond:
                while not self._stop and \
                        self._next_put - self._next_get >= self._depth:
                    self._cond.wait(0.05)
                if self._stop:
                    return
                seq = self._next_put
                self._next_put += 1
            try:
                batch = self._iter.next()
            except StopIteration:
                # a normal epoch end is NOT a dead source: in-flight
                # transform errors delivered after this point must
                # still honor restart_on_error (the _END marker ends
                # the epoch when ITS turn comes)
                self._finish(epoch, seq, _END)
                return
            except Exception as exc:  # surface on the consumer thread
                self._source_dead = True
                self._finish(epoch, seq, exc)
                return
            if self._transform is None:
                self._finish(epoch, seq, batch)
            else:
                self._pool.submit(self._run_transform, epoch, seq, batch)

    def _run_transform(self, epoch, seq, batch):
        def attempt():
            if _faults.armed():
                # transform-worker seam; the rng below re-seeds per
                # attempt, so a healed retry delivers IDENTICAL bytes
                _faults.check("data.transform", epoch=epoch, index=seq)
            rng = onp.random.RandomState(self._batch_seed(epoch, seq))
            return self._transform(batch, rng)
        try:
            out = _faults.retry(attempt, site="data.transform",
                                seed=self._seed)
        except Exception as exc:  # noqa: BLE001 — delivered in order
            out = exc
        self._finish(epoch, seq, out)

    def _batch_seed(self, epoch, seq):
        # THE SplitMix fold (data.augment.fold_seed, shared with the
        # DeviceAugment draw machinery): adjacent batches must land on
        # unrelated streams, and the value is a function of the
        # SEQUENCE position only — worker identity never enters
        from .augment import fold_seed
        return fold_seed(self._seed, epoch, seq)

    def _finish(self, epoch, seq, value):
        with self._cond:
            if self._stop or epoch != self._live_epoch:
                return
            self._results[seq] = value
            self._cond.notify_all()

    # -- DataIter surface ----------------------------------------------
    def next(self):
        if self._closed:
            raise MXNetError("TransformIter is closed")
        with self._cond:
            if self._exhausted:
                # the sequencer exited at epoch end (or on an error it
                # already delivered) — keep raising StopIteration like
                # every DataIter does until reset(), instead of waiting
                # on results that can never arrive
                raise StopIteration
            while self._next_get not in self._results:
                if self._stop:
                    raise MXNetError("TransformIter was reset/closed "
                                     "while a next() was blocked")
                self._cond.wait(0.05)
            value = self._results.pop(self._next_get)
            self._next_get += 1
            if value is _END or (isinstance(value, BaseException)
                                 and not (self._restart_on_error
                                          and not self._source_dead)):
                self._exhausted = True
            self._cond.notify_all()
        if value is _END:
            raise StopIteration
        if isinstance(value, BaseException):
            raise value
        return value

    def iter_next(self):
        try:
            self._current = self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._current.data

    def getlabel(self):
        return self._current.label

    def getpad(self):
        return self._current.pad

    def getindex(self):
        return self._current.index

    def reset(self):
        """Rewind to a fresh epoch.  Safe to call repeatedly and while
        transforms are in flight: the old epoch's work is cancelled and
        joined before the source resets, so no stale batch can leak
        into the new epoch."""
        if self._closed:
            raise MXNetError("TransformIter is closed")
        self._start_epoch(reset_source=True)

    # -- lifecycle -----------------------------------------------------
    def close(self):
        """Join the sequencer and shut the worker pool down.
        Idempotent; also runs via the context-manager exit."""
        if self._closed:
            return
        self._closed = True
        self._stop_sequencer()
        self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
