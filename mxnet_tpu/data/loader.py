"""DeviceLoader — a bounded ring of batches already resident on device.

The reference overlaps host decode with compute through
``PrefetcherIter``'s host-side double buffer (iter_prefetcher.h:129) —
but on an accelerator the host->device TRANSFER is a third pipeline
stage the reference never had to hide (BENCH_r05: the fed rate collapsed
to a few percent of synthetic because every ``device_put`` sat on the
step's critical path).  The DeviceLoader is the tf.data/infeed design
for this stack: a background stager thread pulls host batches from any
``DataIter`` and dispatches ``jax.device_put`` for batch i+1/i+2 while
the device still computes batch i, keeping a bounded ring (depth 2-3)
of batches ALREADY on device.  Host decode, transfer, and compute then
fully overlap; the consumer's ``next()`` only ever waits when the input
path truly cannot keep up — and that wait is measured
(``PipelineStats.host_wait_ms``), not guessed.

Placement is mesh-aware: bound to a fused-mesh ``Module``, each input
is placed with the group's ``NamedSharding`` (``device_put`` splits the
host array into per-device shards directly — no host-side concat, no
intermediate single-device copy), so ``Module.fit``'s own ``_stage``
becomes a no-op on already-resident arrays and the trained parameters
stay BITWISE equal to an unprefetched run.

One source class opts OUT of background staging: an iterator whose
delivery launches a collective device program (``ShardedCachedDataset``
— its dp-sharded gather all-gathers rows across shards) advertises
``background_pull_safe = False``, and the loader pulls it on the
consumer thread instead.  Collectives must enqueue in program order on
every device; racing the training step's collectives from a stager
thread interleaves the per-device rendezvous — a deadlock on XLA:CPU
and a cross-host ordering hazard on a real pod.  Nothing is lost: the
gather output is already device-resident, so there is no transfer for
the ring to hide.  With ``batch_group=K`` the
stager assembles K iterator batches into one contiguous ``(K, B, ...)``
host block and stages it through the group's shared ``stage_stacked``
helper — one transfer per K steps, the grouped train program consumes
the block without re-staging.
"""
from __future__ import annotations

import threading
import time

import numpy as onp

from ..base import MXNetError
from .. import faults as _faults
from .. import ndarray as nd
from ..io import DataBatch, DataIter
from .stats import PipelineStats

__all__ = ["DeviceLoader"]

_END = object()


def _host_value(arr):
    return arr._read() if hasattr(arr, "_read") else arr


def _batch_wire_stats(batches):
    """(bytes, dtype) a group of batches puts on the transport: the
    sum of every HOST array's nbytes (a device-resident array — e.g.
    a CachedDataset gather output — passes through ``device_put``
    without a transfer and counts 0), and the IMAGE (first data
    entry) dtype — uint8 on the u8 wire path, float32 on the classic
    host-assemble path."""
    total = 0
    for b in batches:
        for a in b.data:
            v = _host_value(a)
            if isinstance(v, onp.ndarray):
                total += int(v.nbytes)
    first = _host_value(batches[0].data[0])
    return total, getattr(first, "dtype", None)


class DeviceLoader(DataIter):
    """Wrap ``data_iter`` so every delivered batch is device-resident.

    Parameters
    ----------
    data_iter : DataIter
        Host-side source (NDArrayIter, ImageRecordIter, a
        :class:`TransformIter`, ...).  Pulled from the stager thread
        only.
    module : Module, optional
        A BOUND module: its executor group supplies the target
        shardings (batch inputs on the ``dp`` axis; ``(K, B, ...)``
        blocks through ``stage_stacked``).  Without a module, batches
        are placed whole on the default device — fine for a single
        device, wrong for a mesh.
    depth : int
        Ring bound: maximum batches resident on device at once
        (2-3 is the sweet spot — enough to hide one transfer behind
        one step without tying up HBM).
    batch_group : int, optional
        Stage blocks of K batches through ``stage_stacked`` for
        ``fit(batch_group=K)`` — one transfer and one scanned program
        per K steps.  The epoch tail forms a final smaller block.
    stats : PipelineStats, optional
        Shared counter block; a fresh one is created by default and
        exposed as ``.pipeline_stats`` (``Speedometer`` and the fit
        epoch log read it from there).
    close_source : bool
        Also close ``data_iter`` (when it has a ``close``) from this
        loader's ``close()``.  Default False: the loader does not own
        an iterator the caller built — ``fit(prefetch_to_device=)``
        closes only the loader it created, never the caller's
        iterator.
    """

    def __init__(self, data_iter, module=None, depth=2, batch_group=None,
                 stats=None, close_source=False, restart_on_error=None):
        super().__init__(getattr(data_iter, "batch_size", 0))
        depth = int(depth)
        if depth < 1:
            raise MXNetError("depth must be >= 1 (got %d)" % depth)
        if restart_on_error is None:
            import os
            restart_on_error = os.environ.get(
                "MXNET_FAULT_STAGER_RESTART", "0") == "1"
        # error-propagation contract: a stager error is always
        # delivered IN ORDER on the consumer thread; by default the
        # epoch is then over (reset() recovers). With
        # ``restart_on_error`` the stager instead relaunches after the
        # delivery, so a consumer that catches the error keeps
        # iterating the surviving stream (the chaos-soak posture).
        self._restart_on_error = bool(restart_on_error)
        group = int(batch_group) if batch_group else 0
        if group == 1:
            group = 0
        self._iter = data_iter
        self._depth = depth
        self._group = group
        self._close_source = bool(close_source)
        self._owns_stats = stats is None
        self.pipeline_stats = stats or PipelineStats(ring_depth=depth)
        self.pipeline_stats.ring_depth = depth
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self._data_names = [d[0] for d in self.provide_data]
        self._label_names = [d[0] for d in (self.provide_label or [])]

        self._group_handle = None
        if module is not None:
            grp = getattr(module, "_exec_group", None)
            if grp is None or not getattr(grp, "fused", False):
                # classic per-executor groups slice the batch per
                # context host-side; background-staging whole batches
                # would be wasted work there
                module = None
            else:
                self._group_handle = grp
        self._module = module
        # wire-format attribution: where the augment stage runs for
        # batches staged through this loader, and (set per stage) what
        # dtype crossed the transport
        grp = self._group_handle
        self.pipeline_stats.augment_placement = \
            "device" if grp is not None and \
            getattr(grp, "_device_augment", None) else \
            getattr(data_iter, "augment_placement", None) or "host"
        # u8 pipelines advertise their spec; forward it so a manually
        # built DeviceLoader can still be handed straight to fit()
        self.device_augment_spec = getattr(data_iter,
                                           "device_augment_spec", None)

        # a source whose delivery launches COLLECTIVE device programs
        # (ShardedCachedDataset's dp-sharded gather) must be pulled on
        # the CONSUMER thread: collectives enqueue in program order on
        # every device, and a background launch racing the training
        # step's collectives can interleave the per-device rendezvous
        # (deadlock on XLA:CPU, ordering hazard on a pod).  Such
        # batches are already device-resident — there is no transfer
        # for the ring to hide — so the loader degrades to a
        # pass-through that still keeps the stats wire.
        self._passthrough = not getattr(data_iter,
                                        "background_pull_safe", True)
        self._cond = threading.Condition()
        self._ring = []          # staged entries, delivery order
        self._closed = False
        self._stager = None
        self._start_epoch(reset_source=False)

    # -- staging -------------------------------------------------------
    def _stage_batch(self, batch):
        """Place one host batch on device, preserving the exact bytes
        ``MeshExecutorGroup._stage`` would transfer."""
        import jax
        grp = self._group_handle
        sharding = grp._batch_sharding if grp is not None else None

        def put(arr):
            v = _host_value(arr)
            if _faults.armed():
                # transient transfer fault: healed by the shared
                # bounded-backoff retry — the SAME bytes land on
                # retry, so trained params stay bitwise identical.
                # The retry scaffolding lives under the armed branch:
                # unarmed staging pays one branch, nothing more.
                def attempt():
                    _faults.check("data.device_put")
                    if sharding is not None:
                        return jax.device_put(v, sharding)
                    return jax.device_put(v)
                return _faults.retry(attempt, site="data.device_put")
            if sharding is not None:
                return jax.device_put(v, sharding)
            return jax.device_put(v)

        data = [nd.NDArray(put(d)) for d in batch.data]
        label = None
        if batch.label:
            label = [None if lb is None else nd.NDArray(put(lb))
                     for lb in batch.label]
        return DataBatch(data=data, label=label, pad=batch.pad,
                         index=batch.index,
                         provide_data=batch.provide_data,
                         provide_label=batch.provide_label)

    def _stage_block(self, batches):
        """K host batches -> ONE contiguous (K, B, ...) block per input,
        staged through the group's ``stage_stacked`` (one ``device_put``
        per input).  Delivered as per-batch views onto the block, each
        carrying the staged dict so ``Module._grouped_step`` can hand
        the block straight to the scanned program."""
        from ..module.base_module import stack_group_inputs
        # default stacking rule: all-host batches form ONE contiguous
        # numpy block (single device_put), device-resident batches
        # (CachedDataset gathers) stack with jnp ON DEVICE — an
        # onp.stack there would be K blocking readbacks
        stacked = stack_group_inputs(
            batches, self._data_names, self._label_names)
        if _faults.armed():
            def attempt():
                _faults.check("data.device_put", group=len(batches))
                return self._group_handle.stage_stacked(stacked)
            staged = _faults.retry(attempt, site="data.device_put")
        else:
            staged = self._group_handle.stage_stacked(stacked)
        out = []
        for j, b in enumerate(batches):
            # augmented groups: stage_stacked consumed the wire param
            # arrays and replaced the u8 block with the f32 model view
            # — the views carry whatever inputs the staged block kept
            data = [nd.NDArray(staged[n][j]) for n in self._data_names
                    if n in staged]
            label = None
            if b.label:
                label = [nd.NDArray(staged[n][j]) if n in staged
                         else b.label[i]
                         for i, n in enumerate(self._label_names)
                         if i < len(b.label)]
            view = DataBatch(data=data, label=label, pad=b.pad,
                             index=b.index)
            view._staged_block = staged
            view._staged_index = j
            view._staged_size = len(batches)
            out.append(view)
        return out

    def _stage_entry(self):
        """Pull + stage the next ring entry (a list of delivered
        batches).  Returns _END at epoch end, an exception to re-raise
        in order, or the staged batches."""
        from .. import telemetry
        if _faults.armed():
            # stager-crash seam: raises BEFORE any source pull, so a
            # restarted stager resumes the stream with nothing lost.
            # Transient kinds heal in place through the shared retry;
            # permanent kinds escape to the consumer as the crash.
            _faults.retry(
                lambda: _faults.check("data.stager", group=self._group),
                site="data.stager")
        if self._group:
            pulled = []
            for _ in range(self._group):
                try:
                    pulled.append(self._iter.next())
                except StopIteration:
                    break
            if not pulled:
                return _END
            nbytes, dtype = _batch_wire_stats(pulled)
            t0 = time.perf_counter()
            with telemetry.span("data.stage_block", k=len(pulled)):
                if self._group_handle is not None and len(pulled) > 0 and \
                        self._uniform_shapes(pulled):
                    staged = self._stage_block(pulled)
                else:
                    staged = [self._stage_batch(b) for b in pulled]
            rows = sum(b.data[0].shape[0] for b in staged)
            self.pipeline_stats.note_staged(rows, time.perf_counter() - t0,
                                            nbytes, dtype)
            return staged
        try:
            batch = self._iter.next()
        except StopIteration:
            return _END
        nbytes, dtype = _batch_wire_stats([batch])
        t0 = time.perf_counter()
        with telemetry.span("data.stage"):
            staged = self._stage_batch(batch)
        self.pipeline_stats.note_staged(staged.data[0].shape[0],
                                        time.perf_counter() - t0,
                                        nbytes, dtype)
        return [staged]

    @staticmethod
    def _uniform_shapes(batches):
        """A block must stack; ragged shapes (bucketed iterators) fall
        back to per-batch staging — fit's grouped loop flushes on the
        shape change anyway."""
        def sig(b):
            s = [tuple(d.shape) for d in b.data]
            for lb in (b.label or []):
                s.append(tuple(lb.shape) if lb is not None else None)
            return s

        first = sig(batches[0])
        return all(sig(b) == first for b in batches[1:])

    # -- stager thread -------------------------------------------------
    def _run_stager(self, epoch):
        while True:
            with self._cond:
                while not self._stop and len(self._ring) >= self._depth:
                    if not self._noted_full:
                        self._noted_full = True
                        self.pipeline_stats.note_ring_full()
                    self._cond.wait(0.05)
                if self._stop:
                    return
                self._noted_full = False
            try:
                entry = self._stage_entry()
            except Exception as exc:  # noqa: BLE001 — re-raised in order
                entry = exc
            with self._cond:
                if self._stop or epoch != self._live_epoch:
                    return
                self._ring.append(entry)
                self.pipeline_stats.note_ring(len(self._ring))
                self._cond.notify_all()
                if entry is _END or isinstance(entry, BaseException):
                    return

    def _start_epoch(self, reset_source):
        self._stop_stager()
        if reset_source:
            self._iter.reset()
        with self._cond:
            self._ring = []
            self._pending = []   # staged batches popped but undelivered
            self._stop = False
            self._exhausted = False
            self._noted_full = False
            self._live_epoch = getattr(self, "_live_epoch", -1) + 1
        if not reset_source:
            # construction: start pre-filling right away.  After a
            # reset() the stager restarts LAZILY on the first next():
            # an eager restart would pull batches from the source that
            # a close() (e.g. fit's, after the final epoch's reset)
            # silently drops — the caller's iterator must come out of
            # a prefetched fit in the same state a plain fit leaves it
            self._launch_stager()

    def _launch_stager(self):
        if self._stager is not None:
            return
        if not self._passthrough and \
                not getattr(self._iter, "background_pull_safe", True):
            # re-evaluated at every (lazy, per-epoch) launch, not just
            # construction: a sharded cache built against a module that
            # binds AFTER the loader flips unsafe once its collective
            # gather exists — a stale construction-time snapshot would
            # background exactly the launch this protocol serializes
            self._passthrough = True
        if self._passthrough:
            return
        with self._cond:
            epoch = self._live_epoch
        self._stager = threading.Thread(
            target=self._run_stager, args=(epoch,),
            name="mxtpu-device-stager", daemon=True)
        self._stager.start()

    def _restart_stager(self):
        """Recover from a delivered stager error: join the (already
        returned) stager thread and rebase the epoch tag so a fresh
        stager relaunches on the next ``next()``, continuing the
        source stream from where the crash left it."""
        from .. import telemetry
        self._stop_stager()
        with self._cond:
            self._stop = False
            self._exhausted = False
            self._noted_full = False
            self._live_epoch += 1
        telemetry.registry().counter("data.stager_restarts").add()

    def _stop_stager(self):
        stager = self._stager
        if stager is None:
            return
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        stager.join()
        self._stager = None
        with self._cond:
            self._ring = []
            self._pending = []

    # -- DataIter surface ----------------------------------------------
    def _next_passthrough(self):
        """Consumer-thread pull for collective-gather sources: one
        batch through the normal staging rule (a no-op device_put for
        the already-resident gather output), with delivery/staging
        stats kept so the pipeline wire reads the same."""
        t0 = time.perf_counter()
        batch = self._iter.next()       # StopIteration ends the epoch
        nbytes, dtype = _batch_wire_stats([batch])
        t1 = time.perf_counter()
        staged = self._stage_batch(batch)
        self.pipeline_stats.note_staged(staged.data[0].shape[0],
                                        time.perf_counter() - t1,
                                        nbytes, dtype)
        self.pipeline_stats.note_delivered(staged.data[0].shape[0],
                                           t1 - t0)
        return staged

    def next(self):
        if self._closed:
            raise MXNetError("DeviceLoader is closed")
        if self._passthrough:
            return self._next_passthrough()
        if self._stager is None:
            self._launch_stager()
            if self._passthrough:
                # the lazy launch just re-evaluated the source's
                # background_pull_safe and flipped to pass-through (a
                # cache finalized with a collective gather since the
                # last epoch): route there instead of waiting on a
                # ring no stager will ever fill
                return self._next_passthrough()
        if self._pending:
            batch = self._pending.pop(0)
            self.pipeline_stats.note_delivered(batch.data[0].shape[0],
                                               0.0)
            return batch
        t0 = time.perf_counter()
        with self._cond:
            if self._exhausted:
                # the stager exited at epoch end (or on an error it
                # already delivered) — keep raising StopIteration like
                # every DataIter does until reset(), instead of waiting
                # on a ring that can never refill
                raise StopIteration
            while not self._ring:
                if self._stop:
                    raise MXNetError("DeviceLoader was reset/closed "
                                     "while a next() was blocked")
                self._cond.wait(0.05)
            entry = self._ring.pop(0)
            if entry is _END or (isinstance(entry, BaseException)
                                 and not self._restart_on_error):
                self._exhausted = True
            self.pipeline_stats.note_ring(len(self._ring))
            self._cond.notify_all()
        wait = time.perf_counter() - t0
        if entry is _END:
            raise StopIteration
        if isinstance(entry, BaseException):
            if self._restart_on_error:
                # the stager exited when it delivered this error; join
                # it and relaunch LAZILY so a consumer that catches the
                # error keeps iterating the surviving stream
                self._restart_stager()
            raise entry
        batch = entry[0]
        self._pending = list(entry[1:])
        self.pipeline_stats.note_delivered(batch.data[0].shape[0], wait)
        return batch

    def iter_next(self):
        try:
            self._current = self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._current.data

    def getlabel(self):
        return self._current.label

    def getpad(self):
        return self._current.pad

    def getindex(self):
        return self._current.index

    def _note_cache_stats(self):
        """Forward the source dataset-cache's resolved tier/bytes into
        the pipeline stats (once it finalizes) — the watchdog and
        bench then read the same wire the cache resolved."""
        info_fn = getattr(self._iter, "cache_info", None)
        if info_fn is None:
            return
        try:
            info = info_fn()
        except Exception:  # noqa: BLE001 — attribution, never delivery
            return
        if info.get("tier"):
            self.pipeline_stats.note_cache(
                info["tier"],
                info.get("shard_bytes", info.get("bytes", 0)),
                info.get("rows", 0))

    def reset(self):
        """Rewind for a fresh epoch: cancel+join the stager and reset
        the source; the stager restarts lazily on the next ``next()``,
        so a reset consumes NOTHING from the source.  Repeatedly
        callable; never delivers a stale pre-reset batch."""
        if self._closed:
            raise MXNetError("DeviceLoader is closed")
        self._start_epoch(reset_source=True)
        # a CachedDataset/ShardedCachedDataset source finalizes its
        # cache inside its reset(): pick up the resolved tier now
        self._note_cache_stats()

    def set_epoch(self, epoch):
        """Forward ``fit``'s epoch-coordinate pin to the source (the
        seeded-stream iterators: DeviceAugmentIter, CachedDataset,
        ShardedDataIter).  A no-op when the source is already at
        ``epoch`` — the construction-time prefill stays valid; a real
        rebase cancels the stager and drops any batches staged under
        the stale coordinate (the stager restarts lazily)."""
        if self._closed:
            raise MXNetError("DeviceLoader is closed")
        fwd = getattr(self._iter, "set_epoch", None)
        if fwd is None:
            return
        self._note_cache_stats()
        coord = getattr(self._iter, "epoch_coord", None)
        if coord is None:
            # coordinate-less wrapper (e.g. a PrefetchingIter over
            # non-pinnable sources): its set_epoch is a no-op by the
            # protocol contract (sources that ACT on set_epoch expose
            # epoch_coord), so forward the pin without paying a rebase
            # — dropping the ring every epoch would defeat the prefill
            fwd(epoch)
            return
        if coord == int(epoch):
            return
        self._stop_stager()
        # the dropped ring batches were already PULLED from the source
        # under the stale coordinate — rewind it before pinning, or the
        # rebased epoch would start short by the prefilled batches
        self._iter.reset()
        fwd(epoch)
        with self._cond:
            self._ring = []
            self._pending = []
            self._stop = False
            self._exhausted = False
            self._noted_full = False
            self._live_epoch += 1

    # -- lifecycle -----------------------------------------------------
    def close(self):
        """Stop and join the stager thread, dropping the ring
        (idempotent).  The source iterator is left usable unless the
        loader was built with ``close_source=True``."""
        if self._closed:
            return
        self._closed = True
        self._stop_stager()
        if self._owns_stats:
            # this loader created the stats: retire their registry
            # scope so fit-per-call workloads don't grow the registry
            # unboundedly (the object stays readable for post-mortems)
            self.pipeline_stats.release()
        if self._close_source:
            inner_close = getattr(self._iter, "close", None)
            if callable(inner_close):
                inner_close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
