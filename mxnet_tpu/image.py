"""Image iterators + augmentations (python/mxnet/image.py:559 and the C++
augmenter chain src/io/image_aug_default.cc).

Decode uses PIL (cv2 when present); augmentation math is numpy; the batch
assembly hot loop (normalize/mirror/crop, HWC→CHW) runs in the native
OpenMP runtime (runtime/recordio.cpp assemble_batch).
"""
from __future__ import annotations

import io as _pyio
import logging
import os
import random
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as onp

from . import ndarray as nd
from . import recordio
from .io import DataIter, DataBatch, DataDesc
from . import runtime

__all__ = ["imdecode", "scale_down", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "ResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "HorizontalFlipAug",
           "ColorNormalizeAug", "CastAug", "CreateAugmenter", "ImageIter",
           "ImageRecordIter"]


def imdecode(buf, to_rgb=True):
    """Decode image bytes to a HWC uint8 numpy array."""
    try:
        import cv2
        img = cv2.imdecode(onp.frombuffer(buf, dtype=onp.uint8), 1)
        if to_rgb:
            img = img[:, :, ::-1]
        return img
    except ImportError:
        from PIL import Image
        img = onp.asarray(Image.open(_pyio.BytesIO(bytes(buf))).convert("RGB"))
        if not to_rgb:
            img = img[:, :, ::-1]
        return img


def _resize(img, w, h):
    try:
        import cv2
        return cv2.resize(img, (w, h), interpolation=cv2.INTER_LINEAR)
    except ImportError:
        from PIL import Image
        return onp.asarray(Image.fromarray(img).resize((w, h),
                                                       Image.BILINEAR))


def scale_down(src_size, size):
    """Scale size down to fit in src_size (image.py scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size):
    """Resize so the shorter edge == size."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return _resize(src, new_w, new_h)


def fixed_crop(src, x0, y0, w, h, size=None):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _resize(out, size[0], size[1])
    return out


def random_crop(src, size):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src.astype(onp.float32) - mean
    if std is not None:
        src = src / std
    return src


def random_size_crop(src, size, min_area=0.08, ratio=(3.0 / 4.0, 4.0 / 3.0)):
    """Random area+aspect crop (GoogLeNet-style, image.py random_size_crop)."""
    h, w = src.shape[:2]
    area = h * w
    for _ in range(10):
        new_area = random.uniform(min_area, 1.0) * area
        new_ratio = random.uniform(*ratio)
        new_w = int(round((new_area * new_ratio) ** 0.5))
        new_h = int(round((new_area / new_ratio) ** 0.5))
        if random.random() < 0.5:
            new_w, new_h = new_h, new_w
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            return fixed_crop(src, x0, y0, new_w, new_h, size), \
                (x0, y0, new_w, new_h)
    return center_crop(src, size)


# -- augmenter functors (image.py CreateAugmenter building blocks) ----------
def ResizeAug(size):
    def aug(src):
        return resize_short(src, size)
    return aug


def RandomCropAug(size):
    def aug(src):
        return random_crop(src, size)[0]
    return aug


def RandomSizedCropAug(size, min_area=0.08, ratio=(3. / 4., 4. / 3.)):
    def aug(src):
        return random_size_crop(src, size, min_area, ratio)[0]
    return aug


def CenterCropAug(size):
    def aug(src):
        return center_crop(src, size)[0]
    return aug


def HorizontalFlipAug(p=0.5):
    def aug(src):
        if random.random() < p:
            return src[:, ::-1]
        return src
    return aug


def ColorNormalizeAug(mean, std=None):
    def aug(src):
        return color_normalize(src, mean, std)
    return aug


def CastAug():
    def aug(src):
        return src.astype(onp.float32)
    return aug


def BrightnessJitterAug(brightness):
    def aug(src):
        alpha = 1.0 + random.uniform(-brightness, brightness)
        return onp.clip(src.astype(onp.float32) * alpha, 0, 255)
    return aug


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, **kwargs):
    """Build the standard augmenter list (image.py CreateAugmenter)."""
    auglist = []
    size = (data_shape[2], data_shape[1])
    if resize > 0:
        auglist.append(ResizeAug(resize))
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(size))
    elif rand_crop:
        auglist.append(RandomCropAug(size))
    else:
        auglist.append(CenterCropAug(size))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53])
    if std is True:
        std = onp.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(CastAug())
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Pure-python image iterator over .lst/imglist or RecordIO
    (python/mxnet/image.py ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 shuffle=False, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        if path_imgrec:
            self.rec = runtime.RecordFile(path_imgrec)
            self.imglist = None
            self.seq = list(range(len(self.rec)))
        else:
            self.rec = None
            if path_imglist:
                imglist = []
                with open(path_imglist) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        label = onp.array([float(x) for x in parts[1:-1]],
                                          dtype=onp.float32)
                        imglist.append((label, parts[-1]))
            else:
                imglist = [(onp.array([float(x[0])], dtype=onp.float32), x[1])
                           for x in imglist]
            self.imglist = imglist
            self.path_root = path_root or ""
            self.seq = list(range(len(imglist)))

        self.shuffle = shuffle
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.aug_list = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **kwargs)
        self.cur = 0
        self.data_name = data_name
        self.label_name = label_name
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc(label_name, (batch_size, label_width)
                                       if label_width > 1 else (batch_size,))]
        self.reset()

    def reset(self):
        if self.shuffle:
            random.shuffle(self.seq)
        self.cur = 0

    def next_sample(self):
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.rec is not None:
            header, img_bytes = recordio.unpack(self.rec.read(idx))
            label = header.label
            img = imdecode(img_bytes)
            return label, img
        label, fname = self.imglist[idx]
        with open(os.path.join(self.path_root, fname), "rb") as f:
            img = imdecode(f.read())
        return label, img

    def next(self):
        c, h, w = self.data_shape
        batch_data = onp.zeros((self.batch_size, c, h, w), onp.float32)
        batch_label = onp.zeros((self.batch_size, self.label_width),
                                onp.float32)
        i = 0
        while i < self.batch_size:
            try:
                label, img = self.next_sample()
            except StopIteration:
                if i == 0:
                    raise
                break
            for aug in self.aug_list:
                img = aug(img)
            batch_data[i] = onp.asarray(img, onp.float32).transpose(2, 0, 1)
            batch_label[i] = onp.atleast_1d(label)[:self.label_width]
            i += 1
        pad = self.batch_size - i
        label_out = batch_label if self.label_width > 1 else \
            batch_label[:, 0]
        return DataBatch([nd.array(batch_data)], [nd.array(label_out)],
                         pad=pad)


def _decode_resize_crop(img_bytes, resize, th, tw, pick_crop):
    """Shared record-payload -> cropped uint8 HWC pipeline (thread and
    process decode paths must never diverge). ``pick_crop(h, w)`` ->
    (y0, x0) supplies the crop geometry."""
    if img_bytes[:6] == b"\x93NUMPY":
        # raw (uncompressed) payload from pack_img's npy fallback /
        # im2rec --encoding .npy: decode is a buffer view, the mode
        # for hosts where JPEG decode can't keep up with the chip
        img = onp.load(_pyio.BytesIO(bytes(img_bytes)), allow_pickle=False)
    else:
        img = imdecode(img_bytes)
    if resize > 0:
        img = resize_short(img, resize)
    h, w = img.shape[:2]
    if h < th or w < tw:
        img = _resize(img, max(tw, w), max(th, h))
        h, w = img.shape[:2]
    y0, x0 = pick_crop(h, w)
    return img[y0:y0 + th, x0:x0 + tw]


def _proc_worker_init(path):
    global _PROC_REC
    _PROC_REC = runtime.RecordFile(path)


def _proc_decode_one(args):
    """Decode+resize+crop one record in a worker process (uint8 HWC out).

    Crop geometry uses a per-record deterministic rng seeded from
    (seed, idx, epoch) — processes cannot share the parent's rng stream,
    and folding the epoch keeps crops varying across epochs."""
    idx, resize, th, tw, rand_crop, seed = args
    header, img_bytes = recordio.unpack(_PROC_REC.read(idx))

    def pick(h, w):
        if not rand_crop:
            return (h - th) // 2, (w - tw) // 2
        r = random.Random(seed ^ (idx * 2654435761 & 0xffffffff))
        return r.randint(0, h - th), r.randint(0, w - tw)

    img = _decode_resize_crop(img_bytes, resize, th, tw, pick)
    return img, onp.atleast_1d(header.label)


class ImageRecordIter(DataIter):
    """RecordIO image iterator with threaded decode + native batch assembly
    (src/io/iter_image_recordio_2.cc ImageRecordIter).

    Decode runs on a thread pool (PIL/cv2 release the GIL) or, with
    ``preprocess_processes=N``, on a process pool (for hosts where decode
    is GIL/core-bound — the reference's decode farm,
    iter_image_recordio_2.cc). Augmentation geometry is chosen
    per-sample; the normalize/mirror/transpose hot loop either runs in
    the native OpenMP runtime (host path) or, with
    ``device_augment=True``, on the accelerator: the batch ships as
    uint8 NHWC (4x fewer bytes over PCIe/tunnel than f32 CHW) and ONE
    jitted program does mirror+normalize+transpose device-side —
    the TPU-native replacement for iter_normalize.h. Wrap with
    PrefetchingIter (io.py) for background double-buffering like the
    reference's PrefetcherIter.

    ``device_augment="defer"`` goes one step further: the iterator
    emits raw uint8 NHWC wire batches plus deterministic per-batch
    augment-parameter draws and exposes ``device_augment_spec`` — the
    bound module then runs pad/crop/mirror/normalize as its own
    compiled device program at staging time
    (``mxnet_tpu.data.DeviceAugment``; kept separate from the train
    step so the step program's numerics stay bitwise-identical to the
    host-reference path), so random crop (``augment_pad``) composes
    with ``cache_decoded`` and draws replay across resume.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, scale=1.0, resize=-1, preprocess_threads=4,
                 preprocess_processes=0, device_augment=False,
                 augment_pad=0, cache_decoded=False, round_batch=True,
                 data_name="data", label_name="softmax_label", seed=0,
                 **kwargs):
        super().__init__(batch_size)
        self.rec = runtime.RecordFile(path_imgrec)
        self._path_imgrec = path_imgrec
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.mean = onp.array([mean_r, mean_g, mean_b], onp.float32)
        self.std = onp.array([std_r, std_g, std_b], onp.float32)
        self.scale = scale
        self.resize = resize
        self.round_batch = round_batch
        self.seed = seed
        self.rng = random.Random(seed)
        self.device_augment = device_augment
        self._device_fn = None
        # device_augment="defer": do NOT augment here at all — emit raw
        # uint8 NHWC wire batches plus the per-batch augment-parameter
        # draws of a DeviceAugment spec, and let the bound module
        # compile crop/mirror/normalize INTO the train-step program
        # (fit adopts device_augment_spec).  Decode geometry is then
        # always deterministic (center), so it composes with
        # cache_decoded AND rand_crop: crop randomness comes from the
        # in-program pad+crop (augment_pad), not from decode.
        self._defer = device_augment == "defer"
        self._aug_spec = None
        self._batch_seq = 0
        if self._defer:
            from .data.augment import DeviceAugment
            c, th, tw = self.data_shape
            if rand_crop and not augment_pad:
                # decode geometry is deterministic in defer mode; with
                # no pad the in-program crop window is 0x0 — rand_crop
                # would silently become a center crop
                raise ValueError(
                    "rand_crop with device_augment='defer' needs "
                    "augment_pad>0: crop randomness comes from the "
                    "in-program pad-and-crop, not from decode")
            self._aug_spec = DeviceAugment(
                (c, th, tw), rand_crop=rand_crop,
                rand_mirror=rand_mirror, pad=augment_pad,
                mean=self.mean, std=self.std, scale=scale, seed=seed)
            self.device_augment_spec = {data_name: self._aug_spec}
        elif augment_pad:
            raise ValueError(
                "augment_pad is the in-program pad-and-crop knob; it "
                "needs device_augment='defer'")
        if preprocess_processes > 0:
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor
            # spawn, not fork: the parent typically holds an initialized
            # JAX/TPU client whose threads/state must not be forked
            self.pool = ProcessPoolExecutor(
                max_workers=preprocess_processes,
                mp_context=multiprocessing.get_context("spawn"),
                initializer=_proc_worker_init, initargs=(path_imgrec,))
            self._proc_mode = True
        else:
            self.pool = ThreadPoolExecutor(max_workers=preprocess_threads)
            self._proc_mode = False
        # RAM-cached decoded mode: JPEG decode is the host's bottleneck
        # (it runs once per image per EPOCH on the streaming path), but
        # the decoded geometry is deterministic when rand_crop is off —
        # so decode each image exactly ONCE into a uint8 NHWC cache and
        # serve every later batch as a fancy-index gather (memcpy-rate)
        # + uint8 transfer.  This is the iterator shape that feeds a
        # chip at compute rate from a modest host: per-epoch cost drops
        # from decode (~ms/img/core) to gather+DMA (~µs/img).  Memory:
        # N*H*W*C bytes host RAM (caller's tradeoff).  rand_mirror still
        # applies per draw (it acts on the gathered batch); rand_crop
        # needs fresh geometry per epoch and is rejected.
        self.cache_decoded = cache_decoded
        self._cache = None
        if cache_decoded and rand_crop and not self._defer:
            raise ValueError(
                "cache_decoded caches one deterministic decode per "
                "image; rand_crop needs fresh geometry every epoch — "
                "use the streaming path for random-crop training, or "
                "device_augment='defer' (crop runs in-program)")
        self.seq = list(range(len(self.rec)))
        self.cur = 0
        # NOTE on staging: each batch gets a FRESH host buffer. A pooled
        # double-buffer ring (iter_prefetcher.h pattern) was tried and
        # reverted: jax.device_put zero-copies 64-byte-aligned host arrays
        # onto the CPU jax device, so a recycled buffer would alias any
        # still-live batch NDArray (and downstream TPU transfers read the
        # alias asynchronously). runtime.core.HostPool remains available
        # (and assemble_batch takes ``out=``) for callers that own the
        # buffer lifetime end-to-end.
        # decode-time crop geometry: random only on the host-augment
        # streaming path; "defer" decodes deterministically (the
        # in-program pad+crop supplies the randomness)
        self._decode_rand_crop = bool(rand_crop) and not self._defer
        if self._defer:
            self.provide_data = self._aug_spec.data_descs(data_name,
                                                          batch_size)
        else:
            self.provide_data = [DataDesc(data_name,
                                          (batch_size,) + self.data_shape)]
        self._data_name = data_name
        self.provide_label = [DataDesc(label_name, (batch_size, label_width)
                                       if label_width > 1 else (batch_size,))]
        self.reset()

    def reset(self):
        self._epoch = getattr(self, "_epoch", -1) + 1
        self._reshuffle()
        self.cur = 0
        self._batch_seq = 0

    def _reshuffle(self):
        """Epoch k's order is a pure function of ``(seed, k)`` —
        re-drawn from the FIXED base order, never cumulatively — so
        ``set_epoch(k)`` replays it exactly regardless of how many
        resets this process has seen (the resume-replay contract; a
        cumulative ``rng.shuffle`` would depend on the reset COUNT)."""
        if not self.shuffle:
            return
        from .data.augment import fold_seed
        rs = onp.random.RandomState(
            fold_seed(self.seed ^ 0x5bd1e995, self._epoch, 0))
        self.seq = list(range(len(self.rec)))
        rs.shuffle(self.seq)

    def set_epoch(self, epoch):
        """Pin the epoch coordinate (the resume-replay contract).

        Both the deferred-augment draws and the shuffle order are
        pure functions of the pinned coordinate, so a resumed fit
        replays the uninterrupted run's stream exactly."""
        self._epoch = int(epoch)
        self._batch_seq = 0
        self._reshuffle()

    @property
    def epoch_coord(self):
        return self._epoch

    def _decode_one(self, idx):
        header, img_bytes = recordio.unpack(self.rec.read(idx))
        c, th, tw = self.data_shape

        def pick(h, w):
            if not self._decode_rand_crop:
                return (h - th) // 2, (w - tw) // 2
            return self.rng.randint(0, h - th), self.rng.randint(0, w - tw)

        img = _decode_resize_crop(img_bytes, self.resize, th, tw, pick)
        return img, onp.atleast_1d(header.label)

    def _device_preprocess(self, imgs_u8, mirror):
        """uint8 NHWC batch -> normalized f32 NCHW, entirely on device.

        The transfer is the uint8 batch (4x smaller than the host path's
        f32 NCHW); mirror/normalize/transpose are one jitted program that
        XLA fuses — matching the host assemble_batch numerics exactly:
        out = (x - mean) / (std / scale)."""
        import jax

        if self._device_fn is None:
            import jax.numpy as jnp
            mean = self.mean
            std = self.std / self.scale

            def prep(x, mir):
                # XLA:TPU fuses a direct u8->f32 cast into the downstream
                # transpose as a byte-gather loop ~145x slower than the
                # i32-routed equivalent (7.3 s vs 50 ms on a
                # (128,224,224,3) batch, v5e; PERF.md "transport
                # pathologies") — route via i32
                xf = x.astype(jnp.int32).astype(jnp.float32)
                if mir is not None:
                    xf = jnp.where(mir[:, None, None, None] != 0,
                                   xf[:, :, ::-1, :], xf)
                xf = (xf - mean) / std
                return xf.transpose(0, 3, 1, 2)

            self._device_fn = jax.jit(prep)
        if mirror is None:
            fn = self._device_fn
            return fn(jax.device_put(imgs_u8), None)
        return self._device_fn(jax.device_put(imgs_u8),
                               jax.device_put(mirror))

    def _fill_cache(self):
        """Decode every record once (thread/process pool) into a uint8
        NHWC array + label array."""
        c, th, tw = self.data_shape
        n = len(self.rec)
        cache = onp.empty((n, th, tw, c), onp.uint8)
        lw = self.label_width
        labels = onp.empty((n, lw), onp.float32)
        all_idx = list(range(n))
        if self._proc_mode:
            ep_seed = self.seed
            work = [(i, self.resize, th, tw, False, ep_seed)
                    for i in all_idx]
            results = self.pool.map(_proc_decode_one, work, chunksize=16)
        else:
            results = self.pool.map(self._decode_one, all_idx)
        for i, (img, lab) in zip(all_idx, results):
            cache[i] = img
            labels[i] = lab[:lw]
        self._cache = (cache, labels)
        # the decode pool is never used again on this path
        self.pool.shutdown(wait=True)

    def next(self):
        if self.cur >= len(self.seq):
            raise StopIteration
        idxs = self.seq[self.cur:self.cur + self.batch_size]
        self.cur += self.batch_size
        pad = self.batch_size - len(idxs)
        if pad > 0:
            if self.round_batch:
                idxs = idxs + self.seq[:pad]
            else:
                pass
        if self.cache_decoded:
            if self._cache is None:
                self._fill_cache()
            cache, cl = self._cache
            imgs = cache[idxs]            # fancy-index gather: memcpy-rate
            labels = cl[idxs]
        elif self._proc_mode:
            c, th, tw = self.data_shape
            ep_seed = self.seed ^ (self._epoch * 0x9e3779b1 & 0xffffffff)
            work = [(i, self.resize, th, tw, self._decode_rand_crop,
                     ep_seed) for i in idxs]
            results = list(self.pool.map(_proc_decode_one, work,
                                         chunksize=4))
        else:
            results = list(self.pool.map(self._decode_one, idxs))
        if not self.cache_decoded:
            imgs = onp.stack([r[0] for r in results])
            labels = onp.stack([r[1] for r in results])
        label_out = labels if self.label_width > 1 else labels[:, 0]
        if self._defer:
            # raw uint8 NHWC wire batch + the spec's per-batch augment
            # parameter draws, keyed (seed, epoch, batch index) — the
            # bound program does crop/mirror/normalize in one fused
            # stage (4x fewer staged bytes than f32 NCHW)
            spec = self._aug_spec
            params = spec.draw(self._data_name, self._epoch,
                               self._batch_seq, imgs.shape[0])
            self._batch_seq += 1
            data = [imgs] + [
                params[d.name]
                for d in spec.param_descs(self._data_name,
                                          imgs.shape[0])]
            return DataBatch(data, [nd.array(label_out)], pad=pad)
        mirror = None
        if self.rand_mirror:
            mirror = onp.array(
                [self.rng.random() < 0.5 for _ in range(len(idxs))],
                onp.uint8)
        if self.device_augment:
            batch = nd.NDArray(self._device_preprocess(imgs, mirror))
        else:
            std = self.std / self.scale
            batch = nd.array(runtime.assemble_batch(imgs, mean=self.mean,
                                                    std=std, mirror=mirror))
        return DataBatch([batch], [nd.array(label_out)], pad=pad)


# detection pipeline lives in its own module; re-exported here so the
# reference surface (mx.image / the C-API iterator registry) finds it
from .image_det import DetAugmenter, DetLabel, ImageDetRecordIter  # noqa: E402,F401

__all__ += ["DetLabel", "DetAugmenter", "ImageDetRecordIter"]
