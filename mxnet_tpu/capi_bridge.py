"""Python side of the C ABI boundary (capi/c_api.cpp embeds CPython and
calls these). Each function takes/returns only simple types, NDArray/Symbol/
Executor objects (opaque handles on the C side), lists, and memoryviews —
the C++ layer owns handle lifetime, GIL transitions, buffer copies, and
error propagation (reference: src/c_api/c_api.cc over the C++ core; here
the "core" the C API fronts is the mxnet_tpu runtime itself).
"""
from __future__ import annotations

import numpy as onp

from . import ndarray as nd
from . import symbol as sym
from .context import Context
from .registry import get_op, list_ops

_DTYPE_CODE = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
               4: "int32", 5: "int8", 6: "int64"}
_CODE_DTYPE = {v: k for k, v in _DTYPE_CODE.items()}


def _ctx(dev_type, dev_id):
    # dev_type codes: 1=cpu, 2=gpu(=tpu here), 3=cpu_pinned (base.h Context)
    return Context({1: "cpu", 2: "tpu", 3: "cpu_pinned"}.get(dev_type, "cpu"),
                   dev_id)


# ------------------------------------------------------------------ ndarray
def ndarray_create(shape, dev_type, dev_id, dtype_code=0):
    return nd.zeros(tuple(int(s) for s in shape), ctx=_ctx(dev_type, dev_id),
                    dtype=_DTYPE_CODE[dtype_code])


def ndarray_shape(arr):
    return [int(s) for s in arr.shape]


def ndarray_dtype_code(arr):
    return _CODE_DTYPE.get(str(onp.dtype(arr.dtype)), 0)


def ndarray_context(arr):
    code = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3}
    return code.get(arr.context.device_type, 1), arr.context.device_id


def ndarray_copy_from(arr, mv):
    src = onp.frombuffer(mv, dtype=arr.dtype, count=int(arr.size))
    arr._write(src.reshape(arr.shape))


def ndarray_copy_to(arr):
    return onp.ascontiguousarray(arr.asnumpy()).tobytes()


def ndarray_save(fname, arrs, keys):
    nd.save(fname, dict(zip(keys, arrs)) if keys else list(arrs))


def ndarray_load(fname):
    data = nd.load(fname)
    if isinstance(data, dict):
        names = list(data.keys())
        return [data[n] for n in names], names
    return list(data), []


# ------------------------------------------------------------------ invoke
def imperative_invoke(op_name, inputs, keys, vals, out=None):
    op = get_op(op_name)
    res = nd.invoke(op, list(inputs), dict(zip(keys, vals)),
                    out=list(out) if out else None)
    return list(res) if isinstance(res, (list, tuple)) else [res]


def all_op_names():
    return list_ops()


# ------------------------------------------------------------------ symbol
def symbol_create_atomic(op_name, keys, vals):
    fn = getattr(sym, op_name)
    attrs = {k: v for k, v in zip(keys, vals)}
    name = attrs.pop("name", None)
    return fn(name=name, **attrs) if name else fn(**attrs)


def symbol_compose(s, name, keys, args):
    """nnvm Symbol::Compose semantics: for an atomic symbol, keyword names
    are the op's ARGUMENT names (data/weight/...); translate them to the
    implicit placeholder variables _create generated for the head node."""
    if keys:
        kwargs = dict(zip(keys, args))
        head = s._heads[0][0]
        if head.op is not None:
            argnames = head.op.list_arguments(head.attrs)
            trans = {}
            for (src, _), nm in zip(head.inputs, argnames):
                if src.op is None:
                    trans[nm] = src.name
            kwargs = {trans.get(k, k): v for k, v in kwargs.items()}
        s._compose(name=name or None, **kwargs)
    else:
        s._compose(*args, name=name or None)
    return s


def symbol_list(s, which):
    if which == "arguments":
        return s.list_arguments()
    if which == "outputs":
        return s.list_outputs()
    return s.list_auxiliary_states()


# ---------------------------------------------------------------- executor
def executor_bind(s, dev_type, dev_id, in_args, arg_grads, grad_reqs,
                  aux_states):
    ctx = _ctx(dev_type, dev_id)
    req_map = {0: "null", 1: "write", 2: "write", 3: "add"}
    arg_names = s.list_arguments()
    args = dict(zip(arg_names, in_args))
    grads = {n: g for n, g in zip(arg_names, arg_grads) if g is not None}
    reqs = {n: req_map[int(r)] for n, r in zip(arg_names, grad_reqs)}
    aux_names = s.list_auxiliary_states()
    return s.bind(ctx, args, args_grad=grads or None, grad_req=reqs,
                  aux_states=dict(zip(aux_names, aux_states)) or None)


def executor_forward(e, is_train):
    e.forward(is_train=bool(is_train))


def executor_backward(e, head_grads):
    e.backward(list(head_grads) if head_grads else None)


def executor_outputs(e):
    return list(e.outputs)


# ------------------------------------------------------------ predict API
class _Predictor(object):
    def __init__(self, json_str, param_blob, dev_type, dev_id,
                 input_names, input_shapes):
        import os
        import tempfile
        net = sym.load_json(json_str)
        params = {}
        if param_blob:
            fd, path = tempfile.mkstemp(suffix=".params")
            os.close(fd)
            try:
                with open(path, "wb") as f:
                    f.write(param_blob)
                loaded = nd.load(path)
            finally:
                os.unlink(path)
            for k, v in (loaded.items() if isinstance(loaded, dict) else []):
                # strip the arg:/aux: prefixes of save_checkpoint
                params[k.split(":", 1)[-1]] = v
        ctx = _ctx(dev_type, dev_id)
        shapes = dict(zip(input_names, [tuple(s) for s in input_shapes]))
        self.exe = net.simple_bind(ctx, grad_req="null", **shapes)
        for name, arr in self.exe.arg_dict.items():
            if name in params:
                params[name].copyto(arr)
        for name, arr in self.exe.aux_dict.items():
            if name in params:
                params[name].copyto(arr)
        self.input_names = list(input_names)

    def set_input(self, key, mv):
        arr = self.exe.arg_dict[key]
        ndarray_copy_from(arr, mv)

    def forward(self):
        self.exe.forward(is_train=False)

    def output_shape(self, index):
        return [int(s) for s in self.exe.outputs[index].shape]

    def output(self, index):
        return ndarray_copy_to(self.exe.outputs[index])


def pred_create(json_str, param_blob, dev_type, dev_id, input_names,
                input_shapes):
    return _Predictor(json_str, param_blob, dev_type, dev_id, input_names,
                      input_shapes)


# ------------------------------------------------------------------ global
def random_seed(s):
    from . import random as rnd
    rnd.seed(int(s))


def profiler_config(mode, filename):
    from . import profiler
    profiler.profiler_set_config(mode={0: "symbolic", 1: "all"}.get(mode,
                                                                    "all"),
                                 filename=filename)


def profiler_state(state):
    from . import profiler
    profiler.profiler_set_state({0: "stop", 1: "run"}.get(state, "stop"))


def profiler_dump():
    from . import profiler
    profiler.dump_profile()


def wait_all():
    nd.waitall()
