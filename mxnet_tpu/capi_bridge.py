"""Python side of the C ABI boundary (capi/c_api.cpp embeds CPython and
calls these). Each function takes/returns only simple types, NDArray/Symbol/
Executor objects (opaque handles on the C side), lists, and memoryviews —
the C++ layer owns handle lifetime, GIL transitions, buffer copies, and
error propagation (reference: src/c_api/c_api.cc over the C++ core; here
the "core" the C API fronts is the mxnet_tpu runtime itself).
"""
from __future__ import annotations

import numpy as onp

from . import ndarray as nd
from . import symbol as sym
from .context import Context
from .registry import get_op, list_ops

_DTYPE_CODE = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
               4: "int32", 5: "int8", 6: "int64"}
_CODE_DTYPE = {v: k for k, v in _DTYPE_CODE.items()}


def _ctx(dev_type, dev_id):
    # dev_type codes: 1=cpu, 2=gpu(=tpu here), 3=cpu_pinned (base.h Context)
    return Context({1: "cpu", 2: "tpu", 3: "cpu_pinned"}.get(dev_type, "cpu"),
                   dev_id)


# ------------------------------------------------------------------ ndarray
def ndarray_create(shape, dev_type, dev_id, dtype_code=0):
    return nd.zeros(tuple(int(s) for s in shape), ctx=_ctx(dev_type, dev_id),
                    dtype=_DTYPE_CODE[dtype_code])


def ndarray_shape(arr):
    return [int(s) for s in arr.shape]


def ndarray_dtype_code(arr):
    return _CODE_DTYPE.get(str(onp.dtype(arr.dtype)), 0)


def ndarray_context(arr):
    code = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3}
    return code.get(arr.context.device_type, 1), arr.context.device_id


def ndarray_copy_from(arr, mv):
    # MUST copy out of the foreign buffer: the ABI contract is a
    # synchronous copy (MXNDArraySyncCopyFromCPU), but _write defers
    # device materialization — a zero-copy frombuffer view would read the
    # caller's buffer after its stack frame (e.g. a C updater callback)
    # is gone.
    src = onp.frombuffer(mv, dtype=arr.dtype, count=int(arr.size)).copy()
    arr._write(src.reshape(arr.shape))


def ndarray_copy_to(arr):
    return onp.ascontiguousarray(arr.asnumpy()).tobytes()


def ndarray_save(fname, arrs, keys):
    nd.save(fname, dict(zip(keys, arrs)) if keys else list(arrs))


def ndarray_load(fname):
    data = nd.load(fname)
    if isinstance(data, dict):
        names = list(data.keys())
        return [data[n] for n in names], names
    return list(data), []


# ------------------------------------------------------------------ invoke
def imperative_invoke(op_name, inputs, keys, vals, out=None):
    op = get_op(op_name)
    res = nd.invoke(op, list(inputs), dict(zip(keys, vals)),
                    out=list(out) if out else None)
    return list(res) if isinstance(res, (list, tuple)) else [res]


def all_op_names():
    return list_ops()


# ------------------------------------------------------------------ symbol
def symbol_create_atomic(op_name, keys, vals):
    fn = getattr(sym, op_name)
    attrs = {k: v for k, v in zip(keys, vals)}
    name = attrs.pop("name", None)
    return fn(name=name, **attrs) if name else fn(**attrs)


def symbol_compose(s, name, keys, args):
    """nnvm Symbol::Compose semantics. Atomic-head keyword names (the op's
    argument names, data/weight/...) are translated to placeholder
    variables by Symbol._compose itself (symbol.py)."""
    if keys:
        s._compose(name=name or None, **dict(zip(keys, args)))
    else:
        s._compose(*args, name=name or None)
    return s


def symbol_list(s, which):
    if which == "arguments":
        return s.list_arguments()
    if which == "outputs":
        return s.list_outputs()
    return s.list_auxiliary_states()


# ---------------------------------------------------------------- executor
def executor_bind(s, dev_type, dev_id, in_args, arg_grads, grad_reqs,
                  aux_states):
    ctx = _ctx(dev_type, dev_id)
    req_map = {0: "null", 1: "write", 2: "write", 3: "add"}
    arg_names = s.list_arguments()
    args = dict(zip(arg_names, in_args))
    grads = {n: g for n, g in zip(arg_names, arg_grads) if g is not None}
    reqs = {n: req_map[int(r)] for n, r in zip(arg_names, grad_reqs)}
    aux_names = s.list_auxiliary_states()
    return s.bind(ctx, args, args_grad=grads or None, grad_req=reqs,
                  aux_states=dict(zip(aux_names, aux_states)) or None)


def executor_forward(e, is_train):
    e.forward(is_train=bool(is_train))


def executor_backward(e, head_grads):
    e.backward(list(head_grads) if head_grads else None)


def executor_outputs(e):
    return list(e.outputs)


# ------------------------------------------------------------ predict API
class _Predictor(object):
    def __init__(self, json_str, param_blob, dev_type, dev_id,
                 input_names, input_shapes):
        import os
        import tempfile
        net = sym.load_json(json_str)
        params = {}
        if param_blob:
            fd, path = tempfile.mkstemp(suffix=".params")
            os.close(fd)
            try:
                with open(path, "wb") as f:
                    f.write(param_blob)
                loaded = nd.load(path)
            finally:
                os.unlink(path)
            for k, v in (loaded.items() if isinstance(loaded, dict) else []):
                # strip the arg:/aux: prefixes of save_checkpoint
                params[k.split(":", 1)[-1]] = v
        ctx = _ctx(dev_type, dev_id)
        shapes = dict(zip(input_names, [tuple(s) for s in input_shapes]))
        self.exe = net.simple_bind(ctx, grad_req="null", **shapes)
        for name, arr in self.exe.arg_dict.items():
            if name in params:
                params[name].copyto(arr)
        for name, arr in self.exe.aux_dict.items():
            if name in params:
                params[name].copyto(arr)
        self.input_names = list(input_names)

    def set_input(self, key, mv):
        arr = self.exe.arg_dict[key]
        ndarray_copy_from(arr, mv)

    def forward(self):
        self.exe.forward(is_train=False)

    def output_shape(self, index):
        return [int(s) for s in self.exe.outputs[index].shape]

    def output(self, index):
        return ndarray_copy_to(self.exe.outputs[index])


def pred_create(json_str, param_blob, dev_type, dev_id, input_names,
                input_shapes):
    return _Predictor(json_str, param_blob, dev_type, dev_id, input_names,
                      input_shapes)


def pred_create_partial(json_str, param_blob, dev_type, dev_id, input_names,
                        input_shapes, output_names):
    """MXPredCreatePartialOut: slice the graph at named internal outputs
    (reference c_predict_api.cc matches `name` or `name_output`)."""
    net = sym.load_json(json_str)
    internals = net.get_internals()
    available = internals.list_outputs()
    picked = []
    for want in output_names:
        if want in available:
            picked.append(internals[available.index(want)])
        elif want + "_output" in available:
            picked.append(internals[available.index(want + "_output")])
        else:
            raise ValueError("output %r not found in graph (have %s)"
                             % (want, available[:20]))
    sliced = sym.Group(picked) if len(picked) != 1 else picked[0]
    return _Predictor(sliced.tojson(), param_blob, dev_type, dev_id,
                      input_names, input_shapes)


class _NDList(object):
    """Decoded .nd file for MXNDList*: keeps per-index byte buffers alive
    so C pointers stay valid for the handle's lifetime."""

    def __init__(self, blob):
        import os
        import tempfile
        fd, path = tempfile.mkstemp(suffix=".nd")
        os.close(fd)
        try:
            with open(path, "wb") as f:
                f.write(blob)
            loaded = nd.load(path)
        finally:
            os.unlink(path)
        if isinstance(loaded, dict):
            self.keys = list(loaded.keys())
            self.arrs = [loaded[k] for k in self.keys]
        else:
            self.keys = [""] * len(loaded)
            self.arrs = list(loaded)
        self._cache = {}

    def __len__(self):
        return len(self.arrs)

    def get(self, index):
        i = int(index)
        if i not in self._cache:
            a = self.arrs[i]
            data = onp.ascontiguousarray(
                a.asnumpy().astype(onp.float32)).tobytes()
            self._cache[i] = (self.keys[i], data,
                              [int(s) for s in a.shape])
        return self._cache[i]


def ndlist_create(blob):
    return _NDList(blob)


def ndlist_get(lst, index):
    return lst.get(index)


# ------------------------------------------------------ raw-bytes ndarray
_RAW_MAGIC = b"MXTPUND1"


def ndarray_save_raw(arr):
    """Opaque single-array blob: magic | ndim | shape | dtype-code | data
    (MXNDArraySaveRawBytes; reference serializes via NDArray::Save)."""
    import struct
    shape = [int(s) for s in arr.shape]
    code = ndarray_dtype_code(arr)
    hdr = struct.pack("<8sII", _RAW_MAGIC, len(shape), code)
    hdr += struct.pack("<%dI" % len(shape), *shape)
    return hdr + ndarray_copy_to(arr)


def ndarray_load_raw(blob):
    import struct
    magic, ndim, code = struct.unpack_from("<8sII", blob, 0)
    if magic != _RAW_MAGIC:
        raise ValueError("corrupt NDArray raw-bytes blob")
    off = struct.calcsize("<8sII")
    shape = struct.unpack_from("<%dI" % ndim, blob, off)
    off += 4 * ndim
    dtype = _DTYPE_CODE[code]
    a = onp.frombuffer(blob, dtype=dtype, offset=off,
                       count=int(onp.prod(shape)) if ndim else 1)
    return nd.array(a.reshape(shape), dtype=dtype)


# ---------------------------------------------------------------- autograd
def autograd_set_training(is_training):
    from . import autograd
    prev = autograd.is_training()
    autograd.set_is_training(bool(is_training))
    return 1 if prev else 0


def autograd_mark_variables(variables, reqs, gradients):
    from . import autograd
    req_map = {0: "null", 1: "write", 2: "inplace", 3: "add"}
    autograd.mark_variables(list(variables),
                            list(gradients),
                            [req_map[int(r)] for r in reqs])


def autograd_compute_gradient(outputs):
    from . import autograd
    autograd.compute_gradient(list(outputs))


# ------------------------------------------------------------ op reflection
_ATTR_TYPE_NAMES = {int: "int", float: "float", bool: "boolean",
                    str: "string", tuple: "Shape(tuple)",
                    list: "Shape(tuple)"}


def func_info(op_name):
    """(name, description, arg_names, arg_types, arg_descs, key_var_num_args)
    for MXFuncGetInfo / MXSymbolGetAtomicSymbolInfo.

    Mirrors the reference's dmlc::Parameter reflection
    (include/dmlc/parameter.h __FIELDS__): tensor inputs are reported as
    NDArray-or-Symbol, keyword parameters with the type names declared in
    the registry's attr_types (registry.py OpDef)."""
    op = get_op(op_name)
    args = [a for a in op.list_arguments(None)]
    doc = (op.fcompute.__doc__ or "").strip() if op.fcompute else ""
    types = ["NDArray-or-Symbol"] * len(args)
    descs = [""] * len(args)
    for attr, typ in sorted(op.attr_types.items()):
        args.append(attr)
        tname = _ATTR_TYPE_NAMES.get(typ, getattr(typ, "__name__",
                                                  str(typ)))
        required = (attr == op.variable_args or
                    attr in op.required_attrs)
        types.append("%s, %s" % (tname,
                                 "required" if required else "optional"))
        descs.append("")
    if op.variable_args and op.variable_args not in op.attr_types:
        args.append(op.variable_args)
        types.append("int, required")
        descs.append("number of variadic inputs")
    # report the queried name, not the canonical target an alias resolves
    # to (the reference registry keys aliases as distinct entries);
    # key_var_num_args names the param that carries the vararg count
    # (e.g. add_n's num_args), "" for fixed-arity ops
    return op_name, doc, args, types, descs, op.variable_args or ""


def func_describe(op_name):
    """(num_use_vars, num_scalars, num_mutate_vars, type_mask) — legacy
    NDArrayFunction view (c_api.cc:396): inputs read, outputs mutated,
    scalar params travel as string kwargs here so num_scalars is 0."""
    op = get_op(op_name)
    return (op.num_inputs(None), 0, op.num_outputs(None), 1)


def func_arity(op_name, keys, vals):
    """(num_use_vars, num_mutate_vars) resolved against the ACTUAL params,
    so vararg ops (add_n/Concat: arity carried in e.g. num_args) marshal
    the right handle counts through MXFuncInvokeEx."""
    op = get_op(op_name)
    attrs = dict(zip(keys, vals))
    return (op.num_inputs(attrs), op.num_outputs(attrs))


# ------------------------------------------------------------ symbol extras
def symbol_group(symbols):
    return sym.Group(list(symbols))


def symbol_save_file(s, fname):
    s.save(fname)


def symbol_print(s):
    return s.debug_str() if hasattr(s, "debug_str") else repr(s)


def symbol_get_name(s):
    n = s.name
    return ("", 0) if n is None else (n, 1)


def symbol_get_attr(s, key):
    v = s.attr(key)
    return ("", 0) if v is None else (str(v), 1)


def symbol_set_attr(s, key, value):
    s._set_attr(**{key: value})


def symbol_list_attr(s, shallow):
    """Flattened k,v,k,v list. Deep form prefixes keys with node names
    (reference MXSymbolListAttr over attr_dict)."""
    flat = []
    if shallow:
        head_name = s._heads[0][0].name
        for k, v in sorted(s.attr_dict().get(head_name, {}).items()):
            if not k.startswith("_"):
                flat += [str(k), str(v)]
    else:
        for node_name, attrs in sorted(s.attr_dict().items()):
            for k, v in sorted(attrs.items()):
                if not k.startswith("_"):
                    flat += ["%s$%s" % (node_name, k), str(v)]
    return flat


def symbol_get_internals(s):
    return s.get_internals()


def symbol_get_children(s):
    return s.get_children()


def symbol_get_output(s, index):
    return s[int(index)]


def symbol_infer_shape(s, keys, csr_indptr, csr_data, partial):
    """CSR-decoded arg shapes in, (arg, out, aux) shape lists out; unknown
    shapes come back as empty lists when partial."""
    shapes = []
    for i in range(len(csr_indptr) - 1):
        row = tuple(csr_data[csr_indptr[i]:csr_indptr[i + 1]])
        # ndim-0 rows are the C-API "shape unknown" convention — they must
        # stay unknown (None) so inference can fill them, not become ()
        shapes.append(row if row else None)
    if keys:
        kwargs = dict(zip(keys, shapes))
        args = ()
    else:
        kwargs = {}
        args = tuple(shapes)
    fn = s.infer_shape_partial if partial else s.infer_shape
    arg_s, out_s, aux_s = fn(*args, **kwargs)
    if arg_s is None:
        return None

    def clean(lst):
        return [list(x) if x is not None else [] for x in lst]

    complete = all(x is not None for x in arg_s)
    return clean(arg_s), clean(out_s), clean(aux_s or []), int(complete)


def symbol_infer_type(s, keys, type_codes):
    codes = [int(t) for t in type_codes]
    if keys:
        kwargs = {k: _DTYPE_CODE[c] for k, c in zip(keys, codes)}
        args = ()
    else:
        kwargs = {}
        args = tuple(_DTYPE_CODE[c] for c in codes)
    arg_t, out_t, aux_t = s.infer_type(*args, **kwargs)
    if arg_t is None:
        return None

    def enc(lst):
        return [_CODE_DTYPE.get(str(onp.dtype(t)), -1) if t is not None
                else -1 for t in lst]

    complete = all(t is not None for t in arg_t)
    return enc(arg_t), enc(out_t), enc(aux_t or []), int(complete)


# ---------------------------------------------------------- executor extras
def executor_bind_x(s, dev_type, dev_id, map_keys, map_dev_types, map_dev_ids,
                    in_args, arg_grads, grad_reqs, aux_states, shared_exec):
    """MXExecutorBindX/EX: base device + group2ctx placement map."""
    ctx = _ctx(dev_type, dev_id)
    group2ctx = {k: _ctx(t, i) for k, t, i in
                 zip(map_keys, map_dev_types, map_dev_ids)}
    req_map = {0: "null", 1: "write", 2: "write", 3: "add"}
    arg_names = s.list_arguments()
    args = dict(zip(arg_names, in_args))
    grads = {n: g for n, g in zip(arg_names, arg_grads) if g is not None}
    reqs = {n: req_map[int(r)] for n, r in zip(arg_names, grad_reqs)}
    aux_names = s.list_auxiliary_states()
    return s.bind(ctx, args, args_grad=grads or None, grad_req=reqs,
                  aux_states=dict(zip(aux_names, aux_states)) or None,
                  group2ctx=group2ctx or None,
                  shared_exec=shared_exec)


def executor_print(e):
    return e.debug_str()


def executor_set_monitor_c(e, fn_ptr, ctx_ptr):
    """Install a C monitor callback: void(*)(const char*, NDArrayHandle,
    void*). Fired via ctypes; the NDArrayHandle is a strong ref the C side
    must release with MXNDArrayFree (graph_executor.cc:760 contract)."""
    import ctypes
    cb = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                          ctypes.c_void_p)(fn_ptr)

    def monitor(name, arr):
        ref = ctypes.py_object(arr)
        ctypes.pythonapi.Py_IncRef(ref)
        cb(name.encode(), id(arr), ctx_ptr)

    e.set_monitor_callback(monitor)
    e._c_monitor_keepalive = cb


# -------------------------------------------------------------- data iters
def _parse_attr_str(v):
    """Typed parse of a C-API string param — same parser the op registry
    uses for attrs (registry._parse_value), so dataiter kwargs and op
    params follow one set of string-conversion rules."""
    from .registry import _parse_value
    return _parse_value(str(v))


def _dataiter_registry():
    from . import io as io_mod
    from . import image as image_mod
    reg = {
        "MNISTIter": io_mod.MNISTIter,
        "CSVIter": io_mod.CSVIter,
        "ImageRecordIter": image_mod.ImageRecordIter,
    }
    if hasattr(image_mod, "ImageDetRecordIter"):
        reg["ImageDetRecordIter"] = image_mod.ImageDetRecordIter
    return reg


def list_data_iters():
    return sorted(_dataiter_registry().keys())


def dataiter_info(name):
    import inspect
    cls = _dataiter_registry()[name]
    doc = (cls.__doc__ or "").strip()
    params = [p for p in inspect.signature(cls.__init__).parameters.values()
              if p.name not in ("self",) and p.kind is not p.VAR_KEYWORD]
    names = [p.name for p in params]
    types = ["" if p.default is inspect.Parameter.empty else repr(p.default)
             for p in params]
    return name, doc, names, types, [""] * len(names)


class _CIter(object):
    """Handle-protocol adapter: the C API drives iterators as
    Next/GetData/GetLabel/GetPad over the CURRENT batch (iter_io.h
    DataIter contract), while python iterators expose next()->DataBatch.
    Caches the current batch per Next call."""

    def __init__(self, it):
        self.it = it
        self.cur = None

    def next(self):
        try:
            self.cur = self.it.next()
            return True
        except StopIteration:
            self.cur = None
            return False

    def reset(self):
        self.it.reset()
        self.cur = None


def dataiter_create(name, keys, vals):
    cls = _dataiter_registry()[name]
    kwargs = {k: _parse_attr_str(v) for k, v in zip(keys, vals)}
    return _CIter(cls(**kwargs))


def dataiter_next(it):
    return 1 if it.next() else 0


def dataiter_before_first(it):
    it.reset()


def dataiter_getdata(it):
    return it.cur.data[0]


def dataiter_getlabel(it):
    lab = it.cur.label
    return lab[0] if lab else None


def dataiter_getindex(it):
    idx = it.cur.index
    if idx is None:
        bs = int(it.cur.data[0].shape[0])
        return list(range(bs))
    return [int(i) for i in idx]


def dataiter_getpad(it):
    return int(it.cur.pad or 0)


# ------------------------------------------------------------------ kvstore
def init_ps_env(keys, vals):
    import os
    for k, v in zip(keys, vals):
        os.environ[str(k)] = str(v)


def kvstore_create(kind):
    from . import kvstore
    return kvstore.create(kind)


def kvstore_init(kv, keys, vals):
    kv.init(list(keys), list(vals))


def kvstore_push(kv, keys, vals, priority):
    kv.push(list(keys), list(vals), priority=priority)


def kvstore_pull(kv, keys, vals, priority):
    kv.pull(list(keys), out=list(vals), priority=priority)


def kvstore_set_updater_c(kv, fn_ptr, ctx_ptr):
    """C updater trampoline: void(*)(int key, NDArrayHandle recv,
    NDArrayHandle local, void*). Handles passed in are strong refs released
    by the trampoline after the call (the C side must NOT free them —
    matching the reference's borrowed-handle updater contract)."""
    import ctypes
    cb = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                          ctypes.c_void_p, ctypes.c_void_p)(fn_ptr)

    def updater(key, recv, local):
        cb(int(key), id(recv), id(local), ctx_ptr)

    kv._set_updater(updater)
    kv._c_updater_keepalive = cb


def kvstore_run_server_c(kv, fn_ptr, ctx_ptr):
    import ctypes
    cb = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_char_p,
                          ctypes.c_void_p)(fn_ptr)

    def controller(head, body):
        cb(int(head), str(body).encode(), ctx_ptr)

    kv._c_controller_keepalive = cb
    # no server processes in this design (kvstore_server.py): the controller
    # is registered for command loopback and the server loop is a no-op
    kv._server_controller = controller
    from .kvstore_server import KVStoreServer
    KVStoreServer(kv).run()


def kvstore_send_command(kv, head, body):
    kv._send_command_to_servers(int(head), body)


def kvstore_num_dead_node(kv, node_id, timeout_sec):
    return int(kv.get_num_dead_node(int(node_id), timeout=int(timeout_sec)))


def kvstore_is_role(role):
    import os
    r = os.environ.get("DMLC_ROLE", "worker")
    return 1 if r == role else 0


# ----------------------------------------------------------------- recordio
def recordio_writer_create(uri):
    from . import recordio
    w = recordio.MXRecordIO(uri, "w")
    return w


def recordio_reader_create(uri):
    from . import recordio
    return recordio.MXRecordIO(uri, "r")


def recordio_read(r):
    return r.read()  # None at EOF


def recordio_seek(r, pos):
    # byte-position seek (MXRecordIOReaderSeek); MXRecordIO.seek(idx) is
    # the indexed variant, so address the stream directly
    r.handle.seek(int(pos))


# ---------------------------------------------------------------------- rtc
def rtc_create(name, input_names, output_names, inputs, outputs, kernel):
    from . import rtc
    named_in = list(zip(input_names, inputs))
    named_out = list(zip(output_names, outputs))
    return rtc.Rtc(name, named_in, named_out, kernel)


def rtc_push(r, inputs, outputs, grid_dims, block_dims):
    r.push(list(inputs), list(outputs), grid_dims, block_dims)


# ---------------------------------------------------------- custom op (C)
class _CCallbackList(object):
    """Decoded MXCallbackList: slot index -> (fn_ptr, ctx_ptr)."""

    def __init__(self, num, fn_addrs, ctx_addrs):
        self.slots = list(zip(fn_addrs[:num], ctx_addrs[:num]))

    def get(self, idx):
        if idx >= len(self.slots) or not self.slots[idx][0]:
            return None, None
        return self.slots[idx]


def _c_strlist(fn_ptr, state, functype):
    """Invoke a CustomOpListFunc and decode its NULL-terminated char**."""
    import ctypes
    fn = functype(fn_ptr)
    out = ctypes.POINTER(ctypes.c_char_p)()
    if not fn(ctypes.byref(out), state):
        raise RuntimeError("custom-op list callback failed")
    names, i = [], 0
    while out[i]:
        names.append(out[i].decode())
        i += 1
    return names


def custom_op_register_c(op_type, creator_ptr):
    """MXCustomOpRegister: wrap a C CustomOpPropCreator as a python
    CustomOpProp so C-registered ops flow through the same executor path
    as python custom ops (reference custom.cc tags: in=0 out=1 grad=2
    ograd=3 aux=4; reqs: 0 null, 1 write, 2 inplace, 3 add)."""
    import ctypes
    from . import operator as op_mod

    LIST_T = ctypes.CFUNCTYPE(ctypes.c_int,
                              ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p)),
                              ctypes.c_void_p)
    SHAPE_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int,
                               ctypes.POINTER(ctypes.c_int),
                               ctypes.POINTER(ctypes.POINTER(ctypes.c_uint)),
                               ctypes.c_void_p)
    FB_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int,
                            ctypes.POINTER(ctypes.c_void_p),
                            ctypes.POINTER(ctypes.c_int),
                            ctypes.POINTER(ctypes.c_int),
                            ctypes.c_int, ctypes.c_void_p)
    CREATE_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint)),
                                ctypes.POINTER(ctypes.c_int),
                                ctypes.POINTER(ctypes.c_int),
                                ctypes.c_void_p, ctypes.c_void_p)

    class _CallbackListStruct(ctypes.Structure):
        _fields_ = [("num_callbacks", ctypes.c_int),
                    ("callbacks", ctypes.POINTER(ctypes.c_void_p)),
                    ("contexts", ctypes.POINTER(ctypes.c_void_p))]

    CREATOR_T = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(_CallbackListStruct))
    creator = CREATOR_T(creator_ptr)

    # slot indices (enum CustomOpPropCallbacks / CustomOpCallbacks)
    PROP_LIST_ARG, PROP_LIST_OUT, PROP_LIST_AUX = 1, 2, 3
    PROP_INFER_SHAPE, PROP_BWD_DEP, PROP_CREATE = 4, 5, 6
    OP_FWD, OP_BWD = 1, 2
    _REQ_CODE = {"null": 0, "write": 1, "inplace": 2, "add": 3}

    def decode_cblist(cl):
        n = cl.num_callbacks
        fns = [cl.callbacks[i] or 0 for i in range(n)]
        ctxs = [cl.contexts[i] or 0 for i in range(n)]
        return _CCallbackList(n, fns, ctxs)

    def _as_nd(x):
        if isinstance(x, nd.NDArray):
            return x
        if hasattr(x, "asnumpy"):
            return nd.array(x.asnumpy())
        return nd.array(onp.asarray(x))

    class _COp(op_mod.CustomOp):
        def __init__(self, cbl):
            self._cbl = cbl

        def _fb(self, slot, groups, reqs, is_train):
            fn_ptr, state = self._cbl.get(slot)
            if fn_ptr is None:
                raise RuntimeError("C custom op missing callback %d" % slot)
            fn = FB_T(fn_ptr)
            handles, tags = [], []
            keep = []
            for tag, arrs in groups:
                for a in arrs:
                    a_nd = _as_nd(a)
                    keep.append(a_nd)
                    handles.append(id(a_nd))
                    tags.append(tag)
            n = len(handles)
            arr_t = (ctypes.c_void_p * n)(*handles)
            tag_t = (ctypes.c_int * n)(*tags)
            req_t = (ctypes.c_int * max(len(reqs), 1))(
                *[_REQ_CODE.get(r, 1) for r in reqs] or [1])
            if not fn(n, arr_t, tag_t, req_t, int(is_train), state):
                raise RuntimeError("C custom op forward/backward failed")
            return keep, tags

        def forward(self, is_train, req, in_data, out_data, aux):
            # hand real NDArrays across the ABI; C mutates outputs in place
            in_nd = [_as_nd(x) for x in in_data]
            out_nd = [_as_nd(x) for x in out_data]
            aux_nd = [_as_nd(x) for x in aux]
            keep, _ = self._fb(OP_FWD,
                               [(0, in_nd), (1, out_nd), (4, aux_nd)],
                               list(req), is_train)
            for dst, src in zip(out_data, out_nd):
                self.assign(dst, "write", src.asnumpy())
            for dst, src in zip(aux, aux_nd):
                dst[:] = src.asnumpy()

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            in_nd = [_as_nd(x) for x in in_data]
            out_nd = [_as_nd(x) for x in out_data]
            ig_nd = [_as_nd(x) for x in in_grad]
            aux_nd = [_as_nd(x) for x in aux]
            og_nd = [_as_nd(x) for x in out_grad]
            self._fb(OP_BWD,
                     [(0, in_nd), (1, out_nd), (2, ig_nd), (4, aux_nd),
                      (3, og_nd)],
                     list(req), True)
            for dst, src in zip(in_grad, ig_nd):
                self.assign(dst, "write", src.asnumpy())

    class _CProp(op_mod.CustomOpProp):
        def __init__(self, **kwargs):
            super(_CProp, self).__init__(need_top_grad=True)
            self._kwargs = kwargs
            keys = [str(k).encode() for k in kwargs]
            vals = [str(v).encode() for v in kwargs.values()]
            cl = _CallbackListStruct()
            ok = creator(op_type.encode(), len(keys),
                         (ctypes.c_char_p * max(len(keys), 1))(*keys or
                                                               [b""]),
                         (ctypes.c_char_p * max(len(vals), 1))(*vals or
                                                               [b""]),
                         ctypes.byref(cl))
            if not ok:
                raise RuntimeError("CustomOpPropCreator failed for %s"
                                   % op_type)
            self._cbl = decode_cblist(cl)

        def _strlist(self, slot):
            fn_ptr, state = self._cbl.get(slot)
            if fn_ptr is None:
                return []
            return _c_strlist(fn_ptr, state, LIST_T)

        def list_arguments(self):
            return self._strlist(PROP_LIST_ARG) or ["data"]

        def list_outputs(self):
            return self._strlist(PROP_LIST_OUT) or ["output"]

        def list_auxiliary_states(self):
            return self._strlist(PROP_LIST_AUX)

        def infer_shape(self, in_shape):
            import ctypes as ct
            fn_ptr, state = self._cbl.get(PROP_INFER_SHAPE)
            if fn_ptr is None:
                return super(_CProp, self).infer_shape(in_shape)
            n_in = len(self.list_arguments())
            n_out = len(self.list_outputs())
            n_aux = len(self.list_auxiliary_states())
            total = n_in + n_out + n_aux
            ndims = (ct.c_int * total)(
                *([len(s) for s in in_shape] + [0] * (n_out + n_aux)))
            # per-tensor shape buffers; the callback either reads (inputs)
            # or repoints the row at its own storage (outputs)
            keep = [(ct.c_uint * max(len(s), 8))(*[int(d) for d in s])
                    for s in in_shape]
            keep += [(ct.c_uint * 8)() for _ in range(n_out + n_aux)]
            rows = (ct.POINTER(ct.c_uint) * total)(
                *[ct.cast(b, ct.POINTER(ct.c_uint)) for b in keep])
            fn = SHAPE_T(fn_ptr)
            if not fn(total, ndims, rows, state):
                raise RuntimeError("C custom op infer_shape failed")
            shapes = [tuple(int(rows[i][j]) for j in range(ndims[i]))
                      for i in range(total)]
            return (shapes[:n_in], shapes[n_in:n_in + n_out],
                    shapes[n_in + n_out:])

        def declare_backward_dependency(self, out_grad, in_data, out_data):
            import ctypes as ct
            fn_ptr, state = self._cbl.get(PROP_BWD_DEP)
            if fn_ptr is None:
                return super(_CProp, self).declare_backward_dependency(
                    out_grad, in_data, out_data)
            BWD_T = ct.CFUNCTYPE(ct.c_int, ct.POINTER(ct.c_int),
                                 ct.POINTER(ct.c_int), ct.POINTER(ct.c_int),
                                 ct.POINTER(ct.c_int),
                                 ct.POINTER(ct.POINTER(ct.c_int)),
                                 ct.c_void_p)
            fn = BWD_T(fn_ptr)
            og = (ct.c_int * max(len(out_grad), 1))(*out_grad or [0])
            ind = (ct.c_int * max(len(in_data), 1))(*in_data or [0])
            od = (ct.c_int * max(len(out_data), 1))(*out_data or [0])
            ndeps = ct.c_int(0)
            rdeps = ct.POINTER(ct.c_int)()
            if not fn(og, ind, od, ct.byref(ndeps), ct.byref(rdeps), state):
                raise RuntimeError("C custom op backward-dependency failed")
            return [int(rdeps[i]) for i in range(ndeps.value)]

        def create_operator(self, ctx, in_shapes, in_dtypes):
            import ctypes as ct
            fn_ptr, state = self._cbl.get(PROP_CREATE)
            if fn_ptr is None:
                # the reference CHECKs this callback exists (custom.cc:177)
                raise RuntimeError(
                    "C custom op %s has no CreateOperator callback"
                    % op_type)
            n = len(in_shapes)
            keep = [(ct.c_uint * max(len(s), 1))(*[int(d) for d in s])
                    for s in in_shapes]
            rows = (ct.POINTER(ct.c_uint) * max(n, 1))(
                *[ct.cast(b, ct.POINTER(ct.c_uint)) for b in keep])
            ndims = (ct.c_int * max(n, 1))(*[len(s) for s in in_shapes]
                                           or [0])
            dts = (ct.c_int * max(n, 1))(
                *[_CODE_DTYPE.get(str(onp.dtype(t)), 0) for t in in_dtypes]
                or [0])
            cl = _CallbackListStruct()
            fn = CREATE_T(fn_ptr)
            if not fn(str(ctx).encode(), n, rows, ndims, dts,
                      ct.cast(ct.byref(cl), ct.c_void_p), state):
                raise RuntimeError("C custom op create_operator failed")
            return _COp(decode_cblist(cl))

    op_mod.register(op_type)(_CProp)


# ------------------------------------------------------------------ global
def random_seed(s):
    from . import random as rnd
    rnd.seed(int(s))


def profiler_config(mode, filename):
    from . import profiler
    profiler.profiler_set_config(mode={0: "symbolic", 1: "all"}.get(mode,
                                                                    "all"),
                                 filename=filename)


def profiler_state(state):
    from . import profiler
    profiler.profiler_set_state({0: "stop", 1: "run"}.get(state, "stop"))


def profiler_dump():
    from . import profiler
    profiler.dump_profile()


def wait_all():
    nd.waitall()
