"""RecordIO file format (python/mxnet/recordio.py:269 + dmlc/recordio.h).

Binary-compatible with the reference: records framed by the dmlc magic
``0xced7230a`` + masked-length word, payload padded to 4 bytes; image records
use IRHeader (flag, label, id, id2) packed little-endian. A C++ accelerated
reader lives in runtime/ (same format).
"""
from __future__ import annotations

import collections
import os
import struct

import numpy as onp

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "unpack_img", "pack_img"]

_MAGIC = 0xced7230a
_LMASK = 0x1fffffff


class MXRecordIO(object):
    """Sequential RecordIO reader/writer (recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self.handle.close()
            self.is_open = False

    def __del__(self):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        self.handle.write(struct.pack("<II", _MAGIC, len(buf) & _LMASK))
        self.handle.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        head = self.handle.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        assert magic == _MAGIC, "Invalid RecordIO magic"
        length = lrec & _LMASK
        buf = self.handle.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        return buf

    def tell(self):
        return self.handle.tell()


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with a .idx sidecar for random access (recordio.py
    MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write("%s\t%d\n" % (str(k), self.idx[k]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.keys.append(key)
        self.idx[key] = pos


IRHeader = collections.namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack an IRHeader + payload into one record string."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        header = header._replace(flag=0)
        packed = struct.pack(_IR_FORMAT, 0, float(header.label), header.id,
                             header.id2)
    else:
        label = onp.asarray(header.label, dtype=onp.float32)
        packed = struct.pack(_IR_FORMAT, label.size, 0.0, header.id,
                             header.id2) + label.tobytes()
    return packed + s


def unpack(s):
    """Unpack a record into (IRHeader, payload)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = onp.frombuffer(s[:header.flag * 4], dtype=onp.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=-1):
    """Unpack an image record to (IRHeader, ndarray) via cv2 when present,
    else a raw-npy fallback written by pack_img's fallback."""
    header, s = unpack(s)
    try:
        import cv2
        img = cv2.imdecode(onp.frombuffer(s, dtype=onp.uint8), iscolor)
    except ImportError:
        import io as _io
        img = onp.load(_io.BytesIO(bytes(s)), allow_pickle=False)
    return header, img


def _pack_npy(header, img):
    import io as _io
    bio = _io.BytesIO()
    onp.save(bio, onp.asarray(img), allow_pickle=False)
    return pack(header, bio.getvalue())


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image into a record; encodes via cv2, else PIL, else raw
    .npy bytes (decode with unpack_img). ``img_fmt=".npy"`` forces the raw
    uncompressed payload — zero decode cost at read time, for hosts whose
    image-decode throughput can't feed the chip."""
    if img_fmt == ".npy":
        return _pack_npy(header, img)
    try:
        import cv2
        encode_params = None
        if img_fmt in (".jpg", ".jpeg"):
            encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
        elif img_fmt == ".png":
            # PNG takes a 0-9 compression level, not JPEG's 0-100 quality
            encode_params = [cv2.IMWRITE_PNG_COMPRESSION, min(quality, 9)]
        ret, buf = cv2.imencode(img_fmt, img, encode_params)
        assert ret, "failed to encode image"
        return pack(header, buf.tobytes())
    except ImportError:
        pass
    try:
        import io as _io
        from PIL import Image
        fmt = {"jpg": "JPEG", "jpeg": "JPEG", "png": "PNG"}.get(
            img_fmt.lstrip("."), None)
        if fmt is not None:
            bio = _io.BytesIO()
            Image.fromarray(onp.asarray(img)).save(bio, format=fmt,
                                                   quality=quality)
            return pack(header, bio.getvalue())
    except ImportError:
        pass
    return _pack_npy(header, img)
