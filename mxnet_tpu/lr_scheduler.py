"""Learning-rate schedulers.

API counterpart of the reference's python/mxnet/lr_scheduler.py: a
scheduler is a callable ``num_update -> lr`` that the optimizer consults
on every update (optimizer.py _get_lr). Stepwise decay state is tracked
incrementally so the call is O(1) per update regardless of how many
boundaries have passed.

TPU note: schedulers run on the HOST. On the fused one-program train
step the current lr enters the compiled program as a runtime array
(mesh_executor_group.step_update), so a changing schedule never triggers
recompilation.

Beyond the reference's Factor/MultiFactor pair this module adds the
schedules modern recipes expect: polynomial decay, cosine decay, and a
linear-warmup wrapper that composes with any of them.
"""
from __future__ import annotations

import logging
import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler", "WarmupScheduler"]


class LRScheduler(object):
    """Base class: ``scheduler(num_update) -> learning rate``."""

    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update):
        raise NotImplementedError("subclasses implement __call__")


class FactorScheduler(LRScheduler):
    """Geometric decay: multiply by ``factor`` every ``step`` updates,
    clamped below at ``stop_factor_lr``."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("step must be >= 1 update")
        if factor > 1.0:
            raise ValueError("factor must be <= 1 so the lr decays")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        # advance the decay counter incrementally — num_update may jump
        # (resume from checkpoint) but normally increments by one
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
                logging.info(
                    "Update[%d]: lr clamped at %0.5e; no further decay",
                    num_update, self.base_lr)
            else:
                logging.info("Update[%d]: Change learning rate to %0.5e",
                             num_update, self.base_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    """Decay by ``factor`` at each boundary in the increasing list
    ``step`` (the classic 2-milestone ImageNet schedule)."""

    def __init__(self, step, factor=1):
        super().__init__()
        if not isinstance(step, list) or not step:
            raise ValueError("step must be a non-empty list of updates")
        for i, s in enumerate(step):
            if s < 1:
                raise ValueError("schedule boundaries must be >= 1")
            if i and s <= step[i - 1]:
                raise ValueError("schedule boundaries must increase")
        if factor > 1.0:
            raise ValueError("factor must be <= 1 so the lr decays")
        self.step = step
        self.cur_step_ind = 0
        self.factor = factor
        self.count = 0

    def __call__(self, num_update):
        while self.cur_step_ind < len(self.step) and \
                num_update > self.step[self.cur_step_ind]:
            self.count = self.step[self.cur_step_ind]
            self.cur_step_ind += 1
            self.base_lr *= self.factor
            logging.info("Update[%d]: Change learning rate to %0.5e",
                         num_update, self.base_lr)
        return self.base_lr


class PolyScheduler(LRScheduler):
    """Polynomial decay to ``final_lr`` over ``max_update`` updates:
    lr = final + (base - final) * (1 - t/T)^power."""

    def __init__(self, max_update, base_lr=0.01, power=2.0, final_lr=0.0):
        super().__init__(base_lr)
        if max_update < 1:
            raise ValueError("max_update must be >= 1")
        self.max_update = max_update
        self.power = power
        self.final_lr = final_lr

    def __call__(self, num_update):
        if num_update >= self.max_update:
            return self.final_lr
        frac = 1.0 - float(num_update) / self.max_update
        return self.final_lr + (self.base_lr - self.final_lr) * \
            frac ** self.power


class CosineScheduler(LRScheduler):
    """Cosine decay to ``final_lr`` over ``max_update`` updates."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0.0):
        super().__init__(base_lr)
        if max_update < 1:
            raise ValueError("max_update must be >= 1")
        self.max_update = max_update
        self.final_lr = final_lr

    def __call__(self, num_update):
        if num_update >= self.max_update:
            return self.final_lr
        cos = (1.0 + math.cos(math.pi * num_update / self.max_update)) / 2
        return self.final_lr + (self.base_lr - self.final_lr) * cos


class WarmupScheduler(LRScheduler):
    """Linear warmup from ``start_lr`` over ``warmup_steps`` updates,
    then delegate to ``base_scheduler`` (its clock starts at 0 after
    warmup)."""

    def __init__(self, base_scheduler, warmup_steps, start_lr=0.0):
        if warmup_steps < 1:
            raise ValueError("warmup_steps must be >= 1")
        self.base_scheduler = base_scheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr

    # the optimizer assigns scheduler.base_lr = learning_rate at init
    # (optimizer.py Optimizer.__init__); proxy it to the wrapped
    # scheduler so the warmup target and the post-warmup schedule both
    # honor the configured rate
    @property
    def base_lr(self):
        return self.base_scheduler.base_lr

    @base_lr.setter
    def base_lr(self, value):
        self.base_scheduler.base_lr = value

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            frac = float(num_update) / self.warmup_steps
            return self.start_lr + \
                (self.base_scheduler.base_lr - self.start_lr) * frac
        return self.base_scheduler(num_update - self.warmup_steps)
