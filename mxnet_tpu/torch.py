"""Torch bridge (python/mxnet/torch.py / plugin/torch in the reference).

The reference bridges Lua-torch modules/criterions into the graph. A
CPU-only ``torch`` is present in this image, so the bridge maps torch
callables into the graph via CustomOp semantics (host callback); there is
no TPU-side torch execution.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["pytorch_function"]


def pytorch_function(fn, name="torch_fn"):
    """Wrap a (CPU) pytorch callable as an imperative NDArray function.

    The callable receives/returns torch tensors; data round-trips through
    host memory — use for preprocessing/losses, not hot-path compute.
    """
    try:
        import torch as _torch
    except ImportError:  # pragma: no cover
        raise MXNetError("pytorch is not available in this environment")

    from .ndarray import NDArray, array

    def wrapped(*args):
        t_args = [_torch.from_numpy(a.asnumpy()) if isinstance(a, NDArray)
                  else a for a in args]
        out = fn(*t_args)
        if isinstance(out, (list, tuple)):
            return [array(o.detach().cpu().numpy()) for o in out]
        return array(out.detach().cpu().numpy())

    wrapped.__name__ = name
    return wrapped
