"""Torch bridge (python/mxnet/torch.py / plugin/torch in the reference).

The reference bridges Lua-torch modules/criterions into the symbolic
graph as the ``TorchModule`` / ``TorchCriterion`` ops
(plugin/torch/torch_module-inl.h, torch_criterion-inl.h): ``lua_string``
constructs an ``nn`` module whose parameters become graph arguments.
Here the same two ops are registered with ``lua_string`` evaluated as a
PYTORCH constructor expression in a namespace with ``nn``/``torch``/``F``
bound (``"nn.Linear(4, 3)"`` works verbatim for the many constructors
Lua-nn and torch.nn share). Execution is a host callback
(``jax.pure_callback`` + ``jax.custom_vjp`` running torch autograd —
the CustomOp machinery's pattern, operator.py), so the ops participate
in jitted graphs, Module.fit, and the C API like any native op; there
is no TPU-side torch execution.

Matching reference semantics:
* ``TorchModule(lua_string, num_data, num_params, num_outputs)`` —
  arguments are ``data_0..`` then the module's parameter names
  (torch's ``named_parameters()``, dots -> underscores; the reference
  maps Lua param tensors to their field names the same way,
  torch_module-inl.h ListArguments).
* ``TorchCriterion(lua_string, label_shape, grad_scale)`` — inputs
  (data, label); output shape ``(batch,)`` filled with the scalar
  ``loss * grad_scale`` (torch_criterion-inl.h Forward); backward
  feeds ``dloss/dpred * grad_scale`` and ignores head gradients, like
  the reference (and like SoftmaxOutput's loss-head convention).
"""
from __future__ import annotations

import numpy as onp

from .base import MXNetError
from .registry import register as _register

__all__ = ["pytorch_function"]


# ---------------------------------------------------------------------------
# TorchModule / TorchCriterion ops (plugin/torch parity)
# ---------------------------------------------------------------------------
_MOD_CACHE = {}


def _torch():
    try:
        import torch
    except ImportError:  # pragma: no cover
        raise MXNetError(
            "TorchModule/TorchCriterion need pytorch, which is not "
            "importable in this environment")
    return torch


def _build(lua_string):
    """Construct (and cache) the torch module from the constructor
    expression. The namespace binds nn/torch/F so Lua-style strings like
    'nn.Linear(4, 3)' evaluate directly."""
    torch = _torch()
    if lua_string not in _MOD_CACHE:
        ns = {"nn": torch.nn, "torch": torch, "F": torch.nn.functional}
        try:
            m = eval(lua_string, ns)  # noqa: S307 — the reference
            # executes lua_string in a Lua VM the same way; the string is
            # the user's own model definition, not untrusted input
        except Exception as e:
            raise MXNetError("TorchModule: constructor %r failed: %s"
                             % (lua_string, e))
        if not isinstance(m, torch.nn.Module):
            raise MXNetError("TorchModule: %r did not produce an "
                             "nn.Module" % (lua_string,))
        _MOD_CACHE[lua_string] = m.float()
    return _MOD_CACHE[lua_string]


def _param_names(m):
    return [n.replace(".", "_") for n, _ in m.named_parameters()]


def _tm_args(attrs):
    names = ["data_%d" % i for i in range(int(attrs["num_data"]))]
    try:
        names += _param_names(_build(attrs["lua_string"]))
    except MXNetError:
        names += ["param_%d" % i for i in range(int(attrs["num_params"]))]
    return tuple(names)


def _tm_infer(attrs, in_shapes, aux):
    n_data = int(attrs["num_data"])
    m = _build(attrs["lua_string"])
    params = list(m.parameters())
    if len(params) != int(attrs["num_params"]):
        raise MXNetError(
            "TorchModule: num_params=%s but %r has %d parameters"
            % (attrs["num_params"], attrs["lua_string"], len(params)))
    for i, p in enumerate(params):
        in_shapes[n_data + i] = tuple(p.shape)
    if any(in_shapes[i] is None for i in range(n_data)):
        return in_shapes, None, aux
    torch = _torch()
    with torch.no_grad():
        outs = m(*[torch.zeros(*in_shapes[i]) for i in range(n_data)])
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    if len(outs) != int(attrs["num_outputs"]):
        raise MXNetError(
            "TorchModule: num_outputs=%s but %r produced %d outputs"
            % (attrs["num_outputs"], attrs["lua_string"], len(outs)))
    return in_shapes, [tuple(o.shape) for o in outs], aux


@_register("TorchModule", arg_names=_tm_args,
           num_outputs=lambda attrs: int(attrs["num_outputs"]),
           infer_shape=_tm_infer, needs_rng=True,
           attr_types={"lua_string": str, "num_data": int,
                       "num_params": int, "num_outputs": int},
           required_attrs=("lua_string", "num_data", "num_params",
                          "num_outputs"))
def _torch_module(attrs, ins, octx):
    """Forward/backward both re-run the torch module on the host; the
    op's rng key seeds torch's RNG identically in both callbacks, so
    stochastic layers (Dropout) draw the SAME mask in the backward
    recompute as in the emitted forward. The reference instead keeps one
    live Lua module between forward() and backward() calls — that
    stateful contract can't survive a jitted graph, the seeded-recompute
    one can. Caveat: torch-side stateful BUFFERS (BatchNorm running
    stats) live in the cached module, not the mxnet graph; they advance
    on every (re)run and are not checkpointed — use the native BatchNorm
    op for stats-bearing layers."""
    import jax

    n_data = int(attrs["num_data"])
    n_out = int(attrs["num_outputs"])
    lua = attrs["lua_string"]
    is_train = bool(octx.is_train)
    in_shapes = [tuple(x.shape) for x in ins]
    in_dtypes = [onp.dtype(x.dtype) for x in ins]
    m0 = _build(lua)
    torch = _torch()
    was_training = m0.training
    m0.train(False)
    with torch.no_grad():
        probe = m0(*[torch.zeros(*s) for s in in_shapes[:n_data]])
    m0.train(was_training)
    probe = probe if isinstance(probe, (tuple, list)) else (probe,)
    out_struct = tuple(jax.ShapeDtypeStruct(tuple(o.shape), onp.float32)
                       for o in probe)
    if octx.rng is not None:
        seed = jax.random.randint(octx.rng, (), 0, 2 ** 31 - 1,
                                  dtype=onp.int32)
    else:
        seed = onp.int32(0)

    def _load(arrays, requires_grad):
        torch = _torch()
        m = _build(lua)
        m.train(is_train)
        params = list(m.parameters())
        with torch.no_grad():
            for p, a in zip(params, arrays[n_data:]):
                p.copy_(torch.from_numpy(onp.array(a, onp.float32)))
        for p in params:
            p.requires_grad_(requires_grad)
        data = [torch.from_numpy(onp.array(a, onp.float32))
                for a in arrays[:n_data]]
        return m, params, data

    def host_forward(seed_v, *arrays):
        torch = _torch()
        m, _, data = _load(arrays, False)
        torch.manual_seed(int(seed_v))
        with torch.no_grad():
            outs = m(*data)
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        return tuple(onp.asarray(o.detach(), onp.float32) for o in outs)

    @jax.custom_vjp
    def f(seed_v, *xs):
        return jax.pure_callback(host_forward, out_struct, seed_v, *xs)

    def f_fwd(seed_v, *xs):
        return jax.pure_callback(host_forward, out_struct, seed_v,
                                 *xs), (seed_v, xs)

    def f_bwd(res, gs):
        seed_v, xs = res

        def host_backward(seed_b, *args):
            torch = _torch()
            cot = [torch.from_numpy(onp.array(a, onp.float32))
                   for a in args[:n_out]]
            m, params, data = _load(args[n_out:], True)
            for d in data:
                d.requires_grad_(True)
            torch.manual_seed(int(seed_b))  # same masks as the forward
            outs = m(*data)
            outs = outs if isinstance(outs, (tuple, list)) else (outs,)
            leaves = data + params
            grads = torch.autograd.grad(outs, leaves, grad_outputs=cot,
                                        allow_unused=True)
            return tuple(
                onp.zeros(s, dt) if g is None else
                onp.asarray(g.detach(), onp.float32).astype(dt)
                for g, s, dt in zip(grads, in_shapes, in_dtypes))

        in_struct = tuple(jax.ShapeDtypeStruct(s, dt)
                          for s, dt in zip(in_shapes, in_dtypes))
        grads = jax.pure_callback(host_backward, in_struct, seed_v,
                                  *(tuple(gs) + tuple(xs)))
        return (None,) + tuple(grads)

    f.defvjp(f_fwd, f_bwd)
    return list(f(seed, *ins))


def _tc_infer(attrs, in_shapes, aux):
    dshape = in_shapes[0]
    if dshape is None:
        return in_shapes, None, aux
    lshape = tuple(attrs.get("label_shape", ()) or ())
    in_shapes[1] = (dshape[0],) + lshape
    return in_shapes, [(dshape[0],)], aux


@_register("TorchCriterion", arg_names=("data", "label"),
           infer_shape=_tc_infer,
           attr_types={"lua_string": str, "label_shape": tuple,
                       "grad_scale": float},
           required_attrs=("lua_string",))
def _torch_criterion(attrs, ins, octx):
    import jax

    lua = attrs["lua_string"]
    scale = float(attrs.get("grad_scale", 1.0))
    dshape = tuple(ins[0].shape)
    lshape = tuple(ins[1].shape)
    out_struct = (jax.ShapeDtypeStruct((dshape[0],), onp.float32),)

    def _apply(crit, pred_t, label):
        # class-index criterions (NLLLoss, CrossEntropyLoss) want Long
        # targets; regression criterions want Float. Decide ONCE per
        # criterion (cached on the module) so the hot path never pays a
        # failed forward, and only a dtype complaint triggers the Long
        # retry — other RuntimeErrors (shape mismatches) propagate.
        torch = _torch()
        lab_t = torch.from_numpy(onp.array(label, onp.float32))
        wants_long = getattr(crit, "_mxtpu_wants_long", None)
        if wants_long:
            return crit(pred_t, lab_t.long())
        try:
            out = crit(pred_t, lab_t)
            crit._mxtpu_wants_long = False
            return out
        except RuntimeError as e:
            if wants_long is None and ("Long" in str(e)
                                       or "dtype" in str(e)):
                out = crit(pred_t, lab_t.long())
                crit._mxtpu_wants_long = True
                return out
            raise

    def host_forward(pred, label):
        torch = _torch()
        crit = _build(lua)
        with torch.no_grad():
            loss = _apply(crit,
                          torch.from_numpy(onp.array(pred, onp.float32)),
                          label)
        return (onp.full((dshape[0],), float(loss) * scale, onp.float32),)

    @jax.custom_vjp
    def f(pred, label):
        return jax.pure_callback(host_forward, out_struct, pred, label)

    def f_fwd(pred, label):
        return jax.pure_callback(host_forward, out_struct, pred, label), \
            (pred, label)

    def f_bwd(res, gs):
        pred, label = res

        def host_backward(p, lab):
            torch = _torch()
            crit = _build(lua)
            pt = torch.from_numpy(onp.array(p, onp.float32))
            pt.requires_grad_(True)
            loss = _apply(crit, pt, lab)
            (g,) = torch.autograd.grad(loss, (pt,))
            return onp.asarray(g, onp.float32) * scale

        in_struct = jax.ShapeDtypeStruct(dshape, onp.float32)
        # loss head: out_grad is ignored, like the reference's Backward
        gp = jax.pure_callback(host_backward, in_struct, pred, label)
        import jax.numpy as jnp
        return gp, jnp.zeros(lshape, onp.float32)

    f.defvjp(f_fwd, f_bwd)
    return list(f(*ins))


def pytorch_function(fn, name="torch_fn"):
    """Wrap a (CPU) pytorch callable as an imperative NDArray function.

    The callable receives/returns torch tensors; data round-trips through
    host memory — use for preprocessing/losses, not hot-path compute.
    """
    try:
        import torch as _torch
    except ImportError:  # pragma: no cover
        raise MXNetError("pytorch is not available in this environment")

    from .ndarray import NDArray, array

    def wrapped(*args):
        t_args = [_torch.from_numpy(a.asnumpy()) if isinstance(a, NDArray)
                  else a for a in args]
        out = fn(*t_args)
        if isinstance(out, (list, tuple)):
            return [array(o.detach().cpu().numpy()) for o in out]
        return array(out.detach().cpu().numpy())

    wrapped.__name__ = name
    return wrapped
