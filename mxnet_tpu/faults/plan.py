"""FaultPlan — declarative, deterministically seeded fault injection.

A plan is a list of :class:`FaultRule` entries, each naming an
injection **site** (a dotted seam name like ``checkpoint.commit`` or
``serving.worker``), a **kind** (what happens when the rule fires), and
a **trigger** (when it fires). The whole plan carries ONE seed; every
probabilistic draw and every corruption offset is a pure SplitMix fold
of ``(seed, rule index, evaluation counter)`` — so the same plan + seed
over the same workload produces the same incident transcript, and a
fault run is replayable the way a seeded training run is.

Rule grammar (the ``FaultPlan.parse`` / ``MXNET_FAULT_PLAN`` spelling)::

    site:kind[@key=value[,key=value...]] [; site:kind@... ...]

Trigger keys (at most one of ``nth``/``prob``; context matches compose
with either):

* ``nth=N``   — fire on the N-th evaluation of the site (1-based).
  Deterministic for serially-evaluated sites (the step loop, the
  batcher worker); concurrent sites (transform workers) should match
  on context instead.
* ``prob=P``  — fire with probability P per evaluation, drawn from the
  plan-seeded SplitMix stream (never from wall time or ``random``).
* any other ``key=value`` — fire only when the seam's context carries
  that exact coordinate (``step=12``, ``epoch=1``, ``num_update=14``,
  ``index=3``...). This is the "fire at step/epoch/request N" spelling.

Behavior keys:

* ``count=N`` — maximum firings (default 1; ``count=0`` = unlimited).
* ``ms=N``    — delay duration for ``kind=delay`` (default 50).
* ``value=N`` — the injected value for ``kind=value``.
* ``dead=N``  — dead-peer count for ``kind=worker_lost`` (default 1).

Kinds (which seams honor which kind is the seam table in
docs/api/faults.md):

=============  ==========================================================
``error``      raise :class:`InjectedFault` (permanent — never retried)
``transient``  raise :class:`TransientFault` (healed by ``faults.retry``)
``delay``      ``time.sleep(ms)`` — a straggler / slow device
``value``      seam reads an injected value (heartbeat dead count)
``worker_lost``  raise :class:`mxnet_tpu.dist.WorkerLost` (elastic path)
``flood``      boolean fire — the serving queue treats itself as full
``bitflip``    flip one byte of a committed artifact file
``truncate``   truncate a committed artifact file to half its size
``grad_nonfinite``  poison one step's batch with NaN (numeric seam)
``loss_spike``      scale one step's batch by ``value=`` (default 1000)
``param_bitflip``   corrupt one restored parameter element (read SDC)
=============  ==========================================================

Every firing appends one incident to the plan's transcript (and, via
:mod:`mxnet_tpu.faults`, to the telemetry ``faults.*`` counters and the
FlightRecorder event ring) — the chaos-soak gate asserts the recorded
incidents are EXACTLY the planned ones.
"""
from __future__ import annotations

import json
import threading
import time

from ..base import MXNetError

__all__ = ["FaultError", "InjectedFault", "TransientFault", "FaultRule",
           "FaultPlan", "KINDS", "NUMERIC_KINDS", "PARAM_KINDS"]

KINDS = ("error", "transient", "delay", "value", "worker_lost", "flood",
         "bitflip", "truncate", "grad_nonfinite", "loss_spike",
         "param_bitflip")

# which kinds each seam entry point (faults.check/value/fires/
# corrupt_file/poison/corrupt_params) dispatches — a rule whose kind
# the site's entry point does not honor simply never fires there
# (documented in the seam table)
RAISING_KINDS = ("error", "transient", "worker_lost", "delay")
VALUE_KINDS = ("value",)
FLOOD_KINDS = ("flood",)
FILE_KINDS = ("bitflip", "truncate")
# numeric seams (the training-guardian drivers, mxnet_tpu.guardian):
# grad_nonfinite poisons a step's batch with NaN (non-finite
# loss/grads/params downstream); loss_spike scales it by a large
# finite factor (``value=``, default 1000) — a finite-but-poisonous
# batch; param_bitflip corrupts one restored parameter element's bit
# pattern at the checkpoint-restore hand-off (a read-path SDC)
NUMERIC_KINDS = ("grad_nonfinite", "loss_spike")
PARAM_KINDS = ("param_bitflip",)

# behavior/trigger keys that are NOT context matches
_RESERVED = ("nth", "prob", "count", "ms", "value", "dead")


class FaultError(MXNetError):
    """Base class of every plan-injected failure."""


class InjectedFault(FaultError):
    """A permanent injected failure — recovery must route around it
    (fallback entry, worker restart, failed future), never retry it."""


class TransientFault(InjectedFault):
    """A retryable injected failure — :func:`mxnet_tpu.faults.retry`
    heals it with bounded jittered backoff."""


def splitmix64(x):
    """One SplitMix64 scramble step (the TransformIter/DeviceAugment
    seeding discipline): adjacent inputs land on unrelated outputs,
    and the value is a pure function of its input."""
    x = (x + 0x9e3779b97f4a7c15) & 0xffffffffffffffff
    x = ((x ^ (x >> 30)) * 0xbf58476d1ce4e5b9) & 0xffffffffffffffff
    x = ((x ^ (x >> 27)) * 0x94d049bb133111eb) & 0xffffffffffffffff
    return x ^ (x >> 31)


def fold(*parts):
    """Fold integers into one 64-bit SplitMix draw."""
    x = 0
    for p in parts:
        x = splitmix64((x ^ (int(p) & 0xffffffffffffffff)))
    return x


def _coerce(text):
    """Grammar values: int when int-like, float when float-like, else
    the raw string."""
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        return text


class FaultRule(object):
    """One ``(site, trigger, kind)`` entry of a plan (module docstring
    has the grammar). Build directly or via :meth:`parse`."""

    def __init__(self, site, kind, nth=None, prob=None, count=1,
                 match=None, args=None):
        self.site = str(site)
        self.kind = str(kind)
        if self.kind not in KINDS:
            raise MXNetError("unknown fault kind %r (known: %s)"
                             % (kind, ", ".join(KINDS)))
        if nth is not None and prob is not None:
            raise MXNetError("rule %s:%s: nth= and prob= are exclusive "
                             "triggers" % (self.site, self.kind))
        self.nth = int(nth) if nth is not None else None
        if self.nth is not None and self.nth < 1:
            raise MXNetError("nth= is 1-based (got %d)" % self.nth)
        self.prob = float(prob) if prob is not None else None
        self.count = int(count)
        self.match = dict(match or {})
        self.args = dict(args or {})
        self.evals = 0      # evaluations of this rule's site
        self.fired = 0      # times this rule actually fired

    @classmethod
    def parse(cls, text):
        """``site:kind[@k=v,...]`` -> FaultRule."""
        text = text.strip()
        head, _, tail = text.partition("@")
        site, sep, kind = head.partition(":")
        if not sep or not site.strip() or not kind.strip():
            raise MXNetError(
                "fault rule %r does not parse: expected "
                "'site:kind[@key=value,...]'" % text)
        kw = {"match": {}, "args": {}}
        for item in filter(None, (s.strip() for s in tail.split(","))):
            key, sep, val = item.partition("=")
            if not sep:
                raise MXNetError("fault rule %r: %r is not key=value"
                                 % (text, item))
            key, val = key.strip(), _coerce(val.strip())
            if key in ("nth", "prob", "count"):
                kw[key] = val
            elif key in ("ms", "value", "dead"):
                kw["args"][key] = val
            else:
                kw["match"][key] = val
        return cls(site.strip(), kind.strip(), **kw)

    def describe(self):
        bits = []
        if self.nth is not None:
            bits.append("nth=%d" % self.nth)
        if self.prob is not None:
            bits.append("prob=%g" % self.prob)
        bits += ["%s=%s" % kv for kv in sorted(self.match.items())]
        bits += ["%s=%s" % kv for kv in sorted(self.args.items())]
        spec = "%s:%s" % (self.site, self.kind)
        return spec + ("@" + ",".join(bits) if bits else "")

    def to_dict(self):
        return {"site": self.site, "kind": self.kind, "nth": self.nth,
                "prob": self.prob, "count": self.count,
                "match": dict(self.match), "args": dict(self.args)}

    # ----------------------------------------------------------- firing
    def _matches(self, ctx):
        for key, want in self.match.items():
            if key not in ctx or ctx[key] != want:
                return False
        return True

    def should_fire(self, ctx, seed, index):
        """Evaluate one seam hit against this rule (advances the
        rule's evaluation counter). Pure given (plan seed, rule index,
        counter state) — no wall clock, no global RNG."""
        self.evals += 1
        if self.count and self.fired >= self.count:
            return False
        if not self._matches(ctx):
            return False
        if self.nth is not None:
            return self.evals == self.nth
        if self.prob is not None:
            draw = fold(seed, index, self.evals) / float(1 << 64)
            return draw < self.prob
        # pure context match: fire every matching evaluation (bounded
        # by count, default 1)
        return True


class FaultPlan(object):
    """A seeded list of :class:`FaultRule` entries plus the incident
    transcript their firings produce. Thread-safe: seams are evaluated
    from stager/worker/batcher threads."""

    def __init__(self, rules, seed=0):
        self.rules = []
        for r in rules:
            self.rules.append(r if isinstance(r, FaultRule)
                              else FaultRule.parse(r) if isinstance(r, str)
                              else FaultRule(**r))
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._transcript = []
        self._seq = 0

    # ---------------------------------------------------------- parsing
    @classmethod
    def parse(cls, spec, seed=0):
        """Build a plan from the grammar string (rules separated by
        ``;``), a JSON list (text beginning ``[``), or a file path
        prefixed ``@`` containing either."""
        spec = str(spec).strip()
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                spec = f.read().strip()
        if spec.startswith("["):
            entries = json.loads(spec)
            return cls([FaultRule(**e) if isinstance(e, dict)
                        else FaultRule.parse(e) for e in entries],
                       seed=seed)
        rules = [FaultRule.parse(part)
                 for part in filter(None, (s.strip()
                                           for s in spec.split(";")))]
        if not rules:
            return cls([], seed=seed)
        return cls(rules, seed=seed)

    def describe(self):
        return {"seed": self.seed,
                "rules": [r.describe() for r in self.rules]}

    # --------------------------------------------------------- evaluate
    def evaluate(self, site, ctx, kinds):
        """All rules for ``site`` (restricted to the entry point's
        ``kinds``) that fire on this evaluation; appends one incident
        per firing to the transcript."""
        fired = []
        with self._lock:
            for i, rule in enumerate(self.rules):
                if rule.site != site or rule.kind not in kinds:
                    continue
                if rule.should_fire(ctx, self.seed, i):
                    rule.fired += 1
                    self._seq += 1
                    incident = {
                        "seq": self._seq,
                        "site": site,
                        "kind": rule.kind,
                        "rule": rule.describe(),
                        "ctx": {k: v for k, v in sorted(ctx.items())},
                    }
                    self._transcript.append(incident)
                    fired.append((rule, incident))
        return fired

    def draw(self, *parts):
        """A deterministic 64-bit draw in the plan's seeded stream
        (corruption offsets, jitter)."""
        return fold(self.seed, *parts)

    # -------------------------------------------------------- reporting
    def incidents(self):
        """The incident transcript so far, oldest first."""
        with self._lock:
            return [dict(i) for i in self._transcript]

    def unfired(self):
        """Deterministic rules (nth / pure context match) that never
        fired — a chaos gate asserts this is empty, so a plan that
        silently missed its target step fails loudly."""
        with self._lock:
            return [r.describe() for r in self.rules
                    if r.prob is None and r.fired == 0]

    def sleep(self, seconds):
        """The delay-kind clock (separated for tests to stub)."""
        time.sleep(seconds)
