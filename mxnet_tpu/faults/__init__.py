"""mxnet_tpu.faults — deterministic fault injection + shared recovery.

Failure is an input, not an accident: a :class:`FaultPlan` (a seeded
list of ``(site, trigger, kind)`` rules — grammar in
:mod:`mxnet_tpu.faults.plan`) is **armed** process-wide, and named
injection seams threaded through the stack evaluate it —

=====================  ===========  =================================
site                   entry point  where it lives
=====================  ===========  =================================
``dist.connect``       check        bootstrap coordinator connect
``dist.heartbeat``     value        HeartbeatMonitor dead-node probe
``dist.straggler``     check        VirtualFeed per-host slice clock
``dist.worker``        check        ElasticTrainer per-batch check
``checkpoint.commit``  check        between entry write and rename
``checkpoint.shard``   corrupt      a committed shard file
``checkpoint.manifest``  corrupt    a committed manifest
``data.transform``     check        TransformIter worker apply
``data.stager``        check        DeviceLoader stage entry
``data.device_put``    check        DeviceLoader device placement
``serving.worker``     check        DynamicBatcher launch path
``serving.device``     check        Predictor device launch
``serving.queue_flood``  fires      DynamicBatcher submit
``serving.cache``      corrupt      a committed executable entry
``serving.decode_worker``  check    DecodeEngine scheduler tick
``serving.decode_step``  check      DecodeEngine per-step launch
``serving.decode_abandon``  fires   DecodeEngine mid-stream abandon
``module.step``        poison       fit step boundary (numeric seam)
``checkpoint.params``  corrupt_params  restore hand-off (read SDC)
``guardian.sdc``       value        SDC probe's second launch
``autopilot.poll``     check        Autopilot controller tick
``autopilot.scale``    check        ReplicaPool spin-up path
``gateway.accept``     fires        GatewayServer edge admission
``gateway.route``      check        Router replica selection
``gateway.stream``     check        GatewayServer token-stream flush
=====================  ===========  =================================

The discipline is ``telemetry.enabled()``'s: an UNARMED process pays
one module-attribute branch per seam (``faults.armed()``) and is
bitwise-identical to a build without the seams (pinned by
tests/test_faults.py). Armed, every firing is recorded — the plan's
incident transcript, the ``faults.*`` telemetry counters, and a
FlightRecorder ``fault_injected`` event — so a chaos gate can assert
the incidents that happened are EXACTLY the ones planned.

:func:`retry` is the shared bounded jittered-backoff helper every
transient seam heals through (the PR-6 connect idiom, extracted).

Env: ``MXNET_FAULT_PLAN`` arms a plan at import (grammar string, JSON,
or ``@file``); ``MXNET_FAULT_SEED`` seeds it; ``MXNET_FAULT_RETRIES``/
``MXNET_FAULT_BACKOFF`` set the retry defaults.
"""
from __future__ import annotations

import glob as _glob
import logging
import os
import threading

from ..base import MXNetError
from .plan import (FaultError, FaultPlan, FaultRule, InjectedFault,
                   TransientFault, KINDS, RAISING_KINDS, VALUE_KINDS,
                   FLOOD_KINDS, FILE_KINDS, NUMERIC_KINDS, PARAM_KINDS)
from .retry import retry

__all__ = ["FaultError", "InjectedFault", "TransientFault", "FaultRule",
           "FaultPlan", "KINDS", "retry", "arm", "disarm", "armed",
           "active", "check", "value", "fires", "corrupt_file",
           "poison", "corrupt_params", "incidents"]

_log = logging.getLogger("mxnet_tpu.faults")
_PLAN = None
_lock = threading.Lock()


def armed():
    """Whether a plan is armed — THE one branch an unarmed seam costs
    (the ``telemetry.enabled()`` discipline)."""
    return _PLAN is not None


def active():
    """The armed :class:`FaultPlan`, or None."""
    return _PLAN


def arm(plan, seed=None):
    """Arm ``plan`` process-wide (a :class:`FaultPlan`, a grammar/JSON
    string, or a ``@file`` path). Returns the armed plan. Re-arming
    replaces the previous plan."""
    global _PLAN
    if not isinstance(plan, FaultPlan):
        plan = FaultPlan.parse(plan, seed=int(seed or 0))
    elif seed is not None:
        plan.seed = int(seed)
    with _lock:
        _PLAN = plan
    if plan.rules:
        _log.warning("fault plan ARMED (seed %d): %s", plan.seed,
                     "; ".join(r.describe() for r in plan.rules))
    return plan


def disarm():
    """Disarm (idempotent); the previous plan stays readable for its
    transcript."""
    global _PLAN
    with _lock:
        prev, _PLAN = _PLAN, None
    return prev


def incidents():
    """The armed plan's incident transcript ([] when unarmed)."""
    plan = _PLAN
    return plan.incidents() if plan is not None else []


# ---------------------------------------------------------------------------
# incident recording
# ---------------------------------------------------------------------------
def _note_retry(site, gave_up=False):
    """Count one retry (or give-up) into the telemetry registry."""
    from .. import telemetry
    scope = telemetry.registry().scope("faults")
    scope.counter("retry_giveups" if gave_up else "retries").add()


def _record(incident):
    """One fired rule -> telemetry counter + FlightRecorder event (the
    'exactly the planned incidents' witness surface)."""
    from .. import telemetry
    telemetry.registry().scope("faults").counter("injected").add()
    telemetry.flight_recorder().note(
        "fault_injected", site=incident["site"],
        fault_kind=incident["kind"], seq=incident["seq"],
        ctx=incident["ctx"])
    _log.warning("fault injected: %s (%s) ctx=%r", incident["site"],
                 incident["kind"], incident["ctx"])


# ---------------------------------------------------------------------------
# seam entry points (each site uses exactly ONE — see the seam table)
# ---------------------------------------------------------------------------
def check(site, **ctx):
    """Raising/delaying seam. Fired ``delay`` rules sleep; fired
    ``error``/``transient``/``worker_lost`` rules raise. Returns the
    fired incidents (usually ignored). No-op unless armed."""
    plan = _PLAN
    if plan is None:
        return []
    fired = plan.evaluate(site, ctx, RAISING_KINDS)
    # record + apply delays for EVERY fired rule first: a raising rule
    # must not leave a co-fired rule's incident unrecorded (the plan
    # transcript and the FlightRecorder must stay 1:1)
    out = []
    for _rule, incident in fired:
        _record(incident)
        out.append(incident)
    for rule, _incident in fired:
        if rule.kind == "delay":
            plan.sleep(float(rule.args.get("ms", 50)) / 1000.0)
    for rule, _incident in fired:
        if rule.kind == "delay":
            continue
        if rule.kind == "transient":
            raise TransientFault(
                "injected transient fault at %s (%s)"
                % (site, rule.describe()))
        if rule.kind == "worker_lost":
            from ..dist.elastic import WorkerLost
            raise WorkerLost(
                "injected worker loss at %s (%s)"
                % (site, rule.describe()),
                dead_count=int(rule.args.get("dead", 1)))
        raise InjectedFault(
            "injected fault at %s (%s)" % (site, rule.describe()))
    return out


def value(site, default, **ctx):
    """Value seam: the first fired ``value`` rule's injected value,
    else ``default`` (the heartbeat dead-node count)."""
    plan = _PLAN
    if plan is None:
        return default
    fired = plan.evaluate(site, ctx, VALUE_KINDS)
    for _rule, incident in fired:
        # every fired rule records (transcript and FlightRecorder stay
        # 1:1) even though only the first rule's value is returned
        _record(incident)
    if fired:
        return fired[0][0].args.get("value", default)
    return default


def fires(site, **ctx):
    """Boolean seam: True when a ``flood`` rule fired (the serving
    queue then behaves as if at capacity)."""
    plan = _PLAN
    if plan is None:
        return False
    fired = plan.evaluate(site, ctx, FLOOD_KINDS)
    for _rule, incident in fired:
        _record(incident)
    return bool(fired)


def corrupt_file(site, root, pattern="*", **ctx):
    """Corruption seam: apply a fired ``bitflip``/``truncate`` rule to
    one committed artifact file under ``root`` matching ``pattern``.
    The target file and the flipped byte are plan-seeded draws — the
    same plan poisons the same byte every run. Returns the mutated
    path (or None)."""
    plan = _PLAN
    if plan is None:
        return None
    fired = plan.evaluate(site, ctx, FILE_KINDS)
    mutated = None
    for rule, incident in fired:
        _record(incident)
        candidates = sorted(
            p for p in _glob.glob(os.path.join(str(root), pattern))
            if os.path.isfile(p))
        if not candidates:
            _log.warning("fault %s fired but no file matches %s/%s",
                         site, root, pattern)
            continue
        path = candidates[plan.draw(incident["seq"], 1)
                          % len(candidates)]
        size = os.path.getsize(path)
        if rule.kind == "truncate":
            with open(path, "r+b") as f:
                f.truncate(max(size // 2, 1))
            _log.warning("fault: truncated %s to %d bytes", path,
                         max(size // 2, 1))
        else:
            off = plan.draw(incident["seq"], 2) % max(size, 1)
            with open(path, "r+b") as f:
                f.seek(off)
                byte = f.read(1)
                f.seek(off)
                f.write(bytes([(byte[0] if byte else 0) ^ 0xFF]))
            _log.warning("fault: flipped byte %d of %s", off, path)
        incident["target"] = os.path.basename(path)
        mutated = path
    return mutated


def poison(site, **ctx):
    """Numeric seam (the :mod:`mxnet_tpu.guardian` drivers): the batch
    multiplier a fired numeric rule injects at the step boundary —
    ``float('nan')`` for ``grad_nonfinite`` (non-finite loss/grads/
    params downstream), the rule's ``value=`` (default 1000) for
    ``loss_spike`` (a finite but poisonous batch) — or None when
    nothing fired. The fit loops apply the factor to the step's first
    floating data input. No-op unless armed."""
    plan = _PLAN
    if plan is None:
        return None
    fired = plan.evaluate(site, ctx, NUMERIC_KINDS)
    factor = None
    for rule, incident in fired:
        # every fired rule records (transcript and FlightRecorder stay
        # 1:1) even though only the first rule's factor applies
        _record(incident)
        if factor is None:
            factor = float("nan") if rule.kind == "grad_nonfinite" \
                else float(rule.args.get("value", 1000.0))
    return factor


def corrupt_params(site, params, **ctx):
    """Restore-hand-off SDC seam: a fired ``param_bitflip`` rule
    corrupts ONE element of one restored float parameter array IN
    PLACE — the element's bit pattern is forced to a quiet-NaN, the
    deterministic spelling of a silent read-path corruption the
    guardian's param sentinel (or its post-restore verification) must
    catch. Target array and element are plan-seeded draws. Returns the
    corrupted array name (or None)."""
    plan = _PLAN
    if plan is None:
        return None
    fired = plan.evaluate(site, ctx, PARAM_KINDS)
    target = None
    import numpy as onp
    for _rule, incident in fired:
        _record(incident)
        names = sorted(n for n, a in params.items()
                       if hasattr(a, "dtype")
                       and onp.issubdtype(onp.dtype(a.dtype),
                                          onp.floating)
                       and getattr(a, "size", 0) > 0)
        if not names:
            _log.warning("fault %s fired but no float param to corrupt",
                         site)
            continue
        name = names[plan.draw(incident["seq"], 1) % len(names)]
        arr = params[name]
        idx = plan.draw(incident["seq"], 2) % arr.size
        flat = arr.reshape(-1)
        if flat.dtype == onp.float32:
            # force a quiet-NaN bit pattern (exponent all-ones +
            # mantissa MSB) — guaranteed non-finite whatever the
            # element held, unlike a single-bit flip
            bits = flat.view(onp.uint32)
            bits[idx] |= onp.uint32(0x7FC00000)
        else:
            flat[idx] = onp.nan
        incident["target"] = name
        incident["element"] = int(idx)
        _log.warning("fault: corrupted %s[%d] of restored params",
                     name, idx)
        target = name
    return target


def _autostart():
    spec = os.environ.get("MXNET_FAULT_PLAN")
    if spec:
        arm(spec, seed=int(os.environ.get("MXNET_FAULT_SEED", "0")))


_autostart()
