"""Bounded jittered-backoff retry — THE one recovery idiom.

PR 6's coordinator-connect loop hand-rolled bounded exponential
backoff; the fault-injection plane needs the same discipline at every
transient seam (device staging, transform workers, checkpoint
commits). This module is that loop, extracted once:

* **bounded** — a component that cannot heal must fail loudly, not
  spin forever (the bootstrap contract, kept);
* **jittered deterministically** — the jitter is a SplitMix fold of
  ``(seed, site, attempt)``, never wall time or ``random``, so a
  seeded chaos run retries on the same schedule every time (and the
  bitwise contracts survive: retries change WHEN bytes move, never
  which bytes);
* **selective** — only ``retry_on`` exception types are retried;
  :class:`~mxnet_tpu.faults.TransientFault` by default. A permanent
  :class:`~mxnet_tpu.faults.InjectedFault` (or any real bug) propagates
  on the first throw.

Attempts and give-ups count into the telemetry ``faults.retries`` /
``faults.retry_giveups`` counters so a fleet quietly riding its retry
budget is visible on a scrape.
"""
from __future__ import annotations

import logging
import os
import time

from .plan import TransientFault, fold

__all__ = ["retry"]

_log = logging.getLogger("mxnet_tpu.faults")


def _zlib_site(site):
    import zlib
    return zlib.crc32(str(site).encode("utf-8")) & 0xFFFFFFFF


def retry(fn, retries=None, backoff_s=None, max_backoff_s=30.0,
          jitter=0.25, retry_on=None, seed=0, site="retry", sleep=None,
          logger=None):
    """Call ``fn()`` with bounded exponential backoff.

    Parameters
    ----------
    fn : callable
        The attempt; its return value is returned on success.
    retries : int
        Retries AFTER the first attempt (total attempts = retries+1).
        Default ``MXNET_FAULT_RETRIES`` (3).
    backoff_s : float
        Base delay before the first retry; doubles per retry, capped
        at ``max_backoff_s``. Default ``MXNET_FAULT_BACKOFF`` (0.05).
    jitter : float
        Relative jitter amplitude: each delay is scaled by
        ``1 + jitter * u`` with ``u`` in [-1, 1) drawn from the
        deterministic ``(seed, site, attempt)`` SplitMix fold. 0
        disables (the bootstrap spelling, whose backoff is pinned).
    retry_on : tuple of exception types
        What heals by retrying. Default ``(TransientFault,)``.
    seed, site : int, str
        The jitter stream coordinates; ``site`` also names the retry
        in logs and counters.
    sleep : callable, optional
        Injection point for tests; default ``time.sleep``.

    Returns ``fn()``'s value; re-raises the LAST exception once the
    attempt budget is exhausted (callers wanting a domain-specific
    give-up message catch and wrap it).
    """
    if retry_on is None:
        # fast path: the default retry_on can only ever catch an
        # injection, so an UNARMED process skips the whole retry
        # scaffolding (env lookups, site hashing) — the seam-cost
        # discipline applies to the wrapper too
        from . import armed
        if not armed():
            return fn()
        retry_on = (TransientFault,)
    if retries is None:
        retries = int(os.environ.get("MXNET_FAULT_RETRIES", "3"))
    if backoff_s is None:
        backoff_s = float(os.environ.get("MXNET_FAULT_BACKOFF", "0.05"))
    log = logger or _log
    site_key = None
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as exc:  # noqa: B030 - caller-supplied types
            attempt += 1
            from . import _note_retry
            if attempt > retries:
                _note_retry(site, gave_up=True)
                raise
            delay = min(backoff_s * (2 ** (attempt - 1)), max_backoff_s)
            if jitter:
                if site_key is None:
                    site_key = _zlib_site(site)
                u = fold(seed, site_key, attempt) / float(1 << 63) - 1.0
                delay *= max(0.0, 1.0 + jitter * u)
            _note_retry(site)
            log.warning("%s: attempt %d/%d failed (%s); retrying in "
                        "%.3fs", site, attempt, retries + 1, exc, delay)
            (sleep or time.sleep)(delay)
