"""KVStore — parameter aggregation API over XLA collectives.

TPU-native replacement for src/kvstore/ (1,139 LoC) + ps-lite. The reference
builds reduce/broadcast trees over GPU P2P (comm.h CommCPU/CommDevice) and a
ZMQ parameter server for multi-host (kvstore_dist.h); here

* ``local``/``device``: per-device gradients are summed with jnp adds (XLA
  emits the all-reduce; on one chip it's a fused sum) and broadcast back by
  device_put — no staging buffers, no P2P management;
* ``dist_sync``/``dist_device_sync``: multi-process sums ride
  ``parallel.dist`` (jax.distributed + psum over ICI/DCN); on a single
  process they degrade to ``local`` with rank 0 / size 1 — exactly how the
  reference's tests exercise dist semantics locally (SURVEY.md §4);
* ``dist_async``: same collectives, staleness-1 — each push dispatches the
  current reduction and applies the previous one, so no rank stalls on a
  straggler (see ``create()``'s design note);
* the server processes, heartbeats and barrier of ps-lite disappear; the
  KVStore *API* (init/push/pull/set_optimizer/rank/num_workers/barrier)
  stays for compatibility (include/mxnet/kvstore.h:26-303).

Reduction order is fixed (ascending device index) so summed results are
bitwise deterministic, matching the dist_sync test contract
(tests/nightly/dist_sync_kvstore.py:36-46).
"""
from __future__ import annotations

import os
import pickle

from .base import MXNetError
from . import optimizer as opt
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


def _drain_pending(ctx, best_effort=True):
    """THE drain for dist_async's in-flight reductions — shared by
    barrier() (errors propagate) and the exit finalizer (best-effort: the
    dist backend may already be torn down; no ref to the store object so
    the finalizer cannot resurrect it)."""
    if best_effort and not ctx["enabled"]:
        return
    pending, store = ctx["pending"], ctx["store"]
    for k in sorted(list(pending), key=str):
        thunk = pending.pop(k)
        try:
            effective = thunk()
            if ctx["updater"] is not None:
                ctx["updater"](k, effective, store[k])
            else:
                store[k] = effective
        except Exception:  # pragma: no cover - teardown race
            if not best_effort:
                raise
            return


def _key_list(key):
    return key if isinstance(key, (list, tuple)) else [key]


def _val_list(key, value):
    if isinstance(key, (list, tuple)):
        assert isinstance(value, (list, tuple)) and len(key) == len(value)
        return list(value)
    return [value]


class KVStore(object):
    """Key-value store for data synchronization over devices/hosts."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._barrier_before_exit = True
        self._compress = "none"
        # dist_async: per-key in-flight reduction from the PREVIOUS push
        # (staleness-1 delayed application; see push())
        self._pending = {}
        if kind.startswith("dist"):
            # legacy dist_* stores ride the mxnet_tpu.dist runtime (the
            # ps-lite replacement): same coordination service, same
            # deterministic psum collectives as the global-mesh fit path
            from . import dist as _dist
            self._dist = _dist.get_runtime()
        else:
            self._dist = None
        if kind == "dist_async":
            # exit safety net for the staleness-1 schedule: drain any
            # still-in-flight reduction when the store is collected or the
            # interpreter exits, honoring set_barrier_before_exit — so the
            # 'every gradient applied exactly once' contract holds even
            # for loops that never call barrier() themselves
            import weakref
            self._flush_ctx = {"pending": self._pending,
                               "store": self._store,
                               "updater": None, "enabled": True}
            self._flush_finalizer = weakref.finalize(
                self, _drain_pending, self._flush_ctx)

    # ------------------------------------------------------------- basics
    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return self._dist.rank if self._dist else 0

    @property
    def num_workers(self):
        return self._dist.size if self._dist else 1

    def init(self, key, value):
        """Initialize key(s) with value(s); later push/pull use these keys."""
        for k, v in zip(_key_list(key), _val_list(key, value)):
            if k in self._store:
                raise MXNetError("duplicate init of key %s" % str(k))
            self._store[k] = v.copy()

    def push(self, key, value, priority=0):
        """Aggregate value(s) into the store (KVStore::Push).

        ``value`` may be a list of per-device NDArrays — they are summed in
        fixed device order. With an updater set (update_on_kvstore), the
        updater merges the aggregated gradient into the stored weight;
        otherwise the aggregate replaces the stored value for ``pull``.
        """
        for k, v in zip(_key_list(key), _val_list(key, value)):
            if isinstance(v, (list, tuple)):
                merged = v[0].copy()
                for other in v[1:]:
                    merged += other.as_in_context(merged.context)
            else:
                merged = v.copy()
            if k not in self._store:
                raise MXNetError("please init key %s first" % str(k))
            if self._kind == "dist_async" and self._dist is not None:
                # staleness-1 delayed application — the TPU-native form
                # of the reference's async mode (kvstore_dist_server.h
                # applies pushes on arrival, unordered; SPMD collectives
                # are inherently barriers, so instead of dropping the
                # barrier we move it one step back): DISPATCH this
                # step's cross-worker reduction (allreduce_async — the
                # enqueue returns immediately) and apply the PREVIOUS
                # step's, whose materialization has had a whole step of
                # compute to complete — so no rank stalls in push() on a
                # straggler's in-flight gradient. Deterministic (fixed
                # staleness, fixed reduction order), unlike the
                # reference's async. Cold start: the first push only
                # dispatches (no update runs before the first gradient
                # lands, matching the reference's apply-on-arrival); the
                # final reduction is applied at the closing barrier() —
                # reached via Module.fit's end-of-training drain or the
                # exit finalizer (set_barrier_before_exit) — so every
                # gradient is applied exactly once.
                pending = self._pending.get(k)
                self._pending[k] = self._dist.allreduce_async(merged)
                if pending is None:
                    continue
                effective = pending()
                if self._updater is not None:
                    self._updater(k, effective, self._store[k])
                else:
                    self._store[k] = effective
                continue
            if self._dist is not None:
                merged = self._dist.allreduce(merged)
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                self._store[k] = merged

    def pull(self, key, out=None, priority=0):
        """Broadcast stored value(s) to out array(s) (KVStore::Pull)."""
        assert out is not None
        for k, o in zip(_key_list(key), _val_list(key, out)):
            src = self._store.get(k)
            if src is None:
                raise MXNetError("please init key %s first" % str(k))
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                src.copyto(t)

    # ---------------------------------------------------------- optimizer
    def set_optimizer(self, optimizer):
        """Register an optimizer; in dist mode the reference pickles it to
        the servers (kvstore.py:set_optimizer) — here every process applies
        the same deterministic update locally, so we just install it."""
        self._optimizer = optimizer
        self._set_updater(opt.get_updater(optimizer))

    def _set_updater(self, updater):
        self._updater = updater
        if hasattr(self, "_flush_ctx"):
            self._flush_ctx["updater"] = updater

    def _send_command_to_servers(self, head, body):
        """With no server processes, commands loop back to a controller
        registered in-process via MXKVStoreRunServer (reference
        kvstore_dist.h SendCommandToServers -> server controller)."""
        ctrl = getattr(self, "_server_controller", None)
        if ctrl is not None:
            ctrl(int(head), str(body))

    # -------------------------------------------------------- dist compat
    def barrier(self):
        # dist_async: a barrier is the quiesce point — flush the in-flight
        # staleness-1 reductions so no trailing gradient is ever lost
        # (push() comment; one drain implementation shared with the exit
        # finalizer — _drain_pending)
        if hasattr(self, "_flush_ctx"):
            _drain_pending(self._flush_ctx, best_effort=False)
        if self._dist is not None:
            self._dist.barrier()

    def _barrier(self):
        self.barrier()

    def set_barrier_before_exit(self, barrier_before_exit):
        self._barrier_before_exit = barrier_before_exit
        if hasattr(self, "_flush_ctx"):
            self._flush_ctx["enabled"] = bool(barrier_before_exit)

    @property
    def num_dead_node(self):
        return 0

    def get_num_dead_node(self, node_id, timeout=60):
        """Failure detection (kvstore.h:242): with the PS gone, liveness is
        the JAX distributed runtime's concern; report via parallel.dist."""
        if self._dist is not None:
            return self._dist.num_dead_nodes(timeout)
        return 0

    def save_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot save states for distributed training"
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())


def create(name="local"):
    """Create a KVStore: local | device | dist_sync | dist_device_sync |
    dist_async (KVStore::Create, src/kvstore/kvstore.cc:17-45).

    .. deprecated::
        The ``dist_*`` types are the LEGACY multi-host surface, kept so
        reference launch scripts (``tools/launch.py`` + ``DMLC_*`` env)
        keep working: they now route onto the :mod:`mxnet_tpu.dist`
        runtime (``jax.distributed`` bootstrap + global-mesh psum —
        there are no server processes to talk to). New code should let
        ``Module.fit`` run on the global mesh directly (see
        docs/api/dist.md): the kvstore push/pull hop adds a host
        round-trip per key that the fused global-mesh step does not
        pay, and elastic resume (``mxnet_tpu.dist.ElasticTrainer``)
        only drives the fit path.

    Design note on ``dist_async``: the reference's async mode lets each
    worker's update land on the parameter server unsynchronized —
    straggler tolerance bought with non-determinism
    (kvstore_dist_server.h:136-229). SPMD collectives are inherently
    barriers, so the TPU-native equivalent moves the barrier one step
    back instead of dropping it: each ``push`` *dispatches* the current
    gradient's cross-worker reduction and *applies the previous one*
    (staleness-1 delayed SGD). No rank ever waits on a straggler's
    in-flight gradient — the async mode's purpose — while results stay
    bitwise deterministic and rank-identical, which the reference's
    async never was. Cold start: the first push applies a zero
    gradient; the final in-flight reduction is flushed at ``barrier()``
    (the exit barrier drains end-of-training state), so every gradient
    is applied exactly once, one step late. Convergence behavior is
    that of one-step-delayed SGD.
    ``dist_sync``/``dist_device_sync`` are the exact synchronous path."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = ("local", "device", "local_allreduce_device",
             "local_allreduce_cpu", "dist_sync", "dist_device_sync",
             "dist_async", "dist")
    if name not in valid:
        raise MXNetError("unknown KVStore type %s" % name)
    return KVStore(name)
