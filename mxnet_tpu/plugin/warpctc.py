"""WarpCTC plugin op (reference plugin/warpctc/warpctc-inl.h).

The reference binds Baidu's warp-ctc library; here the op is the native
lax.scan CTC recursion (ops/sequence_loss.py) wrapped in the plugin's
exact contract, which differs from CTCLoss:

- data: 2D ``(input_length * minibatch, alphabet_size)`` — time-major
  flattened activations (warpctc-inl.h InferShape requires ndim==2)
- label: ``(minibatch * label_length,)`` 0-padded, blank = 0
- output: softmax(data), same shape as data; the backward pass ignores
  the head gradient and injects d(sum CTC loss)/d(logits), the
  SoftmaxOutput pattern.
"""
from __future__ import annotations

from ..registry import register


def _jnp():
    import jax.numpy as jnp
    return jnp


def _warpctc_infer(attrs, in_shapes, aux):
    data = in_shapes[0]
    out = [data] if data is not None else None
    if data is not None and in_shapes[1] is None:
        T = int(attrs["input_length"])
        L = int(attrs["label_length"])
        n = data[0] // T
        in_shapes = [data, (n * L,)]
    return in_shapes, out, aux


@register("WarpCTC", arg_names=("data", "label"),
          attr_types={"label_length": int, "input_length": int},
          infer_shape=_warpctc_infer, num_outputs=1,
          backward_ignores_head_grads=True)
def _warpctc(attrs, ins, octx):
    import jax
    jnp = _jnp()
    from ..ops.sequence_loss import _ctc_loss_single

    T = int(attrs["input_length"])
    L = int(attrs["label_length"])
    data, label = ins

    @jax.custom_vjp
    def f(data, label):
        return jax.nn.softmax(data, axis=-1)

    def f_fwd(data, label):
        return jax.nn.softmax(data, axis=-1), (data, label)

    def f_bwd(res, g):
        data, label = res
        n = data.shape[0] // T
        logits = data.reshape(T, n, data.shape[-1])
        labels = label.reshape(n, L).astype("int32")

        def total_loss(lg):
            lp = jax.nn.log_softmax(lg, axis=-1)
            losses = jax.vmap(
                lambda lp_n, lab_n: _ctc_loss_single(jnp, lp_n, lab_n, 0),
                in_axes=(1, 0))(lp, labels)
            return jnp.sum(losses)

        grad = jax.grad(total_loss)(logits).reshape(data.shape)
        return grad, jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return [f(data, label)]
