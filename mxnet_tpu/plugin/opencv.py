"""OpenCV plugin (reference plugin/opencv/opencv.py + cv_api.cc).

The reference routes cv2 decode/resize/border through C-API entry points
into NDArray; here the same surface wraps the framework's native image
kernels (ndarray._cvimdecode/_cvimresize/_cvcopyMakeBorder — cv2 when
present, PIL otherwise) and returns NDArrays.
"""
from __future__ import annotations

import random

from .. import ndarray as nd
from ..io import DataIter, DataBatch, DataDesc


def imdecode(str_img, flag=1):
    """Decode an encoded image buffer to an HWC NDArray, BGR channel
    order — cv2 semantics, like the reference plugin (opencv.py:13-28);
    mx.image.imdecode is the RGB-ordered counterpart."""
    return nd._cvimdecode(str_img, flag, to_rgb=False)


def resize(src, size, interp=2):
    """Resize ``src`` (HWC NDArray) to ``size`` = (w, h)."""
    return nd._cvimresize(src, size[0], size[1], interp)


def copyMakeBorder(src, top, bot, left, right, border_type=0, value=0):
    """Pad an HWC NDArray (cv2.copyMakeBorder semantics)."""
    return nd._cvcopyMakeBorder(src, top, bot, left, right, border_type,
                                value)


def scale_down(src_size, size):
    """Scale size down to fit in src_size, preserving aspect ratio."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop src at (x0, y0) size (w, h), optionally resize to ``size``."""
    out = nd.crop(src, begin=(y0, x0, 0), end=(y0 + h, x0 + w,
                                               int(src.shape[2])))
    if size is not None and (w, h) != size:
        out = resize(out, size, interp)
    return out


def random_crop(src, size):
    """Random crop to exactly ``size`` = (w, h); returns (img, (x0,y0,w,h))."""
    h, w, _ = src.shape
    new_w, new_h = scale_down((w, h), size)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size)
    return out, (x0, y0, new_w, new_h)


class ImageListIter(DataIter):
    """Iterator over (label, path) image lists with decode + resize
    (reference plugin/opencv/opencv.py ImageListIter)."""

    def __init__(self, root, flist, batch_size, size, mean=None):
        import os

        import numpy as onp
        super().__init__(batch_size)
        self.root = root
        self.list = list(flist)
        self.cur = 0
        self.batch_size = batch_size
        self.size = tuple(size)
        if mean is not None:
            self.mean = onp.array(mean, onp.float32)
        else:
            self.mean = None
        self.provide_data = [DataDesc(
            "data", (batch_size, self.size[1], self.size[0], 3))]
        self.provide_label = [DataDesc("label", (batch_size,))]
        self._os = os

    def reset(self):
        self.cur = 0

    def next(self):
        import numpy as onp
        if self.cur + self.batch_size > len(self.list):
            raise StopIteration
        imgs, labels = [], []
        for line in self.list[self.cur:self.cur + self.batch_size]:
            label, fname = line.split("\t")[:2]
            with open(self._os.path.join(self.root, fname.strip()),
                      "rb") as f:
                img = imdecode(f.read())
            img = resize(img, self.size)
            arr = img.asnumpy().astype(onp.float32)
            if self.mean is not None:
                arr -= self.mean
            imgs.append(arr)
            labels.append(float(label))
        self.cur += self.batch_size
        return DataBatch([nd.array(onp.stack(imgs))],
                         [nd.array(onp.array(labels, onp.float32))])
