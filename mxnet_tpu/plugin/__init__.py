"""Plugin namespace (reference plugin/ directory).

- ``warpctc`` — WarpCTC op with the Baidu-plugin contract, lowered onto
  the native lax.scan CTC (imported eagerly: registers ``mx.sym.WarpCTC``)
- ``caffe``  — CaffeOp/CaffeLoss: Caffe layer prototxts lowered to
  native symbols via tools/caffe_converter (no libcaffe)
- ``opencv`` — cv-style imdecode/resize/copyMakeBorder + ImageListIter
  over the framework's native/PIL image kernels

The reference's ``sframe`` plugin (SFrame database iterator) has no
counterpart: it binds the proprietary SFrame C++ SDK; use ImageRecordIter
or CSVIter.
"""
from . import warpctc  # noqa: F401  (registers the WarpCTC op)
from . import opencv  # noqa: F401
from .caffe import CaffeLoss, CaffeOp  # noqa: F401

# ops registered at plugin-import time need re-exposure on the sym/nd
# namespaces (they were populated at package import)
from .. import ndarray as _nd
from .. import symbol as _sym
_sym._init_symbol_module()
_nd._init_ndarray_module()

# reference scripts call mx.sym.CaffeOp / mx.sym.CaffeLoss (plugin/caffe
# registers them as symbols when built in)
_sym.CaffeOp = CaffeOp
_sym.CaffeLoss = CaffeLoss
