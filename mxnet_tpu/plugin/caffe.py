"""Caffe plugin: run Caffe-described layers as native symbols.

Reference counterpart: plugin/caffe/caffe_op.cc — there, CaffeOp embeds
libcaffe and executes the layer with Caffe's own kernels. Binding Caffe
is neither possible nor desirable here; instead the ``prototxt`` layer
string is parsed with the converter's schema (tools/caffe_converter) and
lowered to the equivalent native operator, so models scripted against
``mx.sym.CaffeOp`` keep working on TPU with XLA kernels.

    fc = mx.sym.CaffeOp(data, num_weight=2,
                        prototxt="layer{type:\\"InnerProduct\\" "
                                 "inner_product_param{num_output: 10}}")

Supported layer types: those of tools/caffe_converter/convert_symbol.py
minus the cross-layer BatchNorm+Scale fusion. CaffeLoss supports
SoftmaxWithLoss. CaffeDataIter is NOT provided — it reads LMDB/LevelDB
databases through libcaffe; use ImageRecordIter instead.
"""
from __future__ import annotations

import os
import sys


def _converter():
    """Import tools/caffe_converter from the repo layout."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    tools = os.path.join(root, "tools")
    if not os.path.isdir(os.path.join(tools, "caffe_converter")):
        raise ImportError(
            "tools/caffe_converter not found next to the mxnet_tpu "
            "package — the caffe plugin needs the converter's schema")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    from caffe_converter import caffe_parser, convert_symbol
    return caffe_parser, convert_symbol


def _parse_layer(prototxt):
    from google.protobuf import text_format
    caffe_parser, _ = _converter()
    pb2 = caffe_parser._pb2()
    lay = pb2.LayerParameter()
    txt = prototxt.strip()
    # accept both "layer { ... }" wrappers and bare LayerParameter bodies
    if txt.startswith("layer"):
        txt = txt[txt.index("{") + 1:txt.rindex("}")]
    try:
        text_format.Parse(txt, lay, allow_unknown_field=True)
    except TypeError:
        text_format.Parse(txt, lay)
    return lay


# weight-blob counts by layer type, where knowable (reference CaffeOp's
# num_weight declares how many trailing inputs are parameters)
_KNOWN_NUM_WEIGHT = {
    "Convolution": lambda lay: 2 if lay.convolution_param.bias_term else 1,
    "Deconvolution": lambda lay: 2 if lay.convolution_param.bias_term
    else 1,
    "InnerProduct": lambda lay: 2 if lay.inner_product_param.bias_term
    else 1,
    "ReLU": lambda lay: 0, "Sigmoid": lambda lay: 0,
    "TanH": lambda lay: 0, "Pooling": lambda lay: 0,
    "LRN": lambda lay: 0, "Dropout": lambda lay: 0,
    "Concat": lambda lay: 0, "Eltwise": lambda lay: 0,
    "Flatten": lambda lay: 0, "Reshape": lambda lay: 0,
    "Softmax": lambda lay: 0,
}


def CaffeOp(*data, prototxt="layer{}", num_data=1, num_weight=0,
            num_out=1, name=None, **kwargs):
    """Build the native symbol for a Caffe layer prototxt.

    ``data`` (positional or data_0..data_N kwargs): input symbols.
    num_weight/num_out are reference-API parameters; num_weight is
    checked against the layer type's actual parameter count when known.
    """
    import mxnet_tpu as mx

    _, convert_symbol_mod = _converter()
    lay = _parse_layer(prototxt)
    inputs = list(data)
    for i in range(num_data):
        key = "data_%d" % i
        if key in kwargs:
            inputs.append(kwargs.pop(key))
    if not inputs:
        raise ValueError("CaffeOp needs at least one input symbol")
    if num_out != 1:
        raise ValueError("only single-output Caffe layers are supported")

    t = lay.type
    if not t:
        raise ValueError("prototxt must set layer type")
    want = _KNOWN_NUM_WEIGHT.get(t)
    if want is not None and num_weight not in (0, want(lay)):
        raise ValueError(
            "num_weight=%d but a %s layer with this prototxt has %d "
            "parameter blobs" % (num_weight, t, want(lay)))
    if not lay.name:
        lay.name = name or t.lower()

    return convert_symbol_mod.build_layer(mx, lay, inputs,
                                          name=name or lay.name)


def CaffeLoss(data, label, prototxt='layer{type:"SoftmaxWithLoss"}',
              num_data=2, num_out=1, grad_scale=1.0, name=None):
    """Caffe loss layer -> native loss symbol (SoftmaxWithLoss only)."""
    import mxnet_tpu as mx

    lay = _parse_layer(prototxt)
    t = lay.type or "SoftmaxWithLoss"
    if t != "SoftmaxWithLoss":
        raise ValueError("CaffeLoss supports SoftmaxWithLoss, got %r" % t)
    return mx.sym.SoftmaxOutput(data=data, label=label,
                                grad_scale=grad_scale,
                                name=name or "softmax")
