"""NDArray — imperative tensor with engine-ordered mutation semantics.

TPU-native redesign of the reference NDArray (include/mxnet/ndarray.h:58,
src/ndarray/ndarray.cc). The reference pairs every NDArray with an engine Var
and pushes each mutation as an async engine op; buffers are mutable and
``Slice/At/Reshape`` alias memory (ndarray.h:286-346). JAX arrays are
immutable and async-by-construction, so here:

* a ``_Chunk`` (ndarray.h:376-432's Chunk) holds the *current* jax.Array;
  mutation swaps the chunk's array (a versioned buffer). Ordering hazards the
  engine resolved by Var scheduling are resolved by value semantics.
* views (``Slice``/``At``/``Reshape``) keep a reference to the parent chunk
  plus an axis-0 window and a view shape; writes through a view apply
  ``.at[start:stop].set`` on the parent, so reference aliasing behaviour is
  preserved observably.
* ``wait_to_read`` == ``block_until_ready`` (Engine::WaitForVar); dispatch is
  already async under JAX so there is nothing to schedule host-side.

Every registered operator (registry.py) is exposed as a function in this
module (the reference auto-generates these from the C API op list,
python/mxnet/ndarray.py _init_ndarray_module).
"""
from __future__ import annotations

import sys

import numpy as onp

from .base import MXNetError, numeric_types
from .context import Context, cpu, current_context
from . import registry as _registry
from . import engine as _engine
from . import random as _random

__all__ = ["NDArray", "array", "zeros", "ones", "empty", "full", "arange",
           "concatenate", "load", "save", "waitall", "imdecode", "onehot_encode"]

_DEFAULT_DTYPE = onp.float32
# _init_ndarray_module exposes ops at module level; an op is named "slice",
# so keep a handle on the builtin for internal use.
_py_slice = slice


def _jnp():
    import jax.numpy as jnp
    return jnp


class _Chunk:
    """Holds the current device buffer + its context (ndarray.h Chunk).

    ``force`` is an optional thunk installed by a pending (lazy) executor:
    reading the chunk first materializes the deferred computation — this is
    how forward+backward fuse into one XLA program while `exec.outputs`
    stays eagerly readable (the engine-Var WaitToRead contract).
    """

    __slots__ = ("arr", "ctx", "force")

    def __init__(self, arr, ctx):
        self.arr = arr
        self.ctx = ctx
        self.force = None


class NDArray:
    """Multi-dimensional, mutable-by-swap array on a device context."""

    __slots__ = ("_chunk", "_start", "_stop", "_vshape", "writable")

    def __init__(self, data=None, ctx=None, _chunk=None, _start=None,
                 _stop=None, _vshape=None, writable=True):
        if _chunk is not None:
            self._chunk = _chunk
        else:
            ctx = ctx or current_context()
            self._chunk = _Chunk(data, ctx)
        self._start = _start
        self._stop = _stop
        self._vshape = tuple(_vshape) if _vshape is not None else None
        self.writable = writable

    # ------------------------------------------------------------------ io
    def _read(self):
        """Current jnp value of this (possibly view) array."""
        if self._chunk.force is not None:
            f, self._chunk.force = self._chunk.force, None
            f()
        arr = self._chunk.arr
        if self._start is not None:
            arr = arr[self._start:self._stop]
        if self._vshape is not None and tuple(arr.shape) != self._vshape:
            arr = arr.reshape(self._vshape)
        return arr

    def _write(self, new):
        """Replace this array's contents with jnp value ``new``."""
        if not self.writable:
            raise MXNetError("trying to write to a readonly NDArray")
        chunk = self._chunk
        if chunk.force is not None:
            if self._start is None and self._vshape is None:
                chunk.force = None  # full overwrite supersedes pending value
            else:
                f, chunk.force = chunk.force, None
                f()
        if self._start is None and self._vshape is None:
            chunk.arr = new
            return
        if self._start is None:
            chunk.arr = new.reshape(chunk.arr.shape)
            return
        seg_shape = (self._stop - self._start,) + tuple(chunk.arr.shape[1:])
        chunk.arr = chunk.arr.at[self._start:self._stop].set(
            new.reshape(seg_shape))

    # ------------------------------------------------------------- basics
    @property
    def shape(self):
        if self._vshape is not None:
            return self._vshape
        if self._start is not None:
            return (self._stop - self._start,) + tuple(self._chunk.arr.shape[1:])
        return tuple(self._chunk.arr.shape)

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        sz = 1
        for s in self.shape:
            sz *= s
        return sz

    @property
    def dtype(self):
        return onp.dtype(self._chunk.arr.dtype).type

    @property
    def context(self):
        return self._chunk.ctx

    ctx = context

    @property
    def handle(self):  # compat: opaque handle
        return self._chunk

    @property
    def T(self):
        if self.ndim <= 1:
            return self
        return transpose(self)

    def __repr__(self):
        shape_info = "x".join(str(x) for x in self.shape)
        return "<%s %s @%s>" % (type(self).__name__, shape_info, self.context)

    def __len__(self):
        return self.shape[0]

    # ------------------------------------------------------------ convert
    def asnumpy(self):
        """Copy to host numpy array (blocking read, = WaitToRead + copy)."""
        return onp.asarray(self._read())

    def __array__(self, dtype=None, copy=None):
        # numpy protocol: without this, onp.asarray(nd) walks __getitem__
        # row by row — one jitted slice per element. asnumpy() is already
        # a fresh host copy, so copy=False is satisfiable (NumPy 2 kwarg).
        a = self.asnumpy()
        return a.astype(dtype, copy=False) if dtype is not None else a

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(-1)[0]

    def astype(self, dtype):
        res = empty(self.shape, ctx=self.context, dtype=dtype)
        self.copyto(res)
        return res

    def wait_to_read(self):
        """Block until this array's value is computed (WaitForVar)."""
        if self._chunk.force is not None:
            f, self._chunk.force = self._chunk.force, None
            f()
        try:
            self._chunk.arr.block_until_ready()
        except AttributeError:  # pragma: no cover - non-jax backing
            pass

    wait_to_write = wait_to_read

    # -------------------------------------------------------------- copy
    def copyto(self, other):
        """Copy into another NDArray or to a new array on a Context."""
        import jax
        if isinstance(other, NDArray):
            if other._chunk is self._chunk and other._start == self._start:
                return other
            val = self._read()
            if other.context != self.context:
                val = jax.device_put(val, other.context.jax_device())
            if onp.dtype(val.dtype) != onp.dtype(other.dtype):
                val = val.astype(other.dtype)
            if tuple(val.shape) != other.shape:
                raise ValueError("array shape do not match the target %s vs %s"
                                 % (val.shape, other.shape))
            other._write(val)
            return other
        if isinstance(other, Context):
            arr = jax.device_put(self._read(), other.jax_device())
            return NDArray(arr, ctx=other)
        raise TypeError("copyto does not support type " + str(type(other)))

    def copy(self):
        return self.copyto(self.context)

    def as_in_context(self, context):
        if self.context == context:
            return self
        return self.copyto(context)

    # ------------------------------------------------------------- views
    def slice(self, start, stop):
        """Zero-copy axis-0 slice sharing this array's chunk (ndarray.h:286)."""
        start, stop, _ = _py_slice(start, stop).indices(self.shape[0])
        base = self._start or 0
        sub_shape = (stop - start,) + tuple(self.shape[1:])
        return NDArray(_chunk=self._chunk, _start=base + start,
                       _stop=base + stop,
                       _vshape=sub_shape if self._vshape is not None else None,
                       writable=self.writable)

    def at(self, idx):
        """View of row ``idx`` with the leading axis removed (ndarray.h At)."""
        if idx < 0:
            idx += self.shape[0]
        base = self._start or 0
        return NDArray(_chunk=self._chunk, _start=base + idx,
                       _stop=base + idx + 1, _vshape=tuple(self.shape[1:]),
                       writable=self.writable)

    def reshape(self, shape, **kwargs):
        """Shape-changing view sharing storage (ndarray.h Reshape)."""
        if isinstance(shape, int):
            shape = (shape,) + tuple(kwargs.pop("__rest", ()))
        shape = tuple(shape)
        if -1 in shape:
            known = 1
            for s in shape:
                if s != -1:
                    known *= s
            shape = tuple(self.size // known if s == -1 else s for s in shape)
        sz = 1
        for s in shape:
            sz *= s
        if sz != self.size:
            raise ValueError("new shape %s has different size from current %s"
                             % (shape, self.shape))
        return NDArray(_chunk=self._chunk, _start=self._start, _stop=self._stop,
                       _vshape=shape, writable=self.writable)

    # --------------------------------------------------------- item access
    def __getitem__(self, key):
        if isinstance(key, int):
            return self.at(key)
        if isinstance(key, _py_slice):
            if key.step is not None and key.step != 1:
                raise ValueError("NDArray only supports continuous slicing on axis 0")
            return self.slice(key.start, key.stop)
        raise ValueError("NDArray only supports int/slice as index")

    def __setitem__(self, key, value):
        view = self[key] if not (isinstance(key, _py_slice) and key.start is None
                                 and key.stop is None and key.step is None) else self
        if isinstance(value, NDArray):
            value.copyto(view)
        elif isinstance(value, numeric_types):
            # fill on the array's OWN device — jnp.full would land on the
            # default accelerator and silently migrate a cpu-ctx array
            # (then one jitted step over mixed devices fails to compile)
            view._sync_copyfrom(onp.full(view.shape, value,
                                         dtype=view.dtype))
        elif isinstance(value, (onp.ndarray, onp.generic, list, tuple)):
            view._sync_copyfrom(onp.asarray(value))
        else:
            raise TypeError("type %s not supported" % str(type(value)))

    def _sync_copyfrom(self, source_array):
        import jax
        src = onp.asarray(source_array, dtype=self.dtype)
        if src.shape != self.shape:
            try:
                src = src.reshape(self.shape)
            except ValueError:
                raise ValueError("Shape inconsistent: expected %s, got %s"
                                 % (str(self.shape), str(src.shape)))
        self._write(jax.device_put(src, self.context.jax_device()))

    # ---------------------------------------------------------- operators
    def __add__(self, other):
        return _binary(self, other, "broadcast_add", "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __iadd__(self, other):
        return _binary(self, other, "broadcast_add", "_plus_scalar", out=self)

    def __sub__(self, other):
        return _binary(self, other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _binary(self, other, None, "_rminus_scalar")

    def __isub__(self, other):
        return _binary(self, other, "broadcast_sub", "_minus_scalar", out=self)

    def __mul__(self, other):
        return _binary(self, other, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __imul__(self, other):
        return _binary(self, other, "broadcast_mul", "_mul_scalar", out=self)

    def __div__(self, other):
        return _binary(self, other, "broadcast_div", "_div_scalar")

    __truediv__ = __div__

    def __rdiv__(self, other):
        return _binary(self, other, None, "_rdiv_scalar")

    __rtruediv__ = __rdiv__

    def __idiv__(self, other):
        return _binary(self, other, "broadcast_div", "_div_scalar", out=self)

    __itruediv__ = __idiv__

    def __mod__(self, other):
        return _binary(self, other, "broadcast_mod", "_mod_scalar")

    def __pow__(self, other):
        return _binary(self, other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        return _binary(self, other, None, "_rpower_scalar")

    def __neg__(self):
        return _binary(self, -1.0, None, "_mul_scalar")

    def __eq__(self, other):
        if isinstance(other, (NDArray,) + numeric_types):
            return _binary(self, other, "broadcast_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (NDArray,) + numeric_types):
            return _binary(self, other, "broadcast_not_equal",
                           "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, other):
        return _binary(self, other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return _binary(self, other, "broadcast_greater_equal",
                       "_greater_equal_scalar")

    def __lt__(self, other):
        return _binary(self, other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return _binary(self, other, "broadcast_lesser_equal",
                       "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple "
                         "elements is ambiguous.")

    __nonzero__ = __bool__

    # convenience reductions mirroring generated methods
    def sum(self, *args, **kwargs):
        return sum(self, *args, **kwargs)

    def max(self, *args, **kwargs):
        return max(self, *args, **kwargs)

    def min(self, *args, **kwargs):
        return min(self, *args, **kwargs)

    def mean(self, *args, **kwargs):
        return mean(self, *args, **kwargs)

    def argmax(self, *args, **kwargs):
        return argmax(self, *args, **kwargs)

    def transpose(self, *args, **kwargs):
        return transpose(self, *args, **kwargs)

    def flatten(self):
        return flatten(self)


def _binary(lhs, rhs, nd_op, scalar_op, out=None):
    if isinstance(rhs, NDArray):
        if nd_op is None:
            raise MXNetError("operation not supported between NDArrays")
        return invoke(_registry.get_op(nd_op), [lhs, rhs], {}, out=out)
    if isinstance(rhs, numeric_types):
        return invoke(_registry.get_op(scalar_op), [lhs],
                      {"scalar": float(rhs)}, out=out)
    raise TypeError("type %s not supported" % str(type(rhs)))


# ---------------------------------------------------------------------------
# imperative invoke — the MXImperativeInvoke path (src/c_api/c_api_ndarray.cc)
# ---------------------------------------------------------------------------
def invoke(op, inputs, raw_attrs, out=None, ctx=None):
    """Run a registered op on NDArrays eagerly.

    Mirrors MXImperativeInvoke (c_api_ndarray.cc:123-310): infer shapes/types
    (implicit in jnp), set dependencies (implicit in JAX async dispatch),
    execute, record on the autograd tape when training. Ops with aux state
    mutate the trailing aux inputs in place (FMutateInputs).
    """
    from . import autograd as _autograd

    attrs = _registry.parse_attrs(op, raw_attrs)
    if op.variable_args is not None and op.variable_args not in attrs:
        attrs[op.variable_args] = len(inputs)

    n_aux = len(op.aux_names)
    vals = [x._read() for x in inputs]
    octx = _registry.OpContext(
        is_train=_autograd.is_training(),
        rng=_random.next_key() if op.needs_rng else None)
    # pin input-free ops (zeros/full/random fills) to the op's context:
    # they would otherwise land on the process default device — silently
    # migrating "cpu" arrays onto the accelerator (and, on remote-attached
    # TPUs, turning every host-side fill into tunnel traffic). Ops WITH
    # inputs follow their committed inputs already; skip the config
    # context manager on that hot path.
    out_first = (next((o for o in out if o is not None), None)
                 if isinstance(out, (list, tuple))
                 else out)
    in_ctx = ctx or (inputs[0].context if inputs
                     else out_first.context if out_first is not None
                     else current_context())
    if inputs:
        results = op.fcompute(attrs, vals, octx)
    else:
        import jax
        with jax.default_device(in_ctx.jax_device()):
            results = op.fcompute(attrs, vals, octx)
    n_out = op.num_outputs(attrs)
    outs, aux_updates = list(results[:n_out]), list(results[n_out:])

    # write back mutated aux states (BatchNorm moving stats etc.)
    if n_aux and aux_updates:
        for nda, new in zip(inputs[-n_aux:], aux_updates):
            nda._write(new)

    out_list = out if isinstance(out, (list, tuple)) else (
        [out] if out is not None else None)
    wrapped = []
    for i, o in enumerate(outs):
        if out_list is not None and i < len(out_list) and out_list[i] is not None:
            tgt = out_list[i]
            tgt._write(o.astype(tgt.dtype) if onp.dtype(o.dtype) != onp.dtype(tgt.dtype) else o)
            wrapped.append(tgt)
        else:
            wrapped.append(NDArray(o, ctx=in_ctx))

    if _autograd.is_recording():
        _autograd.record_op(op, attrs, list(inputs), wrapped, octx)

    if _engine.is_naive():
        for w in wrapped:
            w.wait_to_read()
    return wrapped[0] if len(wrapped) == 1 else wrapped


def _make_op_func(op):
    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        ctx = kwargs.pop("ctx", None)
        inputs = [a for a in args if isinstance(a, NDArray)]
        # None kwargs mean "default" — the reference's generated wrappers
        # drop them before the C call (they would stringify to "None")
        attrs = {k: v for k, v in kwargs.items()
                 if v is not None and not isinstance(v, NDArray)}
        named_in = {k: v for k, v in kwargs.items() if isinstance(v, NDArray)}
        if named_in:
            order = op.list_arguments(attrs) + list(op.aux_names)
            for nm in order:
                if nm in named_in:
                    inputs.append(named_in.pop(nm))
            inputs.extend(named_in.values())
        scalars = [a for a in args if not isinstance(a, NDArray)]
        if scalars and "scalar" in getattr(op, "attr_types", {}) and "scalar" not in attrs:
            attrs["scalar"] = scalars[0]
        return invoke(op, inputs, attrs, out=out, ctx=ctx)

    fn.__name__ = op.name
    fn.__doc__ = (op.fcompute.__doc__ or "") + "\n\n(op: %s)" % op.name
    return fn


def _init_ndarray_module():
    """Expose every registered op as a module-level function (mirrors
    python/mxnet/ndarray.py _init_ndarray_module)."""
    mod = sys.modules[__name__]
    for name in _registry.list_ops():
        op = _registry.get_op(name)
        # python-level creation helpers (zeros/ones/arange/...) take
        # precedence over the raw attr-style op wrappers
        if hasattr(mod, name):
            continue
        setattr(mod, name, _make_op_func(op))


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------
def empty(shape, ctx=None, dtype=_DEFAULT_DTYPE):
    """Allocate an NDArray without defined contents (mx.nd.empty).

    Contract note: XLA's functional buffer model has no "uninitialized
    allocation" — every device buffer is produced by a computation, and
    jnp.empty is itself zeros. The zero-fill executes on device at HBM
    bandwidth and typically fuses away when the buffer is first written,
    so unlike the reference (ndarray.cc empty alloc) there is no separate
    fill pass to save.
    """
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=_DEFAULT_DTYPE):
    import jax
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    arr = jax.device_put(onp.zeros(shape, dtype=dtype), ctx.jax_device())
    return NDArray(arr, ctx=ctx)


def ones(shape, ctx=None, dtype=_DEFAULT_DTYPE):
    import jax
    ctx = ctx or current_context()
    if isinstance(shape, int):
        shape = (shape,)
    arr = jax.device_put(onp.ones(shape, dtype=dtype), ctx.jax_device())
    return NDArray(arr, ctx=ctx)


def full(shape, val, ctx=None, dtype=_DEFAULT_DTYPE):
    arr = zeros(shape, ctx=ctx, dtype=dtype)
    arr[:] = val
    return arr


def array(source_array, ctx=None, dtype=_DEFAULT_DTYPE):
    """Create an NDArray from any array-like (defaults to float32, as the
    reference does: python/mxnet/ndarray.py array())."""
    import jax
    ctx = ctx or current_context()
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy().astype(dtype)
    else:
        src = onp.asarray(source_array, dtype=dtype)
    return NDArray(jax.device_put(src, ctx.jax_device()), ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=_DEFAULT_DTYPE):
    if stop is None:
        start, stop = 0, start
    vals = onp.arange(start, stop, step, dtype=dtype)
    if repeat != 1:
        vals = onp.repeat(vals, repeat)
    return array(vals, ctx=ctx, dtype=dtype)


def concatenate(arrays, axis=0, always_copy=True):
    if not arrays:
        raise ValueError("arrays must not be empty")
    import jax
    jnp = _jnp()
    # inputs may live on different devices (multi-ctx executor outputs);
    # stage onto the first array's device like the reference's CPU gather
    parts = [a._read() for a in arrays]
    dev = getattr(parts[0], "devices", lambda: None)()
    if dev:
        target = next(iter(dev))
        parts = [p if getattr(p, "devices", lambda: {target})() == {target}
                 else jax.device_put(p, target) for p in parts]
    res = jnp.concatenate(parts, axis=axis)
    return NDArray(res, ctx=arrays[0].context)


def onehot_encode(indices, out):
    """One-hot into ``out`` (mx.nd.onehot_encode compatibility)."""
    jnp = _jnp()
    depth = out.shape[1]
    idx = indices._read().astype("int32")
    out._write(jnp.squeeze(
        (idx[:, None] == jnp.arange(depth)[None, :]).astype(out.dtype)))
    return out


def imdecode(str_img, **kwargs):
    from .io_util import imdecode as _imdecode
    return _imdecode(str_img, **kwargs)



def _copyto(src, out):
    """Legacy NDArray function (src/ndarray/ndarray.cc MXNET_REGISTER_NDARRAY_FUN
    _copyto): copy ``src`` into ``out``, possibly across devices."""
    return src.copyto(out)


def _set_value(src_scalar, out):
    """Fill ``out`` with a scalar (ndarray.cc _set_value)."""
    jnp = _jnp()
    out._write(jnp.full(out.shape, float(src_scalar), out.dtype))
    return out


def _onehot_encode(indices, out):
    return onehot_encode(indices, out)


def choose_element_0index(lhs, rhs, out=None):
    """out[i] = lhs[i, rhs[i]] (ndarray.cc:765)."""
    from .registry import get_op
    return invoke(get_op("choose_element_0index"), [lhs, rhs], {}, out=out)


def fill_element_0index(lhs, mhs, rhs, out=None):
    """lhs with lhs[i, rhs[i]] = mhs[i] (ndarray.cc:771)."""
    from .registry import get_op
    return invoke(get_op("fill_element_0index"), [lhs, mhs, rhs], {}, out=out)


def _broadcast(src, axis, size, out=None):
    """Broadcast ``src`` along ``axis`` to ``size`` (ndarray.cc:860)."""
    jnp = _jnp()
    x = src._read()
    res = jnp.broadcast_to(
        x, x.shape[:int(axis)] + (int(size),) + x.shape[int(axis) + 1:])
    if out is not None:
        out._write(res)
        return out
    return NDArray(res, ctx=src.context)


def _imdecode(mean, index, x0, y0, x1, y1, n_channels, size, str_img, out=None):
    """Legacy positional imdecode (ndarray.cc _imdecode)."""
    from .io_util import imdecode as _dec
    return _dec(str_img, clip_rect=(x0, y0, x1, y1), out=out, index=index,
                channels=n_channels, mean=mean)


# ---------------------------------------------------------------------------
# OpenCV-backed host image ops (plugin/opencv/cv_api.cc _cvimdecode/
# _cvimresize/_cvcopyMakeBorder). Host-side work, imperative only.
# ---------------------------------------------------------------------------
def _cvimdecode(buf, flag=1, to_rgb=True):
    """Decode a JPEG/PNG byte buffer into an HWC uint8 NDArray.
    ``flag`` follows cv::imread: 0 = grayscale (h,w), nonzero = color."""
    from .image import imdecode as _dec
    import numpy as _np
    img = _dec(buf if isinstance(buf, (bytes, bytearray)) else
               buf.asnumpy().astype("uint8").tobytes(), to_rgb=to_rgb)
    if flag == 0 and img.ndim == 3:
        # ITU-R BT.601 luma — what cv::IMREAD_GRAYSCALE computes
        w = _np.array([0.299, 0.587, 0.114] if to_rgb
                      else [0.114, 0.587, 0.299], _np.float32)
        img = (img.astype(_np.float32) @ w).round().astype(img.dtype)
    return array(img, dtype=img.dtype)


def _cvimresize(src, w, h, interp=1):
    """Resize an HWC image NDArray (plugin/opencv cv_api.cc). ``interp``
    follows cv2 enums (0=nearest, 1=linear, ...) when cv2 is present; the
    PIL fallback maps 0 to nearest and anything else to bilinear."""
    import numpy as _np
    img = src.asnumpy()
    try:
        import cv2
        out = cv2.resize(img, (int(w), int(h)), interpolation=int(interp))
    except ImportError:
        from PIL import Image
        mode = Image.NEAREST if int(interp) == 0 else Image.BILINEAR
        out = _np.asarray(Image.fromarray(img.astype(_np.uint8)).resize(
            (int(w), int(h)), mode)).astype(img.dtype)
    return array(out, dtype=out.dtype)


def _cvcopyMakeBorder(src, top, bot, left, right, type=0, value=0.0):  # noqa: A002
    """Pad an HWC image (plugin/opencv cv_api.cc). ``type`` follows cv2
    border enums: 0 = constant fill; others fall back to edge replicate."""
    import numpy as _np
    img = src.asnumpy()
    if int(type) == 0:
        out = _np.full((img.shape[0] + top + bot, img.shape[1] + left + right)
                       + img.shape[2:], value, dtype=img.dtype)
        out[top:top + img.shape[0], left:left + img.shape[1]] = img
    else:
        pad = [(top, bot), (left, right)] + [(0, 0)] * (img.ndim - 2)
        out = _np.pad(img, pad, mode="edge")
    return array(out, dtype=out.dtype)

# ---------------------------------------------------------------------------
# serialization — NDArray::Save/Load (ndarray.h:360-371); we use the npz
# container (documented own format, not binary-compatible with the reference)
# ---------------------------------------------------------------------------
def save(fname, data):
    """Save a list or str->NDArray dict of NDArrays to file.

    The write is crash-atomic: content goes to ``fname + ".tmp"``, is
    fsynced, then renamed over ``fname`` (``os.replace``). A preemption
    mid-write leaves the previous file intact plus at most a stray
    ``.tmp`` that :func:`load` refuses to read.
    """
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        fmt, arrs = "dict", {k: v.asnumpy() for k, v in data.items()}
    elif isinstance(data, (list, tuple)):
        fmt = "list"
        arrs = {"arr_%d" % i: v.asnumpy() for i, v in enumerate(data)}
    else:
        raise ValueError("data needs to either be a NDArray, dict or list")
    from .checkpoint.serialize import atomic_write_stream
    # savez streams into the tmp handle (which also stops numpy
    # appending ".npz"); atomic_write_stream does the fsync + rename
    atomic_write_stream(
        fname, lambda f: onp.savez(f, __mx_format__=fmt, **arrs))


def load(fname):
    """Load NDArrays saved by ``save`` — returns list or dict like the
    reference's MXNDArrayLoad. ``.tmp`` files (an interrupted
    :func:`save` that never committed) are rejected."""
    if str(fname).endswith(".tmp"):
        raise MXNetError(
            "refusing to load %r: .tmp files are uncommitted partial "
            "writes left by an interrupted save" % (fname,))
    with onp.load(fname, allow_pickle=False) as npz:
        fmt = str(npz["__mx_format__"]) if "__mx_format__" in npz else "dict"
        items = {k: npz[k] for k in npz.files if k != "__mx_format__"}
        if fmt == "list":
            return [array(items["arr_%d" % i], dtype=items["arr_%d" % i].dtype)
                    for i in range(len(items))]
        return {k: array(v, dtype=v.dtype) for k, v in items.items()}


def waitall():
    _engine.waitall()


# Register all operators and expose them at module level immediately, so
# ``from mxnet_tpu.ndarray import sgd_update`` works without package-level
# ordering constraints.
from . import ops as _ops  # noqa: E402,F401
_init_ndarray_module()
