"""Profiler (python/mxnet/profiler.py + src/engine/profiler.{h,cc}).

The reference stamps per-op OprExecStat inside the engine and dumps Chrome
trace JSON. TPU-natively, per-op timing lives in the XLA/TPU runtime: we
bridge to ``jax.profiler`` (XPlane traces, viewable in TensorBoard/Perfetto)
while preserving the reference API (profiler_set_config / set_state /
dump_profile) and emitting a Chrome-trace JSON of host-side step events.
"""
from __future__ import annotations

import json
import os
import time
import threading

__all__ = ["profiler_set_config", "profiler_set_state", "dump_profile",
           "Scope"]

_config = {"mode": "symbolic", "filename": "profile.json"}
_state = "stop"
_events = []
_lock = threading.Lock()
_jax_tracing = False
_ran_undumped = False  # profiling ran but no dump written yet


def _autostart():
    """Honor the reference's MXNET_PROFILER_AUTOSTART / MXNET_PROFILER_MODE
    env contract (docs/how_to/env_var.md:71-76): profiling begins at
    library init and the dump fires at exit."""
    if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") != "1":
        return
    mode = "all" if os.environ.get("MXNET_PROFILER_MODE", "0") == "1" \
        else "symbolic"
    profiler_set_config(mode=mode, filename=os.environ.get(
        "MXNET_PROFILER_FILENAME", "profile.json"))
    profiler_set_state("run")
    import atexit

    def _stop_and_dump():
        # sticky: dump whenever profiling ever ran and data may be
        # undumped (reference enable_output_ semantics,
        # initialize.cc:42-47) — neither a manual stop() nor a mid-run
        # dump may lose the tail of the trace
        was_running = _state == "run"
        if was_running:
            profiler_set_state("stop")
        if was_running or _ran_undumped:
            dump_profile()

    atexit.register(_stop_and_dump)


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """mode: 'symbolic' or 'all' (MXSetProfilerConfig)."""
    _config["mode"] = mode
    _config["filename"] = filename


def profiler_set_state(state="stop"):
    """state: 'run' or 'stop' (MXSetProfilerState); also starts/stops a
    jax.profiler trace next to the chrome-trace output."""
    global _state, _jax_tracing, _ran_undumped
    if state == _state:
        return
    _state = state
    if state == "run":
        _ran_undumped = True
    trace_dir = os.path.splitext(_config["filename"])[0] + "_xplane"
    from . import engine as _engine
    if state == "run":
        _engine.get().profile_start()  # native per-op host stamps
        try:
            import jax
            jax.profiler.start_trace(trace_dir)
            _jax_tracing = True
        except Exception:
            _jax_tracing = False
    else:
        _engine.get().profile_stop()
        if _jax_tracing:
            import jax
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def record_event(name, begin_us, end_us, pid=0, tid=None):
    """Append one duration event (engine's AddOprStat equivalent).

    Emitted as ONE complete event (``"ph": "X"`` with a ``dur``) keyed
    by the REAL recording thread id. The old encoding — unpaired
    ``"B"``/``"E"`` pairs stamped with ``tid=pid`` — collapsed every
    scope onto one track, so nested scopes from different threads
    interleaved their begin/end markers and Perfetto rendered garbage
    nesting; complete events carry their own extent, so per-thread
    containment of ``(ts, dur)`` intervals is unambiguous."""
    global _ran_undumped
    if _state != "run":
        return
    _ran_undumped = True
    if tid is None:
        tid = threading.get_ident()
    with _lock:
        _events.append({"name": name, "cat": "operator", "ph": "X",
                        "ts": begin_us, "dur": max(0.0, end_us - begin_us),
                        "pid": pid, "tid": tid})


class Scope(object):
    """Context manager timing a named region into the trace."""

    def __init__(self, name, pid=0):
        self.name = name
        self.pid = pid

    def __enter__(self):
        self.begin = time.time() * 1e6
        return self

    def __exit__(self, *args):
        record_event(self.name, self.begin, time.time() * 1e6, self.pid)


_native_events = []  # drained from the engine, kept so dumps stay cumulative


def dump_profile():
    """Write accumulated events as Chrome tracing JSON (MXDumpProfile),
    merging the native engine's per-op stamps (OprExecStat equivalents)
    AND the telemetry span ring (``mxnet_tpu.telemetry.span``), so one
    file carries the whole host-side timeline. Callable repeatedly —
    every event source accumulates across dumps."""
    from . import engine as _engine
    eng = _engine.get()
    # "symbolic" mode never emits per-op stamps — skip the temp-file
    # drain entirely rather than accumulating events nobody will see
    if eng.is_native and _config.get("mode") == "all":
        import tempfile
        with tempfile.NamedTemporaryFile(suffix=".json",
                                         delete=False) as tmp:
            path = tmp.name
        try:
            if eng.profile_dump(path) > 0:
                with open(path) as f:
                    fresh = json.load(f).get("traceEvents", [])
                with _lock:
                    _native_events.extend(fresh)
        finally:
            os.unlink(path)
    with _lock:
        events = list(_events)
        # "symbolic" mode (MXNET_PROFILER_MODE=0, the reference default)
        # reports executor/step regions only; "all" adds the engine's
        # per-imperative-op stamps (profiler.h:63-66 mode semantics)
        if _config.get("mode") == "all":
            events += list(_native_events)
        # telemetry spans share the wall clock (time.time() * 1e6), so
        # host spans, engine op stamps, and the jax.profiler XPlane
        # trace line up on one timeline in Perfetto
        from . import telemetry as _telemetry
        events += _telemetry.trace_events()
        data = {"traceEvents": events, "displayTimeUnit": "ms"}
        with open(_config["filename"], "w") as f:
            json.dump(data, f)
    global _ran_undumped
    _ran_undumped = False


_autostart()
