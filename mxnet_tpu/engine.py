"""Engine — async host scheduling over the XLA runtime.

The reference's 2,001-LoC dependency engine (src/engine/, ThreadedEngine-
PerDevice) exists because HIP ops are eager and hazard-prone; it toposorts
ops by NDArray Var read/write dependencies and runs them on per-device
thread pools. On TPU, *device* ordering is XLA's job (every jitted call
returns a future-backed Array ordered by dataflow), so the engine's
remaining real work is HOST-side: input-pipeline stages, staging-buffer
fills, checkpoint writes, python callbacks — overlapped with device compute
but still hazard-ordered among themselves.

That host scheduler is native C++ (runtime/engine_core.cpp, bound in
runtime/core.py): per-var FIFO hazard queues (reads run concurrently,
writes serialize — threaded_engine.h ThreadedVar semantics), a priority
worker pool, WaitForVar/WaitForAll sync points, and per-op profiler stamps
(OprExecStat) dumped as Chrome trace JSON. This module keeps the python
fallback for compiler-less environments and honours the reference's env
contract: ``MXNET_ENGINE_TYPE=NaiveEngine`` makes every op synchronous (the
standard race-bisection tool, src/engine/naive_engine.cc);
``MXNET_CPU_WORKER_NTHREADS`` sizes the pool.
"""
from __future__ import annotations

import atexit
import os
import queue
import threading

__all__ = ["Engine", "get", "waitall", "is_naive"]

_NAIVE = os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"


def is_naive():
    return _NAIVE


class Engine:
    """Host-side async executor.

    Native path: C++ dependency engine with var hazards. Fallback: single
    FIFO worker thread (still async, no var tracking).
    """

    _inst = None

    def __init__(self, num_workers=None):
        self._native = None
        try:
            from .runtime.core import NativeEngine
            eng = NativeEngine(num_workers)
            if eng.available:
                self._native = eng
        except Exception:  # pragma: no cover - build env without g++
            self._native = None
        if self._native is not None:
            # deterministic teardown: drain and JOIN the C++ worker pool
            # while the interpreter is still fully alive. Relying on
            # NativeEngine.__del__ during interpreter finalization races
            # a worker mid-callback against Python teardown and
            # intermittently aborts the process with "terminate called
            # without an active exception" (reproducible under CPU
            # contention with an in-flight async checkpoint save at
            # exit). Registered at creation: atexit is LIFO, so hooks
            # that SCHEDULE work at exit (CheckpointManager's drain,
            # registered later) run first, and shutdown's wait_all still
            # drains anything they pushed.
            atexit.register(self.shutdown)
        self._q = None
        if self._native is None:
            # the fallback has no per-var hazard tracking, so correctness
            # requires ONE worker: FIFO push order then serializes all
            # mutations (threaded_engine.h ThreadedVar semantics degrade to
            # a total order). MXNET_CPU_WORKER_NTHREADS>1 only takes effect
            # on the native engine.
            if num_workers is None:
                num_workers = int(os.environ.get(
                    "MXNET_CPU_WORKER_NTHREADS", 1))
            if num_workers > 1:
                import logging
                logging.getLogger(__name__).warning(
                    "python fallback engine runs a single worker to keep "
                    "var-hazard ordering; MXNET_CPU_WORKER_NTHREADS=%d "
                    "needs the native engine", num_workers)
            self._q = queue.Queue()
            if not _NAIVE:
                t = threading.Thread(target=self._worker, daemon=True)
                t.start()

    # ------------------------------------------------------------- fallback
    def _worker(self):
        while True:
            fn, done = self._q.get()
            try:
                fn()
            finally:
                done.set()
                self._q.task_done()

    def shutdown(self):
        """Drain pending ops and stop the native worker pool
        (idempotent; the interpreter-exit hook). Work pushed AFTER
        shutdown — late ``__del__``-driven host ops during final GC —
        degrades to synchronous execution, which is always safe."""
        native, self._native = self._native, None
        if native is None:
            return
        try:
            native.wait_all()
        except BaseException:  # noqa: BLE001 - exit path; job errors
            import logging    # already surfaced via their own waiters
            logging.getLogger(__name__).exception(
                "pending engine op failed during shutdown drain")
        native.close()

    # ------------------------------------------------------------------ API
    @property
    def is_native(self):
        return self._native is not None

    def new_var(self):
        """Engine::NewVariable — a dependency token for host buffers."""
        if self._native is not None:
            return self._native.new_var()
        return None

    def del_var(self, var):
        if self._native is not None and var is not None:
            self._native.del_var(var)

    def push(self, fn, const_vars=(), mutate_vars=(), priority=0, name="op"):
        """Engine::PushAsync — run fn() once all hazards clear.

        Returns a threading.Event set after fn completes (both paths)."""
        done = threading.Event()

        def run():
            try:
                fn()
            finally:
                done.set()

        if self._native is not None:
            self._native.push(run, const_vars, mutate_vars, priority, name)
        elif _NAIVE or not self._q:
            run()
        else:
            self._q.put((run, done))
        return done

    def push_async(self, fn):
        """Dependency-free host op; returns a waitable Event."""
        return self.push(fn)

    def wait_for_var(self, var):
        """Engine::WaitForVar — block until all pushed ops touching var ran."""
        if self._native is not None:
            if var is not None:
                self._native.wait_for_var(var)
        elif self._q is not None:
            # fallback has no per-var tracking; a full drain is the only
            # way to honor the WaitForVar contract
            self._q.join()

    def wait_for_all(self):
        if self._native is not None:
            self._native.wait_all()
        elif self._q is not None:
            self._q.join()
        import jax
        try:
            jax.effects_barrier()
        except Exception:  # pragma: no cover
            pass
        # Block on any outstanding device computation.
        try:
            jax.device_put(0).block_until_ready()
        except Exception:  # pragma: no cover
            pass

    # ------------------------------------------------------------- profiler
    def profile_start(self):
        if self._native is not None:
            self._native.profile_start()

    def profile_stop(self):
        if self._native is not None:
            self._native.profile_stop()

    def profile_dump(self, path, clear=True):
        """Dump native per-op stats as Chrome trace JSON; 0 if no native."""
        if self._native is not None:
            return self._native.profile_dump(path, clear)
        return 0


def get():
    if Engine._inst is None:
        Engine._inst = Engine()
    return Engine._inst


def waitall():
    """mx.nd.waitall — block until all pending host+device work is done."""
    get().wait_for_all()
