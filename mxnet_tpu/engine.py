"""Engine shim — async semantics over the XLA runtime.

The reference's 2,001-LoC dependency engine (src/engine/, ThreadedEnginePer-
Device) exists because HIP ops are eager and hazard-prone; it toposorts ops by
NDArray Var read/write dependencies and runs them on per-device thread pools.
On TPU, JAX's dispatch is already asynchronous (every eager op / jitted call
returns immediately with a future-backed Array and XLA orders execution by
data flow), so the engine survives only as this thin layer providing:

* ``waitall`` / per-array ``wait_to_read`` sync points
  (Engine::WaitForAll/WaitForVar, include/mxnet/engine.h:172-180);
* a host-side bulk/async push for IO + callbacks (PushAsync's kAsync path);
* engine-type selection compat (``MXNET_ENGINE_TYPE``): "NaiveEngine" makes
  every op synchronous, the reference's standard race-bisection tool
  (src/engine/naive_engine.cc); we honour it by blocking after each op.
"""
from __future__ import annotations

import os
import queue
import threading

__all__ = ["Engine", "get", "waitall", "is_naive"]

_NAIVE = os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"


def is_naive():
    return _NAIVE


class Engine:
    """Host-side async executor (bounded worker, FIFO per push order)."""

    _inst = None

    def __init__(self, num_workers=1):
        self._q = queue.Queue()
        self._threads = []
        for _ in range(num_workers):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
            self._threads.append(t)

    def _worker(self):
        while True:
            fn, done = self._q.get()
            try:
                fn()
            finally:
                done.set()
                self._q.task_done()

    def push_async(self, fn):
        """Run ``fn`` on a host worker; returns an Event (the Var handle)."""
        done = threading.Event()
        if _NAIVE:
            fn()
            done.set()
        else:
            self._q.put((fn, done))
        return done

    def wait_for_all(self):
        self._q.join()
        import jax
        try:
            jax.effects_barrier()
        except Exception:  # pragma: no cover
            pass
        # Block on any outstanding device computation.
        try:
            jax.device_put(0).block_until_ready()
        except Exception:  # pragma: no cover
            pass


def get():
    if Engine._inst is None:
        Engine._inst = Engine()
    return Engine._inst


def waitall():
    """mx.nd.waitall — block until all pending host+device work is done."""
    get().wait_for_all()
