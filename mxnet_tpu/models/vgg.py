"""VGG-16 (example/image-classification/symbols/vgg.py).

Provenance: DERIVED from the reference's model-zoo symbol script — the
layer wiring, filter counts, and layer names are transcribed so that
checkpoints and per-layer comparisons line up 1:1 with the reference
architecture. Model-zoo topology files are the one place where such
derivation is intentional; the execution machinery underneath is
original TPU-native code.
"""
from .. import symbol as sym


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable(name="data")

    def block(data, num_convs, num_filter, stage):
        for i in range(num_convs):
            data = sym.Convolution(data=data, kernel=(3, 3), pad=(1, 1),
                                   num_filter=num_filter,
                                   name="conv%d_%d" % (stage, i + 1))
            data = sym.Activation(data=data, act_type="relu",
                                  name="relu%d_%d" % (stage, i + 1))
        return sym.Pooling(data=data, pool_type="max", kernel=(2, 2),
                           stride=(2, 2), name="pool%d" % stage)

    net = block(data, 2, 64, 1)
    net = block(net, 2, 128, 2)
    net = block(net, 3, 256, 3)
    net = block(net, 3, 512, 4)
    net = block(net, 3, 512, 5)
    flatten = sym.Flatten(data=net, name="flatten")
    fc6 = sym.FullyConnected(data=flatten, num_hidden=4096, name="fc6")
    relu6 = sym.Activation(data=fc6, act_type="relu", name="relu6")
    drop6 = sym.Dropout(data=relu6, p=0.5, name="drop6")
    fc7 = sym.FullyConnected(data=drop6, num_hidden=4096, name="fc7")
    relu7 = sym.Activation(data=fc7, act_type="relu", name="relu7")
    drop7 = sym.Dropout(data=relu7, p=0.5, name="drop7")
    fc8 = sym.FullyConnected(data=drop7, num_hidden=num_classes, name="fc8")
    return sym.SoftmaxOutput(data=fc8, name="softmax")
