"""ResNeXt (example/image-classification/symbols/resnext.py; Xie et al.
2017 "Aggregated Residual Transformations").

Post-activation bottleneck units whose 3x3 stage is a grouped
convolution with ``num_group`` cardinality (the aggregated-transform
trick); grouped convs lower to feature_group_count on the MXU.

Provenance: the filter schedule and layer naming follow the reference's
model-zoo symbol script so checkpoints line up 1:1; the builder itself
is original (table-driven like models/resnet.py).
"""
from .. import symbol as sym


def resnext_unit(data, num_filter, stride, dim_match, name, num_group,
                 bottle_neck=True, bn_mom=0.9, workspace=256):
    if bottle_neck:
        conv1 = sym.Convolution(data=data, num_filter=num_filter // 2,
                                kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                no_bias=True, workspace=workspace,
                                name=name + "_conv1")
        bn1 = sym.BatchNorm(data=conv1, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + "_bn1")
        act1 = sym.Activation(data=bn1, act_type="relu",
                              name=name + "_relu1")
        conv2 = sym.Convolution(data=act1, num_filter=num_filter // 2,
                                num_group=num_group, kernel=(3, 3),
                                stride=stride, pad=(1, 1), no_bias=True,
                                workspace=workspace, name=name + "_conv2")
        bn2 = sym.BatchNorm(data=conv2, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + "_bn2")
        act2 = sym.Activation(data=bn2, act_type="relu",
                              name=name + "_relu2")
        conv3 = sym.Convolution(data=act2, num_filter=num_filter,
                                kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                no_bias=True, workspace=workspace,
                                name=name + "_conv3")
        bn3 = sym.BatchNorm(data=conv3, fix_gamma=False, eps=2e-5,
                            momentum=bn_mom, name=name + "_bn3")
        if dim_match:
            shortcut = data
        else:
            sc = sym.Convolution(data=data, num_filter=num_filter,
                                 kernel=(1, 1), stride=stride,
                                 no_bias=True, workspace=workspace,
                                 name=name + "_sc")
            shortcut = sym.BatchNorm(data=sc, fix_gamma=False, eps=2e-5,
                                     momentum=bn_mom,
                                     name=name + "_sc_bn")
        return sym.Activation(data=bn3 + shortcut, act_type="relu",
                              name=name + "_relu")
    conv1 = sym.Convolution(data=data, num_filter=num_filter,
                            kernel=(3, 3), stride=stride, pad=(1, 1),
                            no_bias=True, workspace=workspace,
                            name=name + "_conv1")
    bn1 = sym.BatchNorm(data=conv1, fix_gamma=False, eps=2e-5,
                        momentum=bn_mom, name=name + "_bn1")
    act1 = sym.Activation(data=bn1, act_type="relu",
                          name=name + "_relu1")
    conv2 = sym.Convolution(data=act1, num_filter=num_filter,
                            kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                            no_bias=True, workspace=workspace,
                            name=name + "_conv2")
    bn2 = sym.BatchNorm(data=conv2, fix_gamma=False, eps=2e-5,
                        momentum=bn_mom, name=name + "_bn2")
    if dim_match:
        shortcut = data
    else:
        sc = sym.Convolution(data=data, num_filter=num_filter,
                             kernel=(1, 1), stride=stride, no_bias=True,
                             workspace=workspace, name=name + "_sc")
        shortcut = sym.BatchNorm(data=sc, fix_gamma=False, eps=2e-5,
                                 momentum=bn_mom, name=name + "_sc_bn")
    return sym.Activation(data=bn2 + shortcut, act_type="relu",
                          name=name + "_relu")


# depth -> (bottleneck, per-stage unit counts), ImageNet schedules
_DEPTHS = {
    18: (False, [2, 2, 2, 2]),
    34: (False, [3, 4, 6, 3]),
    50: (True, [3, 4, 6, 3]),
    101: (True, [3, 4, 23, 3]),
    152: (True, [3, 8, 36, 3]),
}


def get_symbol(num_classes=1000, num_layers=50, num_group=32, bn_mom=0.9,
               workspace=256, image_shape=(3, 224, 224)):
    if isinstance(image_shape, str):
        image_shape = tuple(int(x) for x in image_shape.split(","))
    height = image_shape[1]
    if height <= 28:
        # cifar schedules (reference resnext.py: 3 stages, depth tables
        # like resnet's — resnext-29 = 3 bottleneck units per stage)
        if (num_layers - 2) % 9 == 0:
            bottle_neck = True
            units = [(num_layers - 2) // 9] * 3
            filter_list = [16, 64, 128, 256]
        elif (num_layers - 2) % 6 == 0:
            bottle_neck = False
            units = [(num_layers - 2) // 6] * 3
            filter_list = [16, 16, 32, 64]
        else:
            raise ValueError("no cifar resnext-%d schedule" % num_layers)
    elif num_layers in _DEPTHS:
        bottle_neck, units = _DEPTHS[num_layers]
        filter_list = [64, 256, 512, 1024, 2048] if bottle_neck else \
            [64, 64, 128, 256, 512]
    else:
        raise ValueError("no resnext-%d schedule" % num_layers)

    data = sym.Variable("data")
    data = sym.BatchNorm(data=data, fix_gamma=True, eps=2e-5,
                         momentum=bn_mom, name="bn_data")
    if height <= 32:  # cifar stem (reference resnext.py)
        body = sym.Convolution(data=data, num_filter=filter_list[0],
                               kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                               no_bias=True, workspace=workspace,
                               name="conv0")
    else:  # imagenet stem
        body = sym.Convolution(data=data, num_filter=filter_list[0],
                               kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                               no_bias=True, workspace=workspace,
                               name="conv0")
        body = sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                             momentum=bn_mom, name="bn0")
        body = sym.Activation(data=body, act_type="relu", name="relu0")
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                           pad=(1, 1), pool_type="max")

    for i, n_unit in enumerate(units):
        stride = (1, 1) if i == 0 else (2, 2)
        body = resnext_unit(body, filter_list[i + 1], stride, False,
                            "stage%d_unit1" % (i + 1), num_group,
                            bottle_neck, bn_mom, workspace)
        for j in range(n_unit - 1):
            body = resnext_unit(body, filter_list[i + 1], (1, 1), True,
                                "stage%d_unit%d" % (i + 1, j + 2),
                                num_group, bottle_neck, bn_mom, workspace)

    pool = sym.Pooling(data=body, global_pool=True, kernel=(7, 7),
                       pool_type="avg", name="pool1")
    flat = sym.Flatten(data=pool)
    fc1 = sym.FullyConnected(data=flat, num_hidden=num_classes,
                             name="fc1")
    return sym.SoftmaxOutput(data=fc1, name="softmax")
