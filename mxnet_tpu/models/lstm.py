"""char-LSTM language model builders (example/rnn/lstm.py + char-rnn).

Two flavours: ``get_symbol`` via FusedRNNCell (one lax.scan XLA program —
the TPU path) and ``get_unfused_symbol`` via explicitly unrolled LSTMCells
(the reference example/rnn/lstm.py style).
"""
from .. import symbol as sym
from .. import rnn


def get_symbol(seq_len, vocab_size, num_hidden=256, num_embed=128,
               num_layers=2, dropout=0.0, **kwargs):
    cell = rnn.FusedRNNCell(num_hidden, num_layers=num_layers, mode="lstm",
                            dropout=dropout, prefix="lstm_")
    data = sym.Variable("data")
    embed = sym.Embedding(data, input_dim=vocab_size, output_dim=num_embed,
                          name="embed")
    output, _ = cell.unroll(seq_len, inputs=embed, layout="NTC",
                            merge_outputs=True)
    pred = sym.Reshape(output, shape=(-1, num_hidden))
    pred = sym.FullyConnected(pred, num_hidden=vocab_size, name="pred")
    label = sym.Reshape(sym.Variable("softmax_label"), shape=(-1,))
    return sym.SoftmaxOutput(pred, label, name="softmax")


def get_unfused_symbol(seq_len, vocab_size, num_hidden=256, num_embed=128,
                       num_layers=2, dropout=0.0, **kwargs):
    stack = rnn.SequentialRNNCell()
    for i in range(num_layers):
        stack.add(rnn.LSTMCell(num_hidden, prefix="lstm_l%d_" % i))
        if dropout > 0 and i < num_layers - 1:
            stack.add(rnn.DropoutCell(dropout, prefix="lstm_d%d_" % i))
    data = sym.Variable("data")
    embed = sym.Embedding(data, input_dim=vocab_size, output_dim=num_embed,
                          name="embed")
    outputs, _ = stack.unroll(seq_len, inputs=embed, layout="NTC",
                              merge_outputs=True)
    pred = sym.Reshape(outputs, shape=(-1, num_hidden))
    pred = sym.FullyConnected(pred, num_hidden=vocab_size, name="pred")
    label = sym.Reshape(sym.Variable("softmax_label"), shape=(-1,))
    return sym.SoftmaxOutput(pred, label, name="softmax")
