"""Model zoo — symbol builders for the reference's example networks
(example/image-classification/symbols/ + example/rnn).

Each module exposes ``get_symbol(num_classes, ...)`` with the same signature
style as the reference's symbol scripts, built on mxnet_tpu.symbol. These
drive the benchmarks (bench.py) and the example entry points.
"""
from . import mlp
from . import lenet
from . import alexnet
from . import vgg
from . import resnet
from . import resnext
from . import inception_bn
from . import inception_v3
from . import googlenet
from . import lstm

_MODELS = {
    "mlp": mlp, "lenet": lenet, "alexnet": alexnet, "vgg": vgg,
    "inception-bn": inception_bn,
    "inception-v3": inception_v3, "googlenet": googlenet,
}  # resnet/resnext dispatch via the prefix loop in get_symbol


def get_symbol(name, **kwargs):
    """Look up a model by the reference's --network names."""
    for prefix, mod in (("resnext", resnext), ("resnet", resnet)):
        if name.startswith(prefix):
            num_layers = int(name[len(prefix) + 1:]) if "-" in name else 50
            return mod.get_symbol(num_layers=num_layers, **kwargs)
    return _MODELS[name].get_symbol(**kwargs)
