"""Model zoo — symbol builders for the reference's example networks
(example/image-classification/symbols/ + example/rnn).

Each module exposes ``get_symbol(num_classes, ...)`` with the same signature
style as the reference's symbol scripts, built on mxnet_tpu.symbol. These
drive the benchmarks (bench.py) and the example entry points.
"""
from . import mlp
from . import lenet
from . import alexnet
from . import vgg
from . import resnet
from . import resnext
from . import inception_bn
from . import inception_v3
from . import googlenet
from . import inception_resnet_v2
from . import lstm

_MODELS = {
    "mlp": mlp, "lenet": lenet, "alexnet": alexnet, "vgg": vgg,
    "inception-bn": inception_bn,
    "inception-v3": inception_v3, "googlenet": googlenet,
    "inception-resnet-v2": inception_resnet_v2,
}  # resnet/resnext dispatch via the prefix loop in get_symbol


def get_symbol(name, **kwargs):
    """Look up a model by the reference's --network names.

    A ``-bf16`` suffix selects the reduced-precision symbol variant
    (the reference's ``*_fp16`` zoo scripts, bf16 on TPU): input cast
    down at the graph edge, logits cast back to f32 for the softmax.
    """
    if name.endswith("-bf16"):
        base = name[:-len("-bf16")]
        if not (base.startswith("resnet") and not
                base.startswith("resnext")) and base != "alexnet":
            raise ValueError(
                "no -bf16 symbol variant for %r (the reference ships "
                "fp16 scripts for resnet/alexnet only); use "
                "Module(compute_dtype='bfloat16') for any network" % base)
        kwargs.setdefault("dtype", "bfloat16")
        name = base
    for prefix, mod in (("resnext", resnext), ("resnet", resnet)):
        if name.startswith(prefix):
            num_layers = int(name[len(prefix) + 1:]) if "-" in name else 50
            return mod.get_symbol(num_layers=num_layers, **kwargs)
    return _MODELS[name].get_symbol(**kwargs)
