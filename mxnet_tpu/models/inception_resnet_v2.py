"""Inception-ResNet-v2 (example/image-classification/symbols/
inception-resnet-v2.py).

Provenance: model-zoo topology file — the block structure, filter
counts, and residual scalings follow the published Inception-ResNet-v2
architecture (Szegedy et al. 2016) as the reference's zoo script does,
so per-layer comparisons line up; the machinery underneath is the
TPU-native stack.
"""
from .. import symbol as sym


def Conv(data, num_filter, kernel=(1, 1), stride=(1, 1), pad=(0, 0),
         name=None):
    conv = sym.Convolution(data=data, num_filter=num_filter,
                           kernel=kernel, stride=stride, pad=pad,
                           no_bias=True, name="%s_conv" % name)
    bn = sym.BatchNorm(data=conv, fix_gamma=False, name="%s_bn" % name)
    return sym.Activation(data=bn, act_type="relu", name="%s_relu" % name)


def _stem(data):
    x = Conv(data, 32, (3, 3), (2, 2), name="stem1")
    x = Conv(x, 32, (3, 3), name="stem2")
    x = Conv(x, 64, (3, 3), pad=(1, 1), name="stem3")
    x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max",
                    name="stem_pool1")
    x = Conv(x, 80, (1, 1), name="stem4")
    x = Conv(x, 192, (3, 3), name="stem5")
    x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max",
                    name="stem_pool2")
    # mixed 5b (Inception-A)
    b0 = Conv(x, 96, name="m5b_b0")
    b1 = Conv(x, 48, name="m5b_b1a")
    b1 = Conv(b1, 64, (5, 5), pad=(2, 2), name="m5b_b1b")
    b2 = Conv(x, 64, name="m5b_b2a")
    b2 = Conv(b2, 96, (3, 3), pad=(1, 1), name="m5b_b2b")
    b2 = Conv(b2, 96, (3, 3), pad=(1, 1), name="m5b_b2c")
    b3 = sym.Pooling(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="avg", name="m5b_pool")
    b3 = Conv(b3, 64, name="m5b_b3")
    return sym.Concat(b0, b1, b2, b3, name="mixed_5b")


def _block35(x, i, scale=0.17):
    """Inception-ResNet-A: 320-channel residual block."""
    n = "b35_%d" % i
    b0 = Conv(x, 32, name=n + "_b0")
    b1 = Conv(x, 32, name=n + "_b1a")
    b1 = Conv(b1, 32, (3, 3), pad=(1, 1), name=n + "_b1b")
    b2 = Conv(x, 32, name=n + "_b2a")
    b2 = Conv(b2, 48, (3, 3), pad=(1, 1), name=n + "_b2b")
    b2 = Conv(b2, 64, (3, 3), pad=(1, 1), name=n + "_b2c")
    mixed = sym.Concat(b0, b1, b2, name=n + "_concat")
    up = sym.Convolution(mixed, num_filter=320, kernel=(1, 1),
                         name=n + "_up")
    return sym.Activation(x + up * scale, act_type="relu",
                          name=n + "_relu")


def _reduction_a(x):
    b0 = Conv(x, 384, (3, 3), (2, 2), name="redA_b0")
    b1 = Conv(x, 256, name="redA_b1a")
    b1 = Conv(b1, 256, (3, 3), pad=(1, 1), name="redA_b1b")
    b1 = Conv(b1, 384, (3, 3), (2, 2), name="redA_b1c")
    b2 = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max",
                     name="redA_pool")
    return sym.Concat(b0, b1, b2, name="reduction_a")


def _block17(x, i, scale=0.10):
    """Inception-ResNet-B: 1088-channel residual block."""
    n = "b17_%d" % i
    b0 = Conv(x, 192, name=n + "_b0")
    b1 = Conv(x, 128, name=n + "_b1a")
    b1 = Conv(b1, 160, (1, 7), pad=(0, 3), name=n + "_b1b")
    b1 = Conv(b1, 192, (7, 1), pad=(3, 0), name=n + "_b1c")
    mixed = sym.Concat(b0, b1, name=n + "_concat")
    up = sym.Convolution(mixed, num_filter=1088, kernel=(1, 1),
                         name=n + "_up")
    return sym.Activation(x + up * scale, act_type="relu",
                          name=n + "_relu")


def _reduction_b(x):
    b0 = Conv(x, 256, name="redB_b0a")
    b0 = Conv(b0, 384, (3, 3), (2, 2), name="redB_b0b")
    b1 = Conv(x, 256, name="redB_b1a")
    b1 = Conv(b1, 288, (3, 3), (2, 2), name="redB_b1b")
    b2 = Conv(x, 256, name="redB_b2a")
    b2 = Conv(b2, 288, (3, 3), pad=(1, 1), name="redB_b2b")
    b2 = Conv(b2, 320, (3, 3), (2, 2), name="redB_b2c")
    b3 = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max",
                     name="redB_pool")
    return sym.Concat(b0, b1, b2, b3, name="reduction_b")


def _block8(x, i, scale=0.20, relu=True):
    """Inception-ResNet-C: 2080-channel residual block."""
    n = "b8_%d" % i
    b0 = Conv(x, 192, name=n + "_b0")
    b1 = Conv(x, 192, name=n + "_b1a")
    b1 = Conv(b1, 224, (1, 3), pad=(0, 1), name=n + "_b1b")
    b1 = Conv(b1, 256, (3, 1), pad=(1, 0), name=n + "_b1c")
    mixed = sym.Concat(b0, b1, name=n + "_concat")
    up = sym.Convolution(mixed, num_filter=2080, kernel=(1, 1),
                         name=n + "_up")
    out = x + up * scale
    if relu:
        out = sym.Activation(out, act_type="relu", name=n + "_relu")
    return out


def get_symbol(num_classes=1000, n_a=5, n_b=10, n_c=5, **kwargs):
    """Full architecture is (n_a, n_b, n_c) = (10, 20, 10) in the paper;
    the zoo default halves the repeats like the reference script's
    trainable config — pass the paper counts for the exact model."""
    data = sym.Variable("data")
    x = _stem(data)
    for i in range(n_a):
        x = _block35(x, i)
    x = _reduction_a(x)
    for i in range(n_b):
        x = _block17(x, i)
    x = _reduction_b(x)
    for i in range(n_c - 1):
        x = _block8(x, i)
    x = _block8(x, n_c - 1, scale=1.0, relu=False)
    x = Conv(x, 1536, name="conv_final")
    x = sym.Pooling(x, kernel=(8, 8), global_pool=True, pool_type="avg",
                    name="global_pool")
    x = sym.Flatten(x, name="flatten")
    x = sym.Dropout(x, p=0.2, name="dropout")
    fc = sym.FullyConnected(x, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")
