"""GoogLeNet / Inception-v1.

Architecture counterpart of the reference's model-zoo script
(example/image-classification/symbols/googlenet.py), table-driven: the
inception stages are data (Szegedy et al. 2014, table 1), the builders
below realize them. Layer names match the reference exactly so
checkpoints and per-layer comparisons line up 1:1 — names are the
contract, the construction is original.
"""
from .. import symbol as sym

# (name, num_1x1, reduce_3x3, num_3x3, reduce_5x5, num_5x5, pool_proj)
# per inception block, grouped by stage; "P" entries are 3x3/s2 max-pools
_STAGES = [
    "P",
    ("in3a", 64, 96, 128, 16, 32, 32),
    ("in3b", 128, 128, 192, 32, 96, 64),
    "P",
    ("in4a", 192, 96, 208, 16, 48, 64),
    ("in4b", 160, 112, 224, 24, 64, 64),
    ("in4c", 128, 128, 256, 24, 64, 64),
    ("in4d", 112, 144, 288, 32, 64, 64),
    ("in4e", 256, 160, 320, 32, 128, 128),
    "P",
    ("in5a", 256, 160, 320, 32, 128, 128),
    ("in5b", 384, 192, 384, 48, 128, 128),
]


def _conv_relu(x, filters, kernel, name, stride=(1, 1), pad=(0, 0),
               suffix=""):
    x = sym.Convolution(data=x, num_filter=filters, kernel=kernel,
                        stride=stride, pad=pad,
                        name="conv_%s%s" % (name, suffix))
    return sym.Activation(data=x, act_type="relu",
                          name="relu_%s%s" % (name, suffix))


def _inception(x, name, n1, r3, n3, r5, n5, proj):
    """Four parallel towers concatenated on channels: 1x1 / reduced 3x3 /
    reduced 5x5 / pooled projection."""
    t1 = _conv_relu(x, n1, (1, 1), "%s_1x1" % name)
    t3 = _conv_relu(x, r3, (1, 1), "%s_3x3" % name, suffix="_reduce")
    t3 = _conv_relu(t3, n3, (3, 3), "%s_3x3" % name, pad=(1, 1))
    t5 = _conv_relu(x, r5, (1, 1), "%s_5x5" % name, suffix="_reduce")
    t5 = _conv_relu(t5, n5, (5, 5), "%s_5x5" % name, pad=(2, 2))
    tp = sym.Pooling(data=x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="max",
                     name="max_pool_%s_pool" % name)
    tp = _conv_relu(tp, proj, (1, 1), "%s_proj" % name)
    return sym.Concat(t1, t3, t5, tp, name="ch_concat_%s_chconcat" % name)


def get_symbol(num_classes=1000, **kwargs):
    x = sym.Variable("data")
    # stem: 7x7/s2 -> pool -> 1x1 -> 3x3 -> pool
    x = _conv_relu(x, 64, (7, 7), "conv1", stride=(2, 2), pad=(3, 3))
    x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _conv_relu(x, 64, (1, 1), "conv2")
    x = _conv_relu(x, 192, (3, 3), "conv3", pad=(1, 1))
    for entry in _STAGES:
        if entry == "P":
            x = sym.Pooling(x, kernel=(3, 3), stride=(2, 2),
                            pool_type="max")
        else:
            x = _inception(x, entry[0], *entry[1:])
    x = sym.Pooling(x, kernel=(7, 7), stride=(1, 1), global_pool=True,
                    pool_type="avg")
    x = sym.Flatten(data=x)
    x = sym.FullyConnected(data=x, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=x, name="softmax")
