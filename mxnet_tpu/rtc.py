"""Runtime kernel compilation — Pallas instead of NVRTC.

The reference's ``mx.rtc`` compiles CUDA C source at runtime
(include/mxnet/mxrtc.h:26, python/mxnet/rtc.py:91). The TPU-native
equivalent is runtime Pallas: users provide a python kernel body operating
on ``pl.Ref``s (VMEM tiles) — as python source text (API-compatible with
rtc.Rtc's (name, inputs, outputs, body) signature) or a callable — and it
is JIT-compiled for TPU via ``pl.pallas_call`` on first push.
"""
from __future__ import annotations

import textwrap

import numpy as onp

from .base import MXNetError

__all__ = ["Rtc", "PallasKernel"]


class PallasKernel(object):
    """Compile + run a user Pallas kernel.

    kernel_fn(*refs): standard Pallas kernel taking input Refs then output
    Refs; use jnp ops on ``ref[...]``.
    """

    def __init__(self, kernel_fn, name="rtc_kernel"):
        self.kernel_fn = kernel_fn
        self.name = name
        self._compiled = {}

    def __call__(self, inputs, out_shapes, out_dtypes=None, interpret=None):
        import jax
        from jax.experimental import pallas as pl
        import jax.numpy as jnp

        vals = [x._read() if hasattr(x, "_read") else jnp.asarray(x)
                for x in inputs]
        if out_dtypes is None:
            out_dtypes = [vals[0].dtype] * len(out_shapes)
        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"
        key = tuple((tuple(v.shape), str(v.dtype)) for v in vals) + \
            tuple((tuple(s), str(d)) for s, d in zip(out_shapes, out_dtypes))
        if key not in self._compiled:
            out_struct = [jax.ShapeDtypeStruct(tuple(s), d)
                          for s, d in zip(out_shapes, out_dtypes)]
            call = pl.pallas_call(self.kernel_fn, out_shape=out_struct,
                                  interpret=interpret)
            self._compiled[key] = jax.jit(call)
        outs = self._compiled[key](*vals)
        from .ndarray import NDArray
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        return [NDArray(o) for o in outs]


class Rtc(object):
    """Source-text API mirroring python/mxnet/rtc.py Rtc(name, inputs,
    outputs, kernel). The kernel body is python/Pallas source; input and
    output names bind to Refs in order.

    Example::

        rtc = mx.rtc.Rtc('axpy', [('x', x), ('y', y)], [('z', z)],
                         "z_ref[...] = x_ref[...] * 2.0 + y_ref[...]")
        rtc.push([x, y], [z], (1,1,1), (1,1,1))
    """

    def __init__(self, name, inputs, outputs, kernel):
        self.name = name
        self.input_names = [n for n, _ in inputs]
        self.output_names = [n for n, _ in outputs]
        args = ", ".join(["%s_ref" % n for n in self.input_names]
                         + ["%s_ref" % n for n in self.output_names])
        src = "def _kernel(%s):\n%s\n" % (
            args, textwrap.indent(textwrap.dedent(kernel), "    "))
        scope = {}
        try:
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            scope.update({"jnp": jnp, "pl": pl})
            exec(src, scope)  # noqa: S102 - explicit runtime compilation API
        except SyntaxError as e:
            raise MXNetError("invalid rtc kernel source: %s" % e)
        self._pk = PallasKernel(scope["_kernel"], name=name)

    def push(self, inputs, outputs, grid_dims=None, block_dims=None):
        """Run the kernel; grid/block dims accepted for API compat (Pallas
        grids come from BlockSpecs; simple elementwise kernels need none)."""
        out_shapes = [tuple(o.shape) for o in outputs]
        out_dtypes = [onp.dtype(o.dtype) for o in outputs]
        results = self._pk(inputs, out_shapes, out_dtypes)
        for o, r in zip(outputs, results):
            r.copyto(o)
        return outputs
