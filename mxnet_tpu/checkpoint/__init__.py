"""mxnet_tpu.checkpoint — durable training-state persistence.

The checkpoint subsystem (docs/api/checkpoint.md). Three layers:

* :class:`CheckpointManager` — a directory of step-numbered entries,
  each committed atomically (temp dir + fsync + rename), saved async on
  the host engine worker, sharded per local device shard, and
  garbage-collected by a ``keep``/``keep_every`` retention policy.
* :mod:`~mxnet_tpu.checkpoint.serialize` — atomic file writes, per-shard
  array files with crc32 verification, shard snapshot/reassembly.
* legacy helpers — the reference-era ``arg:``/``aux:`` flat param file
  (``prefix-%04d.params``) packing shared by ``model.save_checkpoint``,
  ``Module.save_checkpoint`` and ``BaseModule.save_params``, now written
  atomically through :func:`mxnet_tpu.ndarray.save`.
"""
from __future__ import annotations

from .manager import Checkpoint, CheckpointManager
from .serialize import params_digest
from . import serialize

__all__ = ["Checkpoint", "CheckpointManager", "serialize",
           "pack_params", "split_params", "save_params_file",
           "load_params_file", "params_digest"]


def pack_params(arg_params, aux_params):
    """Flatten (arg_params, aux_params) into one ``arg:``/``aux:``
    prefixed dict — the name-packing every checkpoint format shares."""
    packed = {("arg:%s" % k): v for k, v in (arg_params or {}).items()}
    packed.update({("aux:%s" % k): v
                   for k, v in (aux_params or {}).items()})
    return packed


def split_params(packed):
    """Inverse of :func:`pack_params`; unknown prefixes raise."""
    from ..base import MXNetError
    arg_params, aux_params = {}, {}
    for k, v in packed.items():
        kind, _, name = k.partition(":")
        if kind == "arg":
            arg_params[name] = v
        elif kind == "aux":
            aux_params[name] = v
        else:
            raise MXNetError("invalid checkpoint param key %r "
                             "(want arg:/aux: prefix)" % (k,))
    return arg_params, aux_params


def save_params_file(fname, arg_params, aux_params):
    """Write the legacy flat ``.params`` file (atomically)."""
    from .. import ndarray as nd
    nd.save(fname, pack_params(arg_params, aux_params))


def load_params_file(fname):
    """Load a legacy flat ``.params`` file -> (arg_params, aux_params)."""
    from .. import ndarray as nd
    return split_params(nd.load(fname))
