"""CheckpointManager — async, sharded, atomic step checkpoints.

One manager owns one checkpoint directory of step-numbered entries::

    <dir>/step_00000003/           committed entry (the rename IS the commit)
        manifest.json              per-array shapes/dtypes/shard crc32s
        a00001_s00.npy ...         one file per (array, local shard)
        optimizer.bin              raw optimizer-state bytes (optional)
        rng.npz                    global RNG state (optional)
    <dir>/.tmp-step_00000004-*/    in-flight or crashed partial entry

Durability contract: every file in an entry is written and fsynced
inside a ``.tmp-*`` staging dir, the dir itself is fsynced, and only
then is the staging dir renamed onto ``step_NNNNNNNN`` (and the parent
fsynced). A crash at ANY point — including mid-rename — leaves either a
committed entry or an ignorable ``.tmp-*``; :meth:`latest` only ever
reports entries whose manifest is in place, so the previous good step
stays restorable.

Saves run **async** by default: ``save()`` snapshots every array to
host memory synchronously (cheap, and immune to later in-place /
donated-buffer mutation by the next train step), then hands
serialization + commit to the host :class:`~mxnet_tpu.engine.Engine`
worker so the next ``fit`` step overlaps the disk write. ``save()``
itself is the error-propagation barrier: it waits for the previous
save and re-raises its failure before snapshotting the next one;
``wait_until_finished()`` does the same on demand.

Sharded arrays (jax Arrays carrying a mesh ``NamedSharding``) write one
file per unique local shard — no full gather — and restore re-assembles
the global array on host, so an entry saved on an 8-device mesh loads
onto 1 device (or any other layout).
"""
from __future__ import annotations

import atexit
import logging
import os
import re
import shutil
import time
import uuid
from collections import namedtuple

from .. import engine as _engine
from .. import faults as _faults
from .. import random as _random
from .. import telemetry
from ..base import MXNetError
from . import serialize

# one shared scope: checkpoint traffic is a per-process story (the
# Prometheus/JSONL view), managers come and go per directory
_TEL = telemetry.registry().scope("checkpoint")

__all__ = ["CheckpointManager", "Checkpoint", "is_checkpoint_dir"]

_STEP_FMT = "step_%08d"
_STEP_RE = re.compile(r"^step_(\d{8})$")
_TMP_PREFIX = ".tmp-"
_MANIFEST = "manifest.json"

Checkpoint = namedtuple(
    "Checkpoint", ["step", "params", "optimizer_state", "extra", "rng"])
Checkpoint.__doc__ = """A restored checkpoint entry.

``params`` maps array name -> assembled global numpy array;
``optimizer_state`` is the raw bytes handed to ``save()`` (or None);
``extra`` the JSON metadata dict; ``rng`` a ``mxnet_tpu.random``
state dict (or None).
"""


def is_checkpoint_dir(path):
    """True if ``path`` is a directory holding at least one committed
    ``step_NNNNNNNN`` entry (used to disambiguate manager directories
    from legacy file prefixes that happen to name a directory)."""
    if not os.path.isdir(path):
        return False
    for name in os.listdir(path):
        if _STEP_RE.match(name) and os.path.exists(
                os.path.join(path, name, _MANIFEST)):
            return True
    return False


def _commit_entry(tmp_dir, final_dir):
    """The atomic commit: fsync the staged entry, rename it onto its
    step name, fsync the parent. Everything before the rename is
    invisible to readers; a crash before it leaves only ``.tmp-*``."""
    serialize.fsync_dir(tmp_dir)
    os.replace(tmp_dir, final_dir)
    serialize.fsync_dir(os.path.dirname(final_dir))


class CheckpointManager(object):
    """Owns a directory of atomic, step-numbered checkpoint entries.

    Parameters
    ----------
    directory : str
        Root of the checkpoint tree (created if missing).
    keep : int or None
        Retain only the newest ``keep`` committed steps (None = all).
    keep_every : int or None
        Additionally retain every step divisible by ``keep_every``
        (a sparse long-horizon trail the ``keep`` window won't GC).
    """

    def __init__(self, directory, keep=None, keep_every=None):
        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)
        if keep is not None and keep < 1:
            raise ValueError("keep must be >= 1 (the latest entry is "
                             "never garbage-collected)")
        if keep_every is not None and keep_every < 1:
            raise ValueError("keep_every must be >= 1")
        self.keep = keep
        self.keep_every = keep_every
        self._pending = []     # [(event, errbox, step)]
        self._atexit_registered = False

    def _drain_at_exit(self):
        try:
            self.wait_until_finished()
        except Exception:   # noqa: BLE001 - can't raise during shutdown
            logging.getLogger(__name__).exception(
                "async checkpoint save failed during interpreter exit")

    def _sweep_partials(self):
        """Remove crashed ``.tmp-*`` partials. Called from :meth:`save`
        only — a saver owns the directory (single-writer contract) and
        its own staged entries are committed by the ``save()`` barrier
        before this runs; read-only managers (``Module.load``,
        ``restore``) never sweep, so constructing one on a directory a
        live trainer is writing into is safe."""
        for name in os.listdir(self.directory):
            if name.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # ------------------------------------------------------------ query
    def _entry_dir(self, step):
        return os.path.join(self.directory, _STEP_FMT % step)

    def all_steps(self):
        """Sorted committed steps (entries with a manifest in place)."""
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.exists(os.path.join(self.directory, name,
                                                 _MANIFEST)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest(self):
        """Newest committed step, or None. Never reports an in-flight,
        partial, or crashed entry."""
        steps = self.all_steps()
        return steps[-1] if steps else None

    # ------------------------------------------------------------- save
    def save(self, step, arrays, optimizer_state=None, extra=None,
             rng_state="auto", async_save=True):
        """Stage a new checkpoint entry for ``step``.

        ``arrays`` maps name -> NDArray / jax.Array / numpy array.
        Arrays are snapshotted to host *now* (so the caller may mutate
        or donate the originals immediately); serialization and the
        atomic commit run on the engine worker when ``async_save``.
        Raises any error from the *previous* async save first.
        """
        step = int(step)
        self.wait_until_finished()   # barrier + previous-save errors
        self._sweep_partials()
        if step in self.all_steps():
            raise MXNetError("checkpoint step %d already exists in %s"
                             % (step, self.directory))
        snaps = [(str(name), serialize.snapshot(value))
                 for name, value in arrays.items()]
        if rng_state == "auto":
            rng_state = _random.get_state()
        opt_bytes = bytes(optimizer_state) if optimizer_state is not None \
            else None
        extra = dict(extra or {})
        save_time = time.time()
        tmp = os.path.join(self.directory, "%s%s-%s" % (
            _TMP_PREFIX, _STEP_FMT % step, uuid.uuid4().hex[:8]))
        final = self._entry_dir(step)
        errbox = []

        n_bytes = sum(arr.nbytes for _name, shards in snaps
                      for _idx, arr in shards)
        if opt_bytes is not None:
            n_bytes += len(opt_bytes)

        def attempt():
            # retryable unit: a retried transient fault (an injected
            # TransientFault by default — pass your own retry policy
            # for real flaky-storage classes) re-stages the WHOLE
            # entry — the half-written tmp is dropped first, so a
            # retry can never commit a torn mix of two attempts
            shutil.rmtree(tmp, ignore_errors=True)
            self._write_entry(tmp, step, snaps, opt_bytes, extra,
                              rng_state, save_time)
            if _faults.armed():
                # kill-mid-commit seam: the entry is fully staged but
                # the rename never happens — exactly what a process
                # death here leaves behind
                _faults.check("checkpoint.commit", step=step)
            _commit_entry(tmp, final)

        def job():
            t0 = time.perf_counter()
            try:
                with telemetry.span("checkpoint.save", step=step):
                    _faults.retry(attempt, site="checkpoint.save",
                                  seed=step)
                if _faults.armed():
                    # post-commit corruption seams: bit-flip a shard /
                    # corrupt the manifest of the COMMITTED entry (a
                    # storage fault after a clean commit) — restore()
                    # must fall back to the previous verifiable entry
                    _faults.corrupt_file("checkpoint.shard", final,
                                         pattern="a*.npy", step=step)
                    _faults.corrupt_file("checkpoint.manifest", final,
                                         pattern=_MANIFEST, step=step)
                self._gc()
                # duration + bytes land in the shared registry: the
                # telemetry story for "how much is checkpointing
                # costing" without any readback or extra I/O
                _TEL.counter("saves").add()
                _TEL.counter("save_ms").add(
                    (time.perf_counter() - t0) * 1000.0)
                _TEL.counter("bytes_written").add(n_bytes)
                _TEL.gauge("last_step").set(step)
            except BaseException as exc:  # noqa: BLE001 - repropagated
                _TEL.counter("save_errors").add()
                errbox.append(exc)
                shutil.rmtree(tmp, ignore_errors=True)

        if async_save:
            if not self._atexit_registered:
                # drain staged saves at interpreter exit: the engine
                # worker is a daemon thread, so the final async save of
                # a run that just falls off the end of fit() would
                # otherwise be killed mid-write (entry uncommitted) and
                # its error never surface. Registered lazily so
                # read-only managers (Module.load, resume_from) are not
                # pinned for the process lifetime.
                atexit.register(self._drain_at_exit)
                self._atexit_registered = True
            event = _engine.get().push_async(job)
            self._pending.append((event, errbox, step))
        else:
            job()
            if errbox:
                raise MXNetError("checkpoint save (step %d) failed"
                                 % step) from errbox[0]
        return step

    def _write_entry(self, tmp, step, snaps, opt_bytes, extra, rng_state,
                     save_time):
        os.makedirs(tmp)
        manifest = {"format": serialize.FORMAT, "step": step,
                    "save_unix_time": save_time, "extra": extra,
                    "arrays": {}}
        for ai, (name, shards) in enumerate(snaps):
            full = next((arr for idx, arr in shards if idx is None), None)
            if full is not None:
                gshape = list(full.shape)
            else:  # global extent = max stop bound per dim over shards
                gshape = [max(idx[d][1] for idx, _ in shards)
                          for d in range(len(shards[0][0]))]
            entry = {"shape": gshape,
                     "dtype": str(shards[0][1].dtype),
                     "shards": []}
            for si, (idx, arr) in enumerate(shards):
                fname = "a%05d_s%02d.npy" % (ai, si)
                meta = serialize.write_array(os.path.join(tmp, fname), arr)
                meta["file"] = fname
                meta["index"] = None if idx is None else \
                    [[int(a), int(b)] for a, b in idx]
                entry["shards"].append(meta)
            manifest["arrays"][name] = entry
        if opt_bytes is not None:
            crc = serialize.write_bytes(os.path.join(tmp, "optimizer.bin"),
                                        opt_bytes)
            manifest["optimizer"] = {"file": "optimizer.bin",
                                     "size": len(opt_bytes), "crc32": crc}
        else:
            manifest["optimizer"] = None
        if rng_state is not None:
            serialize.dump_rng(os.path.join(tmp, "rng.npz"), rng_state)
            manifest["rng"] = {"file": "rng.npz"}
        else:
            manifest["rng"] = None
        serialize.write_json(os.path.join(tmp, _MANIFEST), manifest)

    def wait_until_finished(self):
        """Block until all async saves committed; re-raise the first
        failure (the error-propagation barrier)."""
        pending, self._pending = self._pending, []
        first = None
        for event, errbox, step in pending:
            event.wait()
            if errbox and first is None:
                first = (step, errbox[0])
        if first is not None:
            raise MXNetError("async checkpoint save (step %d) failed"
                             % first[0]) from first[1]

    def step_metadata(self, step=None):
        """The ``extra`` metadata of a committed entry (default: the
        latest) WITHOUT loading any arrays — how the elastic trainer
        and the multi-host dryrun read a step's resume coordinates
        (``epoch``/``nbatch``/``num_update``/``dp_width``) cheaply."""
        self.wait_until_finished()   # same barrier restore() takes
        if step is None:
            step = self.latest()
            if step is None:
                return None
        manifest_path = os.path.join(self._entry_dir(int(step)), _MANIFEST)
        if not os.path.exists(manifest_path):
            raise MXNetError("checkpoint step %d is not committed in %s"
                             % (int(step), self.directory))
        return dict(serialize.read_json(manifest_path).get("extra", {}))

    # ---------------------------------------------------------- restore
    def restore(self, step=None):
        """Load a committed entry as a :class:`Checkpoint`,
        re-assembling sharded arrays into global host arrays regardless
        of the saving mesh layout.

        With ``step=None`` (the resume path), restore walks BACK from
        the newest committed entry to the newest entry that passes
        verification: a latest entry whose manifest is unreadable or
        whose shards fail their crc32/shape checks is skipped with ONE
        loud warning per bad entry (plus a FlightRecorder
        ``checkpoint_fallback`` note), and the previous committed entry
        restores instead — losing the corrupt step's work beats losing
        the job. Only when NO entry verifies does restore refuse.
        An explicit ``step`` is an exact request and stays terminal on
        corruption (the caller asked for those bytes)."""
        self.wait_until_finished()
        if step is not None:
            return self._restore_entry(int(step))
        candidates = sorted(self.all_steps(), reverse=True)
        if not candidates:
            raise MXNetError("no committed checkpoint in %s"
                             % self.directory)
        log = logging.getLogger(__name__)
        failures = []
        for s in candidates:
            try:
                ckpt = self._restore_entry(s)
            except Exception as exc:  # noqa: BLE001 — ANY failure to
                # load this entry (crc refusal, torn JSON that still
                # parsed, missing nested manifest keys) means it does
                # not verify; the walkback's job is to reach an entry
                # that does, logging what it skipped
                failures.append((s, exc))
                _TEL.counter("restore_fallbacks").add()
                log.warning(
                    "checkpoint step %d in %s failed verification (%s); "
                    "falling back to the previous committed entry",
                    s, self.directory, exc)
                telemetry.flight_recorder().note(
                    "checkpoint_fallback", step=s, error=str(exc))
                continue
            if failures:
                log.warning(
                    "restored checkpoint step %d after skipping %d "
                    "corrupt newer entr%s", s, len(failures),
                    "y" if len(failures) == 1 else "ies")
            return ckpt
        raise MXNetError(
            "no checkpoint entry in %s passed verification (%d "
            "candidate%s); newest failure: step %d: %s"
            % (self.directory, len(failures),
               "" if len(failures) == 1 else "s",
               failures[0][0], failures[0][1]))

    def restore_before(self, predicate, verify=None):
        """Walk committed entries newest -> oldest and restore the
        newest one that (a) satisfies ``predicate(step, extra)`` over
        its manifest metadata, (b) passes the per-entry artifact
        verification (crc32/shape/manifest), and (c) passes the
        optional ``verify(ckpt) -> None | reason-str`` hook on the
        loaded payload.

        This is the training guardian's restore-to-step-before-
        coordinate primitive (:mod:`mxnet_tpu.guardian`): ``predicate``
        excludes entries that already trained the poisoned data
        coordinate, and ``verify`` lets the caller reject entries whose
        BYTES verify but whose VALUES are unusable (non-finite
        parameters from a read-path SDC). Every skipped entry logs one
        loud warning and counts into ``checkpoint.restore_fallbacks``
        (the :meth:`restore` walk-back discipline). Raises when no
        entry qualifies."""
        self.wait_until_finished()
        log = logging.getLogger(__name__)
        candidates = sorted(self.all_steps(), reverse=True)
        skipped = 0
        for s in candidates:
            try:
                extra = dict(serialize.read_json(os.path.join(
                    self._entry_dir(s), _MANIFEST)).get("extra", {}))
                qualifies = bool(predicate(s, extra))
            except Exception as exc:  # noqa: BLE001 — unreadable or
                # garbage metadata (and a predicate that chokes on it)
                # means the entry's POSITION is unknowable: it must be
                # SKIPPED like a corrupt entry, never restored — an
                # entry inside the poisoned trajectory would otherwise
                # slip through on a torn manifest extra
                skipped += 1
                _TEL.counter("restore_fallbacks").add()
                log.warning(
                    "checkpoint step %d in %s has unusable metadata "
                    "for rollback (%s); falling back to the previous "
                    "committed entry", s, self.directory, exc)
                telemetry.flight_recorder().note(
                    "checkpoint_fallback", step=s, error=str(exc))
                continue
            if not qualifies:
                continue
            try:
                ckpt = self._restore_entry(s)
                reason = verify(ckpt) if verify is not None else None
            except Exception as exc:  # noqa: BLE001
                reason = str(exc)
                ckpt = None
            if ckpt is not None and not reason:
                if skipped:
                    log.warning(
                        "restored checkpoint step %d after skipping %d "
                        "unusable newer entr%s", s, skipped,
                        "y" if skipped == 1 else "ies")
                return ckpt
            skipped += 1
            _TEL.counter("restore_fallbacks").add()
            log.warning(
                "checkpoint step %d in %s is unusable for rollback "
                "(%s); falling back to the previous committed entry",
                s, self.directory, reason)
            telemetry.flight_recorder().note(
                "checkpoint_fallback", step=s, error=str(reason))
        raise MXNetError(
            "no checkpoint entry in %s both precedes the requested "
            "coordinate and passes verification (%d candidate%s)"
            % (self.directory, len(candidates),
               "" if len(candidates) == 1 else "s"))

    def discard_after(self, step):
        """Delete committed entries NEWER than ``step`` (the rollback
        truncation: after the guardian restores to a pre-poison entry,
        every newer entry belongs to the poisoned trajectory — keeping
        them would both resurrect bad state on the next resume and
        collide with the replay's re-commits at the same step ids).
        Returns the discarded step list."""
        self.wait_until_finished()
        step = int(step)
        dropped = [s for s in self.all_steps() if s > step]
        for s in dropped:
            shutil.rmtree(self._entry_dir(s), ignore_errors=True)
        if dropped:
            logging.getLogger(__name__).warning(
                "discarded %d checkpoint entr%s after step %d (%s)",
                len(dropped), "y" if len(dropped) == 1 else "ies",
                step, dropped)
            _TEL.counter("discarded_entries").add(len(dropped))
        return dropped

    def _restore_entry(self, step):
        """Load + verify ONE committed entry (crc32/shape/dtype per
        shard); any corruption raises :class:`MXNetError` naming the
        failing artifact."""
        step = int(step)
        t0 = time.perf_counter()
        entry = self._entry_dir(step)
        manifest_path = os.path.join(entry, _MANIFEST)
        if not os.path.exists(manifest_path):
            raise MXNetError("checkpoint step %d is not committed in %s"
                             % (step, self.directory))
        try:
            manifest = serialize.read_json(manifest_path)
        except (ValueError, OSError) as exc:
            raise MXNetError(
                "checkpoint manifest %s is unreadable (corrupt or "
                "truncated): %s" % (manifest_path, exc)) from exc
        if manifest.get("format") != serialize.FORMAT:
            raise MXNetError("unknown checkpoint format %r in %s"
                             % (manifest.get("format"), entry))
        params = {}
        try:
            array_items = list(manifest["arrays"].items())
        except (KeyError, AttributeError) as exc:
            raise MXNetError(
                "checkpoint manifest %s has no arrays table (corrupt "
                "or hand-edited)" % manifest_path) from exc
        for name, meta in array_items:
            shards = []
            for smeta in meta["shards"]:
                try:
                    arr = serialize.read_array(
                        os.path.join(entry, smeta["file"]), smeta)
                except (OSError, ValueError) as exc:
                    # a missing/undecodable .npy is the same verdict a
                    # crc mismatch gets: the entry does not verify
                    raise MXNetError(
                        "checkpoint shard %s is unreadable (corrupt or "
                        "truncated): %s"
                        % (os.path.join(entry, smeta["file"]),
                           exc)) from exc
                idx = smeta["index"]
                shards.append((None if idx is None else
                               tuple((a, b) for a, b in idx), arr))
            params[name] = serialize.assemble(meta["shape"], meta["dtype"],
                                              shards)
        opt_bytes = None
        if manifest.get("optimizer"):
            with open(os.path.join(entry,
                                   manifest["optimizer"]["file"]),
                      "rb") as f:
                opt_bytes = f.read()
            import zlib
            if (zlib.crc32(opt_bytes) & 0xFFFFFFFF) != \
                    manifest["optimizer"]["crc32"]:
                raise MXNetError("optimizer state in step %d failed its "
                                 "crc32 check" % step)
        rng = None
        if manifest.get("rng"):
            rng = serialize.load_rng(
                os.path.join(entry, manifest["rng"]["file"]))
        if _faults.armed():
            # restore hand-off SDC seam (kind=param_bitflip): corrupt
            # one element of the ASSEMBLED params after the crc checks
            # passed — a silent read-path corruption the bytes-level
            # verification structurally cannot catch; the guardian's
            # value-level verify / param sentinel is what must
            _faults.corrupt_params("checkpoint.params", params,
                                   step=step)
        _TEL.counter("restores").add()
        _TEL.counter("restore_ms").add((time.perf_counter() - t0) * 1000.0)
        _TEL.counter("bytes_read").add(
            sum(p.nbytes for p in params.values())
            + (len(opt_bytes) if opt_bytes else 0))
        return Checkpoint(step=step, params=params,
                          optimizer_state=opt_bytes,
                          extra=manifest.get("extra", {}), rng=rng)

    # --------------------------------------------------------------- gc
    def _retained(self, steps):
        if not steps:
            return set()
        kept = {steps[-1]}                       # latest is untouchable
        if self.keep is None and self.keep_every is None:
            return set(steps)
        if self.keep is not None:
            kept.update(steps[-self.keep:])
        if self.keep_every is not None:
            kept.update(s for s in steps if s % self.keep_every == 0)
        return kept

    def _gc(self):
        """Apply the retention policy to committed entries (runs after
        every successful commit)."""
        steps = self.all_steps()
        kept = self._retained(steps)
        for s in steps:
            if s not in kept:
                shutil.rmtree(self._entry_dir(s), ignore_errors=True)
