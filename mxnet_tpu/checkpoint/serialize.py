"""Durable array serialization for the checkpoint subsystem.

Low-level pieces the :class:`~mxnet_tpu.checkpoint.CheckpointManager`
builds entries out of:

* **atomic file writes** — write to a ``.tmp`` sibling, ``fsync``,
  ``os.replace`` (POSIX rename atomicity), then ``fsync`` the directory
  so the rename itself is durable. A crash at any point leaves either
  the old file or a stray ``.tmp`` that readers ignore.
* **host shard snapshots** — :func:`snapshot` copies any checkpointable
  value (NDArray, jax.Array, numpy) to host memory as a list of
  ``(index, numpy array)`` shards. Mesh-sharded jax arrays are deduped
  per unique shard index (each replica group writes its slice exactly
  once, no full gather ever materializes); replicated and host arrays
  come back as one full shard. :func:`assemble` is the inverse and is
  what lets a checkpoint written on an 8-device mesh restore onto a
  single device (or any other layout).
* **self-describing array files** — one ``.npy`` per shard plus
  per-shard crc32/shape/dtype entries in the manifest, verified on
  read. The format is documented in docs/api/checkpoint.md and is NOT
  binary-compatible with the reference's ``.params`` container.
"""
from __future__ import annotations

import json
import os
import zlib

import numpy as onp

from ..base import MXNetError

FORMAT = "mxnet_tpu.checkpoint/v1"

__all__ = ["FORMAT", "fsync_dir", "atomic_write_stream",
           "atomic_write_bytes", "write_bytes", "write_array",
           "read_array", "snapshot", "assemble", "write_json",
           "read_json", "dump_rng", "load_rng", "params_digest"]


def params_digest(symbol_json, arrays):
    """Structural identity of a (symbol, parameter set) pair: sha256
    over the symbol JSON plus every array's canonical
    ``name|shape|dtype`` line, sorted by name.

    THE one keying rule shared by checkpoint manifests
    (``Module.save_checkpoint(manager=...)`` records it as
    ``params_digest``) and the serving executable cache
    (``mxnet_tpu.serving.cache`` keys AOT entries by it): a compiled
    bucket program depends on the program structure and the parameter
    shapes/dtypes — the parameter VALUES are runtime inputs, so two
    checkpoints of the same architecture share executables while any
    architecture drift (layer widths, added params, a dtype change)
    produces a different digest and refuses a stale executable.

    ``arrays`` maps name -> anything with ``shape``/``dtype`` (NDArray,
    jax array, numpy). Scalars hash as shape ``()``.
    """
    import hashlib
    h = hashlib.sha256()
    h.update(str(symbol_json).encode("utf-8"))
    for name in sorted(arrays):
        v = arrays[name]
        shape = tuple(getattr(v, "shape", ()))
        dtype = onp.dtype(getattr(v, "dtype", onp.float32)).name
        h.update(("\n%s|%s|%s" % (name, shape, dtype)).encode("utf-8"))
    return h.hexdigest()


def fsync_dir(path):
    """fsync a directory so a rename/create inside it is durable.
    Best-effort: some filesystems/platforms reject directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_bytes(path, payload):
    """Write + fsync ``payload`` at ``path`` (no atomicity by itself —
    used INSIDE a temp entry dir whose rename is the commit). Returns
    the payload's crc32."""
    with open(path, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    return zlib.crc32(payload) & 0xFFFFFFFF


def atomic_write_stream(fname, write_cb):
    """Crash-safe single-file write: ``write_cb(fileobj)`` streams into
    a ``.tmp`` sibling, which is fsynced and renamed over ``fname``.
    Streaming keeps multi-GB payloads (``nd.save`` param files) out of
    host memory."""
    tmp = fname + ".tmp"
    with open(tmp, "wb") as f:
        write_cb(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, fname)
    fsync_dir(os.path.dirname(os.path.abspath(fname)) or ".")


def atomic_write_bytes(fname, payload):
    """Crash-safe single-file write of an in-memory payload."""
    atomic_write_stream(fname, lambda f: f.write(payload))


def write_json(path, obj):
    return write_bytes(path, json.dumps(obj, indent=1,
                                        sort_keys=True).encode("utf-8"))


def read_json(path):
    with open(path, "rb") as f:
        return json.loads(f.read().decode("utf-8"))


# ---------------------------------------------------------------------------
# per-shard array files
# ---------------------------------------------------------------------------
def write_array(path, arr):
    """Write one shard as .npy (+fsync); returns its manifest entry."""
    arr = onp.ascontiguousarray(arr)
    crc = zlib.crc32(arr.tobytes()) & 0xFFFFFFFF
    with open(path, "wb") as f:
        onp.save(f, arr, allow_pickle=False)
        f.flush()
        os.fsync(f.fileno())
    return {"shape": list(arr.shape), "dtype": onp.dtype(arr.dtype).name,
            "crc32": crc}


def read_array(path, meta):
    """Load one shard, verifying shape/dtype/crc32 from its manifest
    entry — a truncated or bit-flipped shard fails loudly here instead
    of silently corrupting a restore."""
    with open(path, "rb") as f:
        arr = onp.load(f, allow_pickle=False)
    if list(arr.shape) != list(meta["shape"]) or \
            onp.dtype(arr.dtype).name != meta["dtype"]:
        raise MXNetError(
            "checkpoint shard %s does not match its manifest: "
            "got %s/%s, manifest says %s/%s"
            % (path, arr.shape, arr.dtype, meta["shape"], meta["dtype"]))
    crc = zlib.crc32(onp.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF
    if crc != meta["crc32"]:
        raise MXNetError("checkpoint shard %s failed its crc32 check "
                         "(corrupt or truncated write)" % path)
    return arr


# ---------------------------------------------------------------------------
# shard snapshot / reassembly
# ---------------------------------------------------------------------------
def _normalize_index(index, shape):
    """jax shard index (tuple of slices) -> tuple of (start, stop)."""
    from ..parallel.mesh import shard_bounds
    try:
        return shard_bounds(index, shape)
    except ValueError as exc:
        raise MXNetError(str(exc)) from exc


def snapshot(value):
    """Host-copy a checkpointable value into ``[(index, ndarray), ...]``.

    ``index`` is ``None`` for a full (replicated / host) array, else a
    tuple of per-dim ``(start, stop)`` bounds. jax Arrays sharded over a
    mesh are deduped by shard index so each slice is copied exactly once
    per process — the per-host sharded-save primitive.
    """
    if hasattr(value, "_read"):              # NDArray (possibly a view)
        value = value._read()
    shards = getattr(value, "addressable_shards", None)
    if shards is None or not hasattr(value, "sharding"):
        return [(None, onp.asarray(value))]  # numpy / scalar
    shape = tuple(value.shape)
    try:
        replicated = bool(value.sharding.is_fully_replicated)
    except Exception:
        replicated = False
    if replicated or not shape:
        return [(None, onp.asarray(value))]
    seen = {}
    for sh in shards:
        idx = _normalize_index(sh.index, shape)
        if idx not in seen:
            seen[idx] = onp.asarray(sh.data)
    if len(seen) == 1:
        (idx, arr), = seen.items()
        if all(a == 0 and b == n for (a, b), n in zip(idx, shape)):
            return [(None, arr)]
    return sorted(seen.items())


def assemble(shape, dtype, shards):
    """Rebuild the global host array from ``[(index, ndarray), ...]``
    shards — the cross-mesh restore path (shard count/layout at save
    time need not match the restoring process)."""
    shape = tuple(int(s) for s in shape)
    if len(shards) == 1 and shards[0][0] is None:
        arr = shards[0][1]
        if tuple(arr.shape) != shape:
            raise MXNetError("checkpoint array shape %s != manifest %s"
                             % (arr.shape, shape))
        return onp.asarray(arr, dtype=dtype)
    out = onp.zeros(shape, dtype=dtype)
    covered = 0
    for idx, arr in shards:
        if idx is None:
            raise MXNetError("mixed full/sharded entries for one array")
        out[tuple(slice(a, b) for a, b in idx)] = arr
        covered += arr.size
    if covered != out.size:
        raise MXNetError(
            "checkpoint shards cover %d of %d elements — entry is "
            "incomplete or overlapping" % (covered, out.size))
    return out


# ---------------------------------------------------------------------------
# RNG state (mxnet_tpu.random.get_state() dict) <-> one npz file
# ---------------------------------------------------------------------------
def dump_rng(path, state):
    import io
    buf = io.BytesIO()
    kind, keys, pos, has_gauss, cached = state["numpy"]
    onp.savez(buf, jax_key=onp.asarray(state["jax_key"], onp.uint32),
              np_kind=onp.array(kind), np_keys=onp.asarray(keys),
              np_pos=onp.array(pos), np_has_gauss=onp.array(has_gauss),
              np_cached=onp.array(cached))
    return write_bytes(path, buf.getvalue())


def load_rng(path):
    with onp.load(path, allow_pickle=False) as z:
        return {"jax_key": onp.asarray(z["jax_key"], onp.uint32),
                "numpy": (str(z["np_kind"]), onp.asarray(z["np_keys"]),
                          int(z["np_pos"]), int(z["np_has_gauss"]),
                          float(z["np_cached"]))}
