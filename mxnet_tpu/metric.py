"""Evaluation metrics (python/mxnet/metric.py:490).

Same EvalMetric hierarchy and ``create``/registry contract as the reference;
math runs on host numpy after a device sync, exactly like ``update_metric``'s
``asnumpy`` in the reference loop (executor_group.py:510).
"""
from __future__ import annotations

import math

import numpy

from .base import string_types

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy", "Loss",
           "Torch", "Caffe", "CustomMetric", "np", "create"]


def check_label_shapes(labels, preds, shape=0):
    if shape == 0:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(label_shape, pred_shape))


class EvalMetric(object):
    """Base class for evaluation metrics."""

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self.reset()

    def update(self, label, pred):
        raise NotImplementedError()

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num

    def get(self):
        if self.num is None:
            if self.num_inst == 0:
                return (self.name, float("nan"))
            return (self.name, self.sum_metric / self.num_inst)
        names = ["%s_%d" % (self.name, i) for i in range(self.num)]
        values = [x / y if y != 0 else float("nan")
                  for x, y in zip(self.sum_metric, self.num_inst)]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics as one (metric.py CompositeEvalMetric)."""

    def __init__(self, metrics=None, **kwargs):
        super().__init__("composite", **kwargs)
        self.metrics = metrics if metrics is not None else []

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names = []
        results = []
        for metric in self.metrics:
            result = metric.get()
            names.append(result[0])
            results.append(result[1])
        return (names, results)


class Accuracy(EvalMetric):
    """Classification accuracy; ``pred_index`` scores one output of a
    multi-output (Grouped) symbol — e.g. ``Accuracy(pred_index=0)`` for
    a (softmax, aux_loss) group where only output 0 has a label."""

    def __init__(self, pred_index=None):
        super().__init__("accuracy")
        self.pred_index = pred_index

    def update(self, labels, preds):
        if self.pred_index is not None:
            preds = preds[self.pred_index:self.pred_index + 1]
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            p = pred_label.asnumpy()
            # reference: argmax over the CHANNEL axis (axis 1) whenever
            # shapes differ (metric.py Accuracy / ndarray argmax_channel);
            # for the common (N, C) case that equals argmax(-1), and for
            # multi_output softmax (N, C, H, W) it yields per-pixel labels
            if p.shape != tuple(label.shape) and p.ndim > 1:
                p = numpy.argmax(p, axis=1)
            p = p.astype("int32").reshape(-1)
            l = label.asnumpy().astype("int32").reshape(-1)
            check_label_shapes(l, p)
            self.sum_metric += (p.flat == l.flat).sum()
            self.num_inst += len(p.flat)


class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1):
        super().__init__("top_k_accuracy")
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred_label in zip(labels, preds):
            assert len(pred_label.shape) <= 2, "Predictions should be no more than 2 dims"
            p = numpy.argsort(pred_label.asnumpy().astype("float32"), axis=1)
            l = label.asnumpy().astype("int32")
            check_label_shapes(l, p)
            num_samples = p.shape[0]
            num_dims = len(p.shape)
            if num_dims == 1:
                self.sum_metric += (p.flat == l.flat).sum()
            elif num_dims == 2:
                num_classes = p.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (p[:, num_classes - 1 - j].flat ==
                                        l.flat).sum()
            self.num_inst += num_samples


class F1(EvalMetric):
    """Binary-classification F1 (metric.py F1)."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = pred.asnumpy()
            label = label.asnumpy().astype("int32")
            pred_label = numpy.argmax(pred, axis=1)
            check_label_shapes(label, pred)
            if len(numpy.unique(label)) > 2:
                raise ValueError("F1 currently only supports binary classification.")
            true_positives = false_positives = false_negatives = 0.0
            for y_pred, y_true in zip(pred_label, label):
                if y_pred == 1 and y_true == 1:
                    true_positives += 1.0
                elif y_pred == 1 and y_true == 0:
                    false_positives += 1.0
                elif y_pred == 0 and y_true == 1:
                    false_negatives += 1.0
            precision = true_positives / (true_positives + false_positives) \
                if true_positives + false_positives > 0 else 0.0
            recall = true_positives / (true_positives + false_negatives) \
                if true_positives + false_negatives > 0 else 0.0
            f1_score = 2 * precision * recall / (precision + recall) \
                if precision + recall > 0 else 0.0
            self.sum_metric += f1_score
            self.num_inst += 1


class Perplexity(EvalMetric):
    """exp(avg NLL); ignore_label masks padding (metric.py Perplexity)."""

    def __init__(self, ignore_label, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            probs = pred.asnumpy()
            lab = label.asnumpy().astype("int32").reshape(-1)
            probs = probs.reshape(-1, probs.shape[-1])
            picked = probs[numpy.arange(lab.shape[0]), lab]
            if self.ignore_label is not None:
                ignore = (lab == self.ignore_label)
                picked = numpy.where(ignore, 1.0, picked)
                num -= int(ignore.sum())
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, picked)))
            num += lab.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


class MAE(EvalMetric):
    def __init__(self):
        super().__init__("mae")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            # normalize BOTH to (N, -1): a 1-D pred against an (N,1) label
            # would otherwise broadcast to an (N,N) difference matrix
            label = label.reshape(label.shape[0], -1)
            pred = pred.reshape(pred.shape[0], -1)
            self.sum_metric += numpy.abs(label - pred).mean()
            self.num_inst += 1


class MSE(EvalMetric):
    def __init__(self):
        super().__init__("mse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            # normalize BOTH to (N, -1): a 1-D pred against an (N,1) label
            # would otherwise broadcast to an (N,N) difference matrix
            label = label.reshape(label.shape[0], -1)
            pred = pred.reshape(pred.shape[0], -1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


class RMSE(EvalMetric):
    def __init__(self):
        super().__init__("rmse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            # normalize BOTH to (N, -1): a 1-D pred against an (N,1) label
            # would otherwise broadcast to an (N,N) difference matrix
            label = label.reshape(label.shape[0], -1)
            pred = pred.reshape(pred.shape[0], -1)
            self.sum_metric += numpy.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = label.asnumpy()
            pred = pred.asnumpy()
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


class Loss(EvalMetric):
    """Mean of the raw outputs (for MakeLoss heads)."""

    def __init__(self):
        super().__init__("loss")

    def update(self, _, preds):
        for pred in preds:
            self.sum_metric += numpy.sum(pred.asnumpy())
            self.num_inst += pred.size


class Torch(Loss):
    def __init__(self, name="torch"):
        super(Loss, self).__init__(name)


class Caffe(Torch):
    def __init__(self):
        super().__init__("caffe")


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = label.asnumpy()
            pred = pred.asnumpy()
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy eval function as a metric (metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, **kwargs):
    """Create metric from name / callable / list (metric.create)."""
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(child)
        return composite
    metrics = {
        "acc": Accuracy, "accuracy": Accuracy, "ce": CrossEntropy,
        "f1": F1, "mae": MAE, "mse": MSE, "rmse": RMSE,
        "top_k_accuracy": TopKAccuracy, "perplexity": Perplexity,
        "loss": Loss,
    }
    try:
        return metrics[metric.lower()](**kwargs)
    except Exception:
        raise ValueError("Metric must be either callable or in {}".format(
            sorted(metrics)))
