"""Evaluation metrics (reference surface: python/mxnet/metric.py:490).

Same ``EvalMetric`` hierarchy, registry and ``create`` contract as the
reference, but the bodies are TPU-first redesigns rather than ports:

* host ``update`` paths are vectorized numpy (no per-sample Python loops);
* every decomposable builtin also publishes a jax-traceable *fused
  statistic* (:meth:`EvalMetric.fused_stat`) so the mesh Module path can
  accumulate ``(sum, count)`` on device **inside** the fused train step.
  On this transport a scalar device->host readback costs ~100ms
  (docs/architecture/note_measurement.md), so the reference's
  per-batch ``asnumpy`` metric feed (executor_group.py:510) would
  collapse ``fit`` throughput ~25x; the fused tally is drained with a
  single readback only when ``get()`` is called (epoch end / Speedometer
  tick). Host and device paths are pinned equal by
  tests/test_device_metric.py.

Subclass contract (kept from the reference): ``self.sum_metric`` /
``self.num_inst`` accumulators, list-valued when ``num`` is given.
"""
from __future__ import annotations

import math

import numpy

from .base import string_types  # noqa: F401  (re-exported for parity)

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy", "Loss",
           "Torch", "Caffe", "CustomMetric", "np", "create"]


def _as_np(x):
    """NDArray / device array / array-like -> host numpy array."""
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return numpy.asarray(x)


def check_label_shapes(labels, preds, shape=0):
    """Raise when the label / prediction structure disagrees."""
    got = (labels.shape, preds.shape) if shape else (len(labels), len(preds))
    if got[0] != got[1]:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(*got))


class EvalMetric(object):
    """Base class for evaluation metrics.

    Tracks a running ``sum_metric / num_inst`` ratio (list-valued when
    ``num`` outputs are scored separately). A metric may additionally be
    bound to a device-side tally by the fused Module path; the tally is
    folded into the host accumulators lazily, on the first ``get()``.
    """

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self._dev_read = None   # () -> numpy (n_slots, 2) device tally
        self._dev_zero = None   # () -> None, resets the device tally
        self.reset()

    # -- accumulation ---------------------------------------------------
    def update(self, label, pred):
        raise NotImplementedError()

    def reset(self):
        many = self.num is not None
        self.sum_metric = [0.0] * self.num if many else 0.0
        self.num_inst = [0] * self.num if many else 0
        if self._dev_zero is not None:
            self._dev_zero()

    # -- reporting ------------------------------------------------------
    def get(self):
        self._drain_device()
        if self.num is None:
            if not self.num_inst:
                return (self.name, float("nan"))
            return (self.name, self.sum_metric / self.num_inst)
        values = [s / n if n else float("nan")
                  for s, n in zip(self.sum_metric, self.num_inst)]
        return (["%s_%d" % (self.name, i) for i in range(self.num)], values)

    def get_name_value(self):
        names, values = self.get()
        names = names if isinstance(names, list) else [names]
        values = values if isinstance(values, list) else [values]
        return list(zip(names, values))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    # -- fused-step bridge ----------------------------------------------
    def fused_stat(self):
        """Device-side statistic for the fused train step, or ``None``.

        When not ``None``: a callable ``stat(jnp, labels, preds) ->
        (sum, count)`` pair of scalars, traceable under ``jax.jit`` and
        numerically equal to what ``update`` would add to
        ``sum_metric`` / ``num_inst`` for the same batch. Metrics whose
        accumulation is not a plain pair-sum (e.g. :class:`CustomMetric`)
        return ``None`` and keep the host path.
        """
        return None

    def _leaf_stats(self):
        """Flat list of per-row stat callables (None entries = host-only)."""
        return [self.fused_stat()]

    def _bind_device_tally(self, reader, zeroer):
        """Attach a device tally (called by the fused Module path)."""
        self._dev_read = reader
        self._dev_zero = zeroer

    def _unbind_device_tally(self):
        self._dev_read = self._dev_zero = None

    def _drain_device(self):
        """Fold the device tally into the host accumulators (one readback)."""
        if self._dev_read is None:
            return
        tally = numpy.asarray(self._dev_read())
        self._dev_zero()
        self._fold_tally(tally)

    def _fold_tally(self, tally):
        self.sum_metric += float(tally[0, 0])
        self.num_inst += int(round(float(tally[0, 1])))

    def _n_slots(self):
        """Rows this metric occupies in a shared device tally."""
        return 1


class CompositeEvalMetric(EvalMetric):
    """Manage several metrics as one (reference CompositeEvalMetric)."""

    def __init__(self, metrics=None, **kwargs):
        super().__init__("composite", **kwargs)
        self.metrics = [] if metrics is None else metrics

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for child in self.metrics:
            child.update(labels, preds)

    def reset(self):
        for child in getattr(self, "metrics", []):
            child.reset()
        if getattr(self, "_dev_zero", None) is not None:
            self._dev_zero()

    def get(self):
        self._drain_device()
        parts = [child.get() for child in self.metrics]
        return ([p[0] for p in parts], [p[1] for p in parts])

    def _leaf_stats(self):
        flat = []
        for child in self.metrics:
            flat.extend(child._leaf_stats())
        return flat

    def fused_stat(self):
        # flattened leaf rows so nested composites line up with the
        # recursive _fold_tally / _n_slots row layout; returns a LIST of
        # per-leaf (sum, count) pairs
        stats = self._leaf_stats()
        if not stats or any(s is None for s in stats):
            return None

        def stat(jnp, labels, preds):
            return [s(jnp, labels, preds) for s in stats]

        stat.n_slots = len(stats)
        return stat

    def _fold_tally(self, tally):
        row = 0
        for child in self.metrics:
            n = child._n_slots()
            child._fold_tally(tally[row:row + n])
            row += n

    def _n_slots(self):
        return sum(child._n_slots() for child in self.metrics)


def _decide_labels(scores, label_shape):
    """Reference rule (metric.py Accuracy / ndarray argmax_channel): when
    prediction and label shapes differ, class scores live on axis 1."""
    if scores.ndim > 1 and scores.shape != tuple(label_shape):
        return scores.argmax(axis=1)
    return scores


class Accuracy(EvalMetric):
    """Classification accuracy; ``pred_index`` scores one output of a
    multi-output (Grouped) symbol — e.g. ``Accuracy(pred_index=0)`` for a
    (softmax, aux_loss) group where only output 0 has a label."""

    def __init__(self, pred_index=None):
        super().__init__("accuracy")
        self.pred_index = pred_index

    def _select(self, preds):
        if self.pred_index is None:
            return preds
        return preds[self.pred_index:self.pred_index + 1]

    def update(self, labels, preds):
        preds = self._select(preds)
        check_label_shapes(labels, preds)
        for lab, out in zip(labels, preds):
            decided = _decide_labels(_as_np(out), tuple(lab.shape))
            got = decided.astype("int64").ravel()
            want = _as_np(lab).astype("int64").ravel()
            check_label_shapes(want, got)
            self.sum_metric += int((got == want).sum())
            self.num_inst += want.size

    def fused_stat(self):
        select = self._select

        def stat(jnp, labels, preds):
            hits = jnp.float32(0.0)
            seen = 0
            for lab, out in zip(labels, select(preds)):
                decided = out.argmax(axis=1) \
                    if out.ndim > 1 and out.shape != lab.shape else out
                eq = decided.astype(jnp.int32).ravel() == \
                    lab.astype(jnp.int32).ravel()
                hits = hits + eq.sum().astype(jnp.float32)
                seen += eq.size
            return hits, jnp.float32(seen)

        return stat


class TopKAccuracy(EvalMetric):
    """Fraction of samples whose label lands in the top-k scores.

    Host path selects the k-set with ``argpartition`` (O(C) per row vs the
    reference's full sort); tie-breaking at the k-boundary is unspecified,
    as in the reference.
    """

    def __init__(self, top_k=1):
        super().__init__("top_k_accuracy")
        self.top_k = top_k
        assert self.top_k > 1, "Please use Accuracy if top_k is no more than 1"
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for lab, out in zip(labels, preds):
            scores = _as_np(out).astype("float32")
            want = _as_np(lab).astype("int64").ravel()
            if scores.ndim == 1:
                hits = int((scores.astype("int64") == want).sum())
            else:
                assert scores.ndim == 2, \
                    "predictions must be at most 2-dimensional"
                k = min(self.top_k, scores.shape[1])
                kset = numpy.argpartition(scores, -k, axis=1)[:, -k:]
                hits = int((kset == want[:, None]).any(axis=1).sum())
            self.sum_metric += hits
            self.num_inst += want.size

    def fused_stat(self):
        top_k = self.top_k

        def stat(jnp, labels, preds):
            import jax.lax as lax
            hits = jnp.float32(0.0)
            seen = 0
            for lab, out in zip(labels, preds):
                want = lab.astype(jnp.int32).ravel()
                if out.ndim == 1:
                    eq = out.astype(jnp.int32) == want
                    hits = hits + eq.sum().astype(jnp.float32)
                else:
                    k = min(top_k, out.shape[1])
                    _, kset = lax.top_k(out.astype(jnp.float32), k)
                    inset = (kset == want[:, None]).any(axis=1)
                    hits = hits + inset.sum().astype(jnp.float32)
                seen += want.size
            return hits, jnp.float32(seen)

        return stat


class F1(EvalMetric):
    """Binary-classification F1, averaged per batch (reference F1)."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for lab, out in zip(labels, preds):
            scores = _as_np(out)
            want = _as_np(lab).astype("int64").ravel()
            check_label_shapes(want, scores)
            if numpy.unique(want).size > 2:
                raise ValueError(
                    "F1 currently only supports binary classification.")
            got = scores.argmax(axis=1)
            tp = int(((got == 1) & (want == 1)).sum())
            fp = int(((got == 1) & (want == 0)).sum())
            fn = int(((got == 0) & (want == 1)).sum())
            precision = tp / (tp + fp) if tp + fp else 0.0
            recall = tp / (tp + fn) if tp + fn else 0.0
            both = precision + recall
            self.sum_metric += 2.0 * precision * recall / both if both else 0.0
            self.num_inst += 1


class Perplexity(EvalMetric):
    """exp(mean negative log-likelihood); ``ignore_label`` masks padding."""

    def __init__(self, ignore_label, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        nll, count = 0.0, 0
        for lab, out in zip(labels, preds):
            probs = _as_np(out)
            probs = probs.reshape(-1, probs.shape[-1])
            ids = _as_np(lab).astype("int64").ravel()
            chosen = probs[numpy.arange(ids.size), ids]
            keep = numpy.ones(ids.size, bool) if self.ignore_label is None \
                else ids != self.ignore_label
            nll -= float(numpy.log(numpy.maximum(chosen, 1e-10))[keep].sum())
            count += int(keep.sum())
        self.sum_metric += nll
        self.num_inst += count

    def get(self):
        self._drain_device()
        if not self.num_inst:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))

    def fused_stat(self):
        ignore = self.ignore_label

        def stat(jnp, labels, preds):
            nll = jnp.float32(0.0)
            count = jnp.float32(0.0)
            for lab, out in zip(labels, preds):
                probs = out.reshape(-1, out.shape[-1]).astype(jnp.float32)
                ids = lab.astype(jnp.int32).ravel()
                chosen = jnp.take_along_axis(
                    probs, ids[:, None], axis=1)[:, 0]
                logp = jnp.log(jnp.maximum(chosen, 1e-10))
                if ignore is None:
                    nll = nll - logp.sum()
                    count = count + jnp.float32(ids.size)
                else:
                    keep = (ids != ignore).astype(jnp.float32)
                    nll = nll - (logp * keep).sum()
                    count = count + keep.sum()
            return nll, count

        return stat


class _BatchScore(EvalMetric):
    """Regression-style metrics: one score per (label, pred) pair."""

    def _flat_pair(self, lab, out):
        want, got = _as_np(lab), _as_np(out)
        return (want.reshape(want.shape[0], -1),
                got.reshape(got.shape[0], -1))

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for lab, out in zip(labels, preds):
            want, got = self._flat_pair(lab, out)
            self.sum_metric += float(self._score(numpy, want, got))
            self.num_inst += 1

    def fused_stat(self):
        score = self._score

        def stat(jnp, labels, preds):
            total = jnp.float32(0.0)
            for lab, out in zip(labels, preds):
                want = lab.reshape(lab.shape[0], -1).astype(jnp.float32)
                got = out.reshape(out.shape[0], -1).astype(jnp.float32)
                total = total + score(jnp, want, got)
            return total, jnp.float32(len(preds))

        return stat


class MAE(_BatchScore):
    def __init__(self):
        super().__init__("mae")

    @staticmethod
    def _score(xp, want, got):
        return xp.abs(want - got).mean()


class MSE(_BatchScore):
    def __init__(self):
        super().__init__("mse")

    @staticmethod
    def _score(xp, want, got):
        return ((want - got) ** 2).mean()


class RMSE(_BatchScore):
    def __init__(self):
        super().__init__("rmse")

    @staticmethod
    def _score(xp, want, got):
        return xp.sqrt(((want - got) ** 2).mean())


class CrossEntropy(EvalMetric):
    """Mean -log p(label) over samples; ``pred`` rows are probabilities."""

    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for lab, out in zip(labels, preds):
            probs = _as_np(out)
            ids = _as_np(lab).ravel().astype("int64")
            assert ids.size == probs.shape[0]
            chosen = probs[numpy.arange(ids.size), ids]
            self.sum_metric += float(-numpy.log(chosen + self.eps).sum())
            self.num_inst += ids.size

    def fused_stat(self):
        eps = self.eps

        def stat(jnp, labels, preds):
            total = jnp.float32(0.0)
            seen = 0
            for lab, out in zip(labels, preds):
                ids = lab.astype(jnp.int32).ravel()
                chosen = jnp.take_along_axis(
                    out.astype(jnp.float32), ids[:, None], axis=1)[:, 0]
                total = total - jnp.log(chosen + eps).sum()
                seen += ids.size
            return total, jnp.float32(seen)

        return stat


class Loss(EvalMetric):
    """Mean of the raw outputs (for MakeLoss heads)."""

    def __init__(self):
        super().__init__("loss")

    def update(self, _, preds):
        for out in preds:
            self.sum_metric += float(_as_np(out).sum())
            self.num_inst += out.size

    def fused_stat(self):
        def stat(jnp, labels, preds):
            total = jnp.float32(0.0)
            seen = 0
            for out in preds:
                total = total + out.astype(jnp.float32).sum()
                seen += out.size
            return total, jnp.float32(seen)

        return stat


class Torch(Loss):
    def __init__(self, name="torch"):
        super(Loss, self).__init__(name)


class Caffe(Torch):
    def __init__(self):
        super().__init__("caffe")


class CustomMetric(EvalMetric):
    """Host-only metric from a user ``feval(label, pred)`` callable."""

    def __init__(self, feval, name=None, allow_extra_outputs=False):
        if name is None:
            name = feval.__name__
            if "<" in name:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for out, lab in zip(preds, labels):
            got = self._feval(_as_np(lab), _as_np(out))
            if isinstance(got, tuple):
                part_sum, part_n = got
                self.sum_metric += part_sum
                self.num_inst += part_n
            else:
                self.sum_metric += got
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy eval function as a metric (reference ``metric.np``)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


_REGISTRY = {
    "acc": Accuracy, "accuracy": Accuracy, "ce": CrossEntropy,
    "f1": F1, "mae": MAE, "mse": MSE, "rmse": RMSE,
    "top_k_accuracy": TopKAccuracy, "perplexity": Perplexity,
    "loss": Loss,
}


def create(metric, **kwargs):
    """Create a metric from a name / callable / list (``metric.create``)."""
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(child)
        return composite
    try:
        return _REGISTRY[metric.lower()](**kwargs)
    except Exception:
        raise ValueError("Metric must be either callable or in {}".format(
            sorted(_REGISTRY)))
