"""KVStore server-role entry (python/mxnet/kvstore_server.py:58).

The reference dispatches on DMLC_ROLE: "server"/"scheduler" processes run
the ps-lite loop, "worker" returns to user code. The TPU-native stack has no
server processes — every process is a worker participating in XLA
collectives — so server/scheduler roles become no-op participants kept only
so reference launch scripts (tools/launch.py -s N) still work: they join
coordination and exit cleanly at shutdown.
"""
from __future__ import annotations

import logging
import os
import sys

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer(object):
    """Compatibility shim for the server role."""

    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.init_logging()

    def init_logging(self):
        verbose = int(os.getenv("MXNET_KVSTORE_DEBUG", "0"))
        if verbose > 0:
            logging.basicConfig(level=logging.DEBUG)

    def run(self):
        logging.info("kvstore server role is a no-op under XLA collectives; "
                     "idling until workers finish")
        # Workers synchronize via jax.distributed; nothing to serve.


def _init_kvstore_server_module():
    """Called on import like the reference: if DMLC_ROLE is server or
    scheduler, run the (no-op) server loop then exit."""
    role = os.getenv("DMLC_ROLE", "worker")
    if role in ("server", "scheduler"):
        server = KVStoreServer(None)
        server.run()
        sys.exit(0)


_init_kvstore_server_module()
