#!/usr/bin/env python3
"""Kill a distributed training job on every host of a hostfile.

Counterpart of the reference's tools/kill-mxnet.py: for each host in the
hostfile (one ``host[:port]`` per line) ssh in and kill all of ``user``'s
processes whose command line matches ``prog``, then do the same locally.

Usage: kill-mxnet.py <hostfile> <user> <prog>
"""
import os
import subprocess
import sys


def _kill_cmd(user, prog):
    # pgrep -f matches full command lines; exclude whatever shell/python
    # is running this very command (its argv also contains the pattern)
    import shlex
    q = shlex.quote(prog)
    return ("for p in $(pgrep -u %s -f %s); do "
            "[ \"$p\" != \"$$\" ] && [ \"$p\" != \"$PPID\" ] && "
            "kill -9 \"$p\"; done; true" % (shlex.quote(user), q))


def main():
    if len(sys.argv) != 4:
        sys.stderr.write("usage: %s <hostfile> <user> <prog>\n" % sys.argv[0])
        return 1
    hostfile, user, prog = sys.argv[1:4]
    cmd = _kill_cmd(user, prog)
    print(cmd)

    procs = []
    with open(hostfile) as f:
        for line in f:
            host = line.strip()
            if not host or host.startswith("#"):
                continue
            host = host.split(":")[0]
            print("killing on %s" % host)
            try:
                procs.append(subprocess.Popen(
                    ["ssh", "-o", "StrictHostKeyChecking=no", host, cmd],
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
            except FileNotFoundError:
                sys.stderr.write("ssh not available; skipping %s\n" % host)
    for p in procs:
        p.wait()
    os.system(cmd)
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
